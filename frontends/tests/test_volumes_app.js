/* Tests for frontends/volumes/app.js: list rendering, viewer launch,
 * delete guard, and the details drawer (overview + events, pods, YAML) —
 * reference surface: VWA Angular pages + cypress
 * (components/crud-web-apps/volumes/frontend/). */
(function () {
  "use strict";
  const H = (typeof TpuKFHarness !== "undefined")
    ? TpuKFHarness : window.TpuKFHarness;
  const SRC = (typeof TpuKFSources !== "undefined")
    ? TpuKFSources : window.TpuKFSources;
  const { makeWorld, runSource, makeFetch, drain, test, assert } = H;

  const PVCS = { pvcs: [{
    name: "vol1", namespace: "u1",
    status: { phase: "ready", message: "Bound" },
    capacity: "5Gi", modes: ["ReadWriteOnce"], class: "standard",
    notebooks: ["nb1"],
    viewer: { status: "ready", url: "/pvcviewer/u1/vol1/" },
  }, {
    name: "vol2", namespace: "u1",
    status: { phase: "waiting", message: "Provisioning Volume..." },
    capacity: "1Gi", modes: ["ReadWriteMany"], class: null,
    notebooks: [],
    viewer: { status: "uninitialized", url: null },
  }] };

  const EVENTS = { events: [{
    type: "Normal", reason: "ProvisioningSucceeded",
    message: "provisioned ok", lastTimestamp: "2026-07-30T00:00:00Z",
  }] };

  const PODS = { pods: [{
    metadata: { name: "nb1-0" },
    status: { phase: "Running" },
    spec: { volumes: [
      { name: "data", persistentVolumeClaim: { claimName: "vol1" } },
    ] },
  }] };

  const RAW = { pvc: {
    apiVersion: "v1", kind: "PersistentVolumeClaim",
    metadata: { name: "vol1", namespace: "u1" },
    spec: { accessModes: ["ReadWriteOnce"] },
  } };

  function routes(extra) {
    return Object.assign({
      "GET api/namespaces/u1/pvcs": PVCS,
      "GET api/namespaces/u1/pvcs/vol1/events": EVENTS,
      "GET api/namespaces/u1/pvcs/vol1/pods": PODS,
      "GET api/namespaces/u1/pvcs/vol1": RAW,
    }, extra || {});
  }

  function app(fetchStub) {
    const world = makeWorld({ fetch: fetchStub, search: "?ns=u1" });
    const { document } = world;
    const main = document.createElement("div");
    main.id = "main";
    const nsSlot = document.createElement("div");
    nsSlot.id = "ns-slot";
    const newBtn = document.createElement("button");
    newBtn.id = "new-btn";
    document.body.append(main, nsSlot, newBtn);
    runSource(world, SRC.tpukf, "tpukf.js");
    runSource(world, SRC.volumes, "volumes/app.js");
    return world;
  }

  test("volumes list renders status, usage and viewer state", async () => {
    const world = app(makeFetch(routes()));
    await drain();
    const main = world.document.getElementById("main");
    assert(main.textContent.includes("vol1"));
    assert(main.textContent.includes("5Gi"));
    assert(main.textContent.includes("nb1"), "used-by notebooks shown");
    assert(main.textContent.includes("Browse"),
      "ready viewer offers Browse");
    assert(main.textContent.includes("Launch browser"),
      "uninitialized viewer offers Launch");
  });

  test("volume details overview shows events and viewer URL", async () => {
    const world = app(makeFetch(routes()));
    await drain();
    world.location.hash = "#/details/vol1";
    await drain();
    const main = world.document.getElementById("main");
    assert(main.textContent.includes("u1/vol1"), "title");
    assert(main.textContent.includes("ProvisioningSucceeded"),
      "events table populated");
    assert(main.textContent.includes("/pvcviewer/u1/vol1/"),
      "viewer URL surfaced");
    assert(main.textContent.includes("ReadWriteOnce"));
  });

  test("volume details pods tab lists mounting pods", async () => {
    const world = app(makeFetch(routes()));
    await drain();
    world.location.hash = "#/details/vol1";
    await drain();
    const main = world.document.getElementById("main");
    const podsBtn = Array.from(main.querySelectorAll("button")).find(
      (b) => b.textContent === "Pods");
    assert(podsBtn, "Pods tab exists");
    podsBtn.click();
    await drain();
    assert(main.textContent.includes("nb1-0"), "mounting pod listed");
    assert(main.textContent.includes("Running"));
  });

  test("volume details YAML tab renders the raw object", async () => {
    const world = app(makeFetch(routes()));
    await drain();
    world.location.hash = "#/details/vol1";
    await drain();
    const main = world.document.getElementById("main");
    Array.from(main.querySelectorAll("button")).find(
      (b) => b.textContent === "YAML").click();
    await drain();
    assert(main.textContent.includes("PersistentVolumeClaim"),
      "kind in YAML view");
  });

  test("back link returns to the list", async () => {
    const world = app(makeFetch(routes()));
    await drain();
    world.location.hash = "#/details/vol1";
    await drain();
    const main = world.document.getElementById("main");
    Array.from(main.querySelectorAll("button")).find(
      (b) => b.textContent === "Back").click();
    await drain();
    assert(world.location.hash === "#/");
    assert(main.textContent.includes("vol2"), "list restored");
  });
})();
