/* Tests for frontends/jupyter/app.js: router, list rendering + actions,
 * and the spawner form — including data volumes (new + existing-PVC),
 * affinity/toleration groups, environment variables, TPU picker and
 * PodDefault configurations (reference: jupyter frontend form-new
 * sections + cypress form-page.cy.ts). */
(function () {
  "use strict";
  const H = (typeof TpuKFHarness !== "undefined")
    ? TpuKFHarness : window.TpuKFHarness;
  const SRC = (typeof TpuKFSources !== "undefined")
    ? TpuKFSources : window.TpuKFSources;
  const { makeWorld, runSource, makeFetch, drain, test, assert } = H;

  const CONFIG = {
    config: {
      image: { value: "img-b", options: ["img-a", "img-b"] },
      cpu: { value: "0.5" },
      memory: { value: "1Gi" },
      tpu: { generations: [
        { key: "v5e", uiName: "TPU v5e", topologies: ["2x2", "2x4", "4x4"] },
        { key: "v4", uiName: "TPU v4", topologies: ["2x2x2"] },
      ] },
      affinityConfig: { value: "none", options: [
        { configKey: "tpu-pool", displayName: "TPU pool" },
      ] },
      tolerationGroup: { value: "none", options: [
        { groupKey: "preemptible", displayName: "Preemptible" },
      ] },
    },
  };

  const NB_LIST = { notebooks: [{
    name: "nb1", serverType: "jupyter", shortImage: "img-a",
    cpu: "0.5", memory: "1Gi", tpu: { generation: "v5e", topology: "2x4" },
    status: { phase: "ready", message: "Running" },
  }, {
    name: "nb2", serverType: "jupyter", shortImage: "img-b",
    cpu: "1", memory: "2Gi", tpu: null,
    status: { phase: "stopped", message: "" },
  }] };

  function routes(extra) {
    return Object.assign({
      "GET api/config": CONFIG,
      "GET api/namespaces/u1/notebooks": NB_LIST,
      "GET api/namespaces/u1/poddefaults": { poddefaults: [
        { label: "multislice-dcn", desc: "Join a multi-slice job" },
        { label: "jax-cache", desc: "Persistent JAX compile cache" },
      ] },
      "GET api/namespaces/u1/pvcs": { pvcs: [
        { name: "datasets" }, { name: "models" },
      ] },
    }, extra || {});
  }

  function app(fetchStub) {
    const world = makeWorld({ fetch: fetchStub, search: "?ns=u1" });
    const { document } = world;
    const main = document.createElement("div");
    main.id = "main";
    const nsSlot = document.createElement("div");
    nsSlot.id = "ns-slot";
    const newBtn = document.createElement("button");
    newBtn.id = "new-btn";
    document.body.append(main, nsSlot, newBtn);
    runSource(world, SRC.tpukf, "tpukf.js");
    runSource(world, SRC.jupyter, "jupyter/app.js");
    return world;
  }

  test("list view renders notebooks with status and TPU labels",
    async () => {
      const fetchStub = makeFetch(routes());
      const world = app(fetchStub);
      await drain();
      const main = world.document.getElementById("main");
      assert(main.textContent.includes("nb1"));
      assert(main.textContent.includes("v5e 2x4"));
      assert(main.textContent.includes("—"), "no-TPU shows a dash");
      const stopBtns = main.querySelectorAll("button")
        .filter((b) => b.textContent === "Stop");
      const startBtns = main.querySelectorAll("button")
        .filter((b) => b.textContent === "Start");
      assert.equal(stopBtns.length, 1, "ready row offers Stop");
      assert.equal(startBtns.length, 1, "stopped row offers Start");
    });

  test("stop button PATCHes stopped:true and resets the poller",
    async () => {
      const fetchStub = makeFetch(routes({
        "PATCH api/namespaces/u1/notebooks/nb1": { ok: 1 },
      }));
      const world = app(fetchStub);
      await drain();
      const main = world.document.getElementById("main");
      main.querySelectorAll("button")
        .filter((b) => b.textContent === "Stop")[0].click();
      await drain();
      const patch = fetchStub.calls.find((c) => c.method === "PATCH");
      assert(patch, "PATCH sent");
      assert.deepEqual(patch.body, { stopped: true });
    });

  test("delete asks for confirmation before DELETE", async () => {
    const fetchStub = makeFetch(routes({
      "DELETE api/namespaces/u1/notebooks/nb1": { ok: 1 },
    }));
    const world = app(fetchStub);
    await drain();
    const main = world.document.getElementById("main");
    main.querySelectorAll("button.danger")[0].click();
    await drain();
    assert(!fetchStub.calls.some((c) => c.method === "DELETE"),
      "no DELETE before the dialog is answered");
    const dlg = world.document.querySelectorAll("dialog")[0];
    assert(dlg, "confirm dialog shown");
    dlg.querySelectorAll("button.danger")[0].click();
    await drain();
    assert(fetchStub.calls.some((c) => c.method === "DELETE" &&
      c.path === "api/namespaces/u1/notebooks/nb1"));
  });

  test("form submits every section: volumes, affinity, tolerations, env, " +
       "TPU, configurations", async () => {
    const fetchStub = makeFetch(routes({
      "POST api/namespaces/u1/notebooks": { ok: 1 },
    }));
    const world = app(fetchStub);
    await drain();
    world.location.hash = "#/new";
    await drain();
    const main = world.document.getElementById("main");
    assert(main.textContent.includes("New notebook in u1"));

    // name + image
    const inputs = main.querySelectorAll("input");
    const name = inputs.find((i) =>
      i.getAttribute("placeholder") === "my-notebook");
    name.value = "test-nb";

    // TPU picker: generation enables topologies
    const selects = main.querySelectorAll("select");
    const tpuGen = selects.find((s) =>
      s.children.some((o) => o.value === "v5e"));
    tpuGen.value = "v5e";
    tpuGen.dispatchEvent(new world.Event("change"));
    const tpuTopo = selects[selects.indexOf(tpuGen) + 1];
    assert(!tpuTopo.disabled, "topology enabled after picking a generation");
    assert.deepEqual(tpuTopo.children.map((o) => o.value),
      ["2x2", "2x4", "4x4"]);
    tpuTopo.value = "4x4";

    // data volumes: one new, one existing
    const addVol = main.querySelectorAll("button")
      .filter((b) => b.textContent === "+ add volume")[0];
    addVol.click();
    addVol.click();
    const volRows = main.querySelectorAll(".vol-row");
    assert.equal(volRows.length, 2);
    volRows[0].querySelector(".vol-mount").value = "/data";
    volRows[0].querySelector(".vol-size").value = "20Gi";
    const type1 = volRows[1].querySelector(".vol-type");
    type1.value = "existing";
    type1.dispatchEvent(new world.Event("change"));
    const pick = volRows[1].querySelector(".pvc-pick");
    assert.deepEqual(pick.children.map((o) => o.value),
      ["datasets", "models"], "existing PVCs listed from the API");
    pick.value = "datasets";
    volRows[1].querySelector(".vol-mount").value = "/datasets";

    // affinity + tolerations from config options
    const affinity = main.querySelectorAll("select.affinity")[0];
    assert.deepEqual(affinity.children.map((o) => o.value),
      ["none", "tpu-pool"]);
    affinity.value = "tpu-pool";
    const tol = main.querySelectorAll("select.tolerations")[0];
    tol.value = "preemptible";

    // environment variables
    const addEnv = main.querySelectorAll("button")
      .filter((b) => b.textContent === "+ add variable")[0];
    addEnv.click();
    addEnv.click();
    const envRows = main.querySelectorAll(".env-row");
    envRows[0].querySelector(".env-key").value = "JAX_CACHE";
    envRows[0].querySelector(".env-value").value = "/cache";
    envRows[1].querySelector(".env-key").value = "  ";  // blank: dropped

    // configurations (PodDefault labels)
    const chips = main.querySelectorAll("label.chip input");
    assert.equal(chips.length, 2, "poddefaults listed");
    chips[0].checked = true;

    main.querySelectorAll("button.primary")
      .filter((b) => b.textContent === "Launch")[0].click();
    await drain();

    const post = fetchStub.calls.find((c) => c.method === "POST");
    assert(post, "POST sent");
    const body = post.body;
    assert.equal(body.name, "test-nb");
    assert.deepEqual(body.tpu, { generation: "v5e", topology: "4x4" });
    assert.equal(body.affinityConfig, "tpu-pool");
    assert.equal(body.tolerationGroup, "preemptible");
    assert.deepEqual(body.environment, { JAX_CACHE: "/cache" });
    assert.deepEqual(body.configurations, ["multislice-dcn"]);
    assert.equal(body.datavols.length, 2);
    assert.equal(body.datavols[0].mount, "/data");
    assert.equal(
      body.datavols[0].newPvc.spec.resources.requests.storage, "20Gi");
    assert.deepEqual(body.datavols[1],
      { mount: "/datasets", existingSource: "datasets" });
    assert.equal(body.workspace.mount, "/home/jovyan");
    assert.equal(world.location.hash, "#/", "returns to the list on success");
  });

  test("form without TPU or extras posts a minimal body", async () => {
    const fetchStub = makeFetch(routes({
      "POST api/namespaces/u1/notebooks": { ok: 1 },
    }));
    const world = app(fetchStub);
    await drain();
    world.location.hash = "#/new";
    await drain();
    const main = world.document.getElementById("main");
    main.querySelectorAll("input")
      .find((i) => i.getAttribute("placeholder") === "my-notebook")
      .value = "cpu-nb";
    main.querySelectorAll("button.primary")
      .filter((b) => b.textContent === "Launch")[0].click();
    await drain();
    const body = fetchStub.calls.find((c) => c.method === "POST").body;
    assert.equal(body.tpu, undefined);
    assert.equal(body.affinityConfig, undefined);
    assert.equal(body.tolerationGroup, undefined);
    assert.equal(body.datavols, undefined, "empty sections are omitted");
    assert.equal(body.environment, undefined);
  });

  test("readOnly config sections render disabled and stay out of the " +
       "POST body", async () => {
    const roConfig = JSON.parse(JSON.stringify(CONFIG));
    roConfig.config.cpu.readOnly = true;
    roConfig.config.dataVolumes = { value: [], readOnly: true };
    roConfig.config.environment = { value: {}, readOnly: true };
    roConfig.config.shm = { value: true, readOnly: true };
    const fetchStub = makeFetch(routes({
      "GET api/config": roConfig,
      "POST api/namespaces/u1/notebooks": { ok: 1 },
    }));
    const world = app(fetchStub);
    await drain();
    world.location.hash = "#/new";
    await drain();
    const main = world.document.getElementById("main");
    assert(main.textContent.includes("fixed by your administrator"));
    assert.equal(main.querySelectorAll("button")
      .filter((b) => b.textContent === "+ add volume").length, 0,
      "readOnly data volumes offer no add button");
    main.querySelectorAll("input")
      .find((i) => i.getAttribute("placeholder") === "my-notebook")
      .value = "ro-nb";
    main.querySelectorAll("button.primary")
      .filter((b) => b.textContent === "Launch")[0].click();
    await drain();
    const body = fetchStub.calls.find((c) => c.method === "POST").body;
    // readOnly keys absent: their presence would 400 in the backend
    assert.equal(body.cpu, undefined);
    assert.equal(body.shm, undefined);
    assert.equal(body.datavols, undefined);
    assert.equal(body.environment, undefined);
    assert.equal(body.memory, "1Gi", "writable keys still sent");
  });

  test("a failing launch keeps the form and re-enables submit",
    async () => {
      const fetchStub = makeFetch(routes({
        "POST api/namespaces/u1/notebooks":
          { __status: 400, log: "name taken" },
      }));
      const world = app(fetchStub);
      await drain();
      world.location.hash = "#/new";
      await drain();
      const main = world.document.getElementById("main");
      const launch = main.querySelectorAll("button.primary")
        .filter((b) => b.textContent === "Launch")[0];
      launch.click();
      await drain();
      assert.equal(world.location.hash, "#/new", "stays on the form");
      assert.equal(launch.disabled, false, "submit re-enabled for retry");
      const bar = world.document.querySelectorAll(".snackbar")[0];
      assert(bar && bar.textContent.includes("name taken"));
    });

  test("details YAML tab edits the CR and PUTs the whole object",
    async () => {
      const nbObj = {
        metadata: { name: "nb1", namespace: "u1" },
        spec: { tpu: { generation: "v5e", topology: "2x4" } },
        status: { conditions: [] },
      };
      const fetchStub = makeFetch(routes({
        "GET api/namespaces/u1/notebooks/nb1": {
          notebook: nbObj,
          summary: { status: { phase: "ready", message: "Running" } },
          events: [],
        },
        "PUT api/namespaces/u1/notebooks/nb1": { ok: 1 },
      }));
      const world = app(fetchStub);
      await drain();
      world.location.hash = "#/details/nb1";
      await drain();
      const main = world.document.getElementById("main");
      const yamlBtn = main.querySelectorAll("button")
        .filter((b) => b.textContent === "YAML")[0];
      yamlBtn.click();
      await drain();
      main.querySelectorAll("button.edit-yaml")[0].click();
      await drain();
      const area = main.querySelectorAll("textarea.yaml-editor")[0];
      assert(area, "editor textarea rendered");
      area.value = area.value.replace("topology: 2x4", "topology: 4x4");
      main.querySelectorAll("button.primary")
        .filter((b) => b.textContent === "Save")[0].click();
      await drain();
      const put = fetchStub.calls.find((c) => c.method === "PUT");
      assert(put, "PUT sent");
      assert.equal(put.body.spec.tpu.topology, "4x4");
      assert.equal(put.body.metadata.name, "nb1");
    });

  test("list API errors render in the card and the poller backs off",
    async () => {
      const fetchStub = makeFetch({
        "GET api/namespaces/u1/notebooks":
          { __status: 403, log: "no access" },
      });
      const world = app(fetchStub);
      await drain();
      const main = world.document.getElementById("main");
      assert(main.textContent.includes("no access"));
      assert.deepEqual(world.timers.pending(), [6000],
        "3s base doubled after the failure");
    });
})();
