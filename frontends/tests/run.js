#!/usr/bin/env node
/* Node entry point for the frontend unit tests (CI: unit_tests.yaml).
 * Usage: node frontends/tests/run.js
 */
"use strict";

const fs = require("fs");
const path = require("path");

const harness = require("./harness.js");

const ROOT = path.resolve(__dirname, "..");
const SOURCES = {
  tpukf: fs.readFileSync(path.join(ROOT, "common", "tpukf.js"), "utf8"),
  jupyter: fs.readFileSync(path.join(ROOT, "jupyter", "app.js"), "utf8"),
  volumes: fs.readFileSync(path.join(ROOT, "volumes", "app.js"), "utf8"),
  tensorboards: fs.readFileSync(
    path.join(ROOT, "tensorboards", "app.js"), "utf8"),
  dashboard: fs.readFileSync(
    path.join(ROOT, "dashboard", "app.js"), "utf8"),
};

global.TpuKFHarness = harness;
global.TpuKFSources = SOURCES;

require("./test_tpukf.js");
require("./test_jupyter_app.js");
require("./test_volumes_app.js");
require("./test_tensorboards_app.js");

harness.runAll((line) => console.log(line)).then((failed) => {
  process.exit(failed ? 1 : 0);
});
