/* Unit tests for frontends/common/tpukf.js (the kubeflow-common-lib
 * analog): poller backoff/reset/staleness, toYaml quoting, table render,
 * namespace persistence, api() CSRF echo, status widgets. */
(function () {
  "use strict";
  const H = (typeof TpuKFHarness !== "undefined")
    ? TpuKFHarness : window.TpuKFHarness;
  const SRC = (typeof TpuKFSources !== "undefined")
    ? TpuKFSources : window.TpuKFSources;
  const { makeWorld, runSource, makeFetch, drain, test, assert } = H;

  function lib(opts) {
    const world = makeWorld(opts);
    runSource(world, SRC.tpukf, "tpukf.js");
    return world;
  }

  test("toYaml quotes ambiguous scalars and leaves plain ones bare",
    () => {
      const { TpuKF } = lib();
      assert.equal(TpuKF.toYaml("plain-value", 0), "plain-value");
      assert.equal(TpuKF.toYaml("a/b.c:d", 0), "a/b.c:d");
      // strings that would re-parse as bool/int/float must be quoted
      assert.equal(TpuKF.toYaml("true", 0), '"true"');
      assert.equal(TpuKF.toYaml("on", 0), '"on"');
      assert.equal(TpuKF.toYaml("123", 0), '"123"');
      assert.equal(TpuKF.toYaml("1.5e3", 0), '"1.5e3"');
      assert.equal(TpuKF.toYaml("null", 0), '"null"');
      // YAML 1.1 sexagesimal + hex int forms (kubectl parses 1.1)
      assert.equal(TpuKF.toYaml("1:30", 0), '"1:30"');
      assert.equal(TpuKF.toYaml("0x1A", 0), '"0x1A"');
      assert.equal(TpuKF.toYaml("10:99", 0), "10:99",
        "99 is not a valid sexagesimal digit pair: stays bare");
      // while real booleans/numbers stay bare
      assert.equal(TpuKF.toYaml(true, 0), "true");
      assert.equal(TpuKF.toYaml(42, 0), "42");
      assert.equal(TpuKF.toYaml("has spaces", 0), '"has spaces"');
    });

  test("toYaml renders nested objects and lists", () => {
    const { TpuKF } = lib();
    const out = TpuKF.toYaml({
      metadata: { name: "nb", labels: { app: "x" } },
      list: ["a", "b"],
      empty: [],
    }, 0);
    assert(out.includes("metadata:\n  name: nb"), out);
    assert(out.includes("labels:\n    app: x"), out);
    assert(out.includes("list:\n  - a\n  - b"), out);
    assert(out.includes("empty: []"), out);
  });

  test("fromYaml round-trips everything toYaml emits", () => {
    const { TpuKF } = lib();
    const obj = {
      apiVersion: "tpukf.dev/v1beta1",
      kind: "Notebook",
      metadata: {
        name: "nb", namespace: "u1",
        labels: { "app.kubernetes.io/name": "nb", ver: "123" },
        annotations: { note: "has spaces", flag: "true" },
      },
      spec: {
        tpu: { generation: "v5e", topology: "2x4", slices: 2 },
        template: { spec: { containers: [
          { name: "nb", image: "ghcr.io/x:y",
            env: [{ name: "A", value: "1" }] },
        ], tolerations: [] } },
      },
      count: 4, ratio: 0.5, on: true, off: false, nothing: null,
      emptyMap: {}, emptyList: [],
      // empty containers as LIST ITEMS must emit inline ("- {}" / "- []"):
      // the block form placed the literal at column 0, which fromYaml
      // rejected — a CR with e.g. an empty securityContext entry broke Save
      listOfEmpties: [{}, [], { full: 1 }, "s"],
    };
    const round = TpuKF.fromYaml(TpuKF.toYaml(obj, 0));
    assert.deepEqual(round, JSON.parse(JSON.stringify(obj)));
  });

  test("fromYaml parses canonical k8s inline list-item maps", () => {
    // users type '- key: value' style in the editor even though toYaml
    // emits the dash on its own line — both forms must parse identically
    const { TpuKF } = lib();
    const text = [
      "tolerations:",
      "  - key: tpu",
      "    operator: Exists",
      "  - key: spot",
      '    value: "true"',
      "env:",
      "  - name: FOO",
      "    valueFrom:",
      "      fieldRef:",
      "        fieldPath: metadata.name",
      "images:",
      "  - ghcr.io/x:y",
    ].join("\n");
    assert.deepEqual(TpuKF.fromYaml(text), {
      tolerations: [
        { key: "tpu", operator: "Exists" },
        { key: "spot", value: "true" },
      ],
      env: [{ name: "FOO", valueFrom: {
        fieldRef: { fieldPath: "metadata.name" } } }],
      images: ["ghcr.io/x:y"],
    }, "colon-no-space stays a scalar; colon-space opens a map");
  });

  test("fromYaml rejects garbage instead of guessing", () => {
    const { TpuKF } = lib();
    let err = null;
    try { TpuKF.fromYaml("a: 1\n}{nonsense"); } catch (e) { err = e; }
    assert(err && err.message.includes("unparseable"), err);
    assert.equal(TpuKF.fromYaml(""), null);
  });

  test("yamlEditor saves the parsed object and surfaces parse errors",
    async () => {
      const world = lib();
      const saved = [];
      const ed = world.TpuKF.yamlEditor(
        { metadata: { name: "nb" } }, async (o) => { saved.push(o); });
      const area = ed.area;
      assert(area.value.includes("name: nb"));
      const saveBtn = ed.node.querySelectorAll("button.primary")[0];
      area.value = "metadata:\n  }{broken";
      saveBtn.click();
      await drain();
      assert.equal(saved.length, 0, "broken YAML must not save");
      assert(ed.node.textContent.includes("unparseable"));
      assert.equal(saveBtn.disabled, false);
      area.value = "metadata:\n  name: nb2\nspec:\n  tpu:\n    chips: 4";
      saveBtn.click();
      await drain();
      assert.deepEqual(saved[0],
        { metadata: { name: "nb2" }, spec: { tpu: { chips: 4 } } });
    });

  test("poller backs off exponentially on failure and resets on success",
    async () => {
      const world = lib();
      const { TpuKF } = world;
      let fail = true;
      let calls = 0;
      TpuKF.poller(async () => {
        calls++;
        if (fail) throw new Error("boom");
      }, 1000);
      await drain();
      assert.equal(calls, 1, "first tick runs immediately");
      assert.deepEqual(world.timers.pending(), [2000],
        "failure doubles the base delay");
      await world.timers.fire();
      assert.deepEqual(world.timers.pending(), [4000], "keeps doubling");
      await world.timers.fire();
      assert.deepEqual(world.timers.pending(), [8000]);
      fail = false;
      await world.timers.fire();
      assert.deepEqual(world.timers.pending(), [1000],
        "success resets to base");
      assert.equal(calls, 4);
    });

  test("poller backoff is capped at 30s", async () => {
    const world = lib();
    world.TpuKF.poller(async () => { throw new Error("x"); }, 20000);
    await drain();
    assert.deepEqual(world.timers.pending(), [30000]);
    await world.timers.fire();
    assert.deepEqual(world.timers.pending(), [30000], "stays capped");
  });

  test("poller reset() bumps the generation: stale in-flight runs don't " +
       "reschedule", async () => {
    const world = lib();
    let release;
    const gate = new Promise((r) => { release = r; });
    let calls = 0;
    const p = world.TpuKF.poller(async () => { calls++; await gate; }, 1000);
    await drain(2);
    assert.equal(calls, 1);
    p.reset();           // while call 1 is still in flight
    release();
    await drain();
    // exactly ONE chain must be live: the reset's (call 2 ran), and the
    // stale run must not have scheduled a competing timer
    assert.equal(calls, 2, "reset chain ran");
    assert.equal(world.timers.pending().length, 1,
      "stale chain must not reschedule");
    p.stop();
    assert.equal(world.timers.pending().length, 0, "stop clears the timer");
  });

  test("api() echoes the CSRF cookie on mutating methods only",
    async () => {
      const fetchStub = makeFetch({
        "GET api/x": { ok: 1 },
        "POST api/x": { ok: 1 },
      });
      const world = lib({ fetch: fetchStub });
      world.document.cookie = "other=1; XSRF-TOKEN=tok-123";
      await world.TpuKF.api("GET", "api/x");
      await world.TpuKF.api("POST", "api/x", { a: 1 });
      assert.equal(fetchStub.calls[0].headers["X-XSRF-TOKEN"], undefined,
        "GET must not send the token");
      assert.equal(fetchStub.calls[1].headers["X-XSRF-TOKEN"], "tok-123");
      assert.deepEqual(fetchStub.calls[1].body, { a: 1 });
    });

  test("api() surfaces the backend log message on error", async () => {
    const world = lib({ fetch: makeFetch({
      "GET api/bad": { __status: 403, log: "no access to namespace" },
    }) });
    let err = null;
    try { await world.TpuKF.api("GET", "api/bad"); }
    catch (e) { err = e; }
    assert(err && err.message === "no access to namespace", err);
  });

  test("currentNamespace prefers ?ns= and persists it", () => {
    const world = lib({ search: "?ns=team-a" });
    assert.equal(world.TpuKF.currentNamespace(), "team-a");
    assert.equal(world.localStorage.getItem("tpukf.namespace"), "team-a");
    // a later visit without the param falls back to the stored value
    world.location.search = "";
    assert.equal(world.TpuKF.currentNamespace(), "team-a");
  });

  test("resourceTable renders rows, node cells and the empty state", () => {
    const world = lib();
    const { TpuKF } = world;
    const cols = [
      { title: "Name", render: (x) => x.name },
      { title: "Status", render: (x) => TpuKF.statusIcon("ready", "ok") },
    ];
    const table = TpuKF.resourceTable(cols, [{ name: "a" }, { name: "b" }]);
    const headers = table.querySelectorAll("th").map((h) => h.textContent);
    assert.deepEqual(headers, ["Name", "Status"]);
    const tbody = table.children.find((c) => c.tagName === "TBODY");
    assert.equal(tbody.children.length, 2, "two data rows");
    assert.equal(tbody.children[0].children[0].textContent, "a");
    assert(tbody.children[0].children[1].querySelector(".status"),
      "node-valued cells are appended, not stringified");
    const empty = TpuKF.resourceTable(cols, [], "nothing!");
    assert(empty.textContent.includes("nothing!"));
  });

  test("statusIcon carries phase class and tooltip", () => {
    const { TpuKF } = lib();
    const icon = TpuKF.statusIcon("warning", "slice incomplete");
    assert(icon.classList.contains("status"));
    assert(icon.classList.contains("warning"));
    assert.equal(icon.title, "slice incomplete");
    assert(icon.textContent.includes("warning"));
  });

  test("conditionsTable and eventsTable render their columns", () => {
    const { TpuKF } = lib();
    const ct = TpuKF.conditionsTable([
      { type: "GangScheduled", status: "True", reason: "AllHostsPresent",
        message: "4/4", lastTransitionTime: "t1" },
    ]);
    assert(ct.textContent.includes("GangScheduled"));
    assert(ct.textContent.includes("AllHostsPresent"));
    const et = TpuKF.eventsTable([
      { type: "Warning", reason: "SliceIncomplete", message: "3/4",
        count: 7, lastTimestamp: "t2" },
    ]);
    assert(et.textContent.includes("SliceIncomplete"));
    assert(et.textContent.includes("7"));
  });

  test("snackbar reuses one element and flags errors", () => {
    const world = lib({ realTimers: true });
    world.TpuKF.snackbar("hello");
    world.TpuKF.snackbar("bad thing", true);
    const bars = world.document.querySelectorAll(".snackbar");
    assert.equal(bars.length, 1, "one snackbar element");
    assert.equal(bars[0].textContent, "bad thing");
    assert(bars[0].classList.contains("error"));
    assert(bars[0].classList.contains("show"));
  });

  test("confirmDialog resolves true on Delete, false on Cancel",
    async () => {
      const world = lib();
      const p1 = world.TpuKF.confirmDialog("Delete x", "really?");
      const dlg1 = world.document.querySelectorAll("dialog")[0];
      assert(dlg1.open, "dialog opened");
      assert(dlg1.textContent.includes("really?"));
      dlg1.querySelectorAll("button.danger")[0].click();
      assert.equal(await p1, true);
      const p2 = world.TpuKF.confirmDialog("Delete y", "?");
      const dlg2 = world.document.querySelectorAll("dialog")[0];
      dlg2.querySelectorAll("button")[0].click();  // Cancel
      assert.equal(await p2, false);
      assert.equal(world.document.querySelectorAll("dialog").length, 0,
        "dialogs remove themselves");
    });
})();
