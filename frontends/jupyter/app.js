/* Jupyter web app — notebook list + spawner form.
 * API surface: webapps/jupyter/app.py (GET/POST/PATCH/DELETE notebooks,
 * GET config/pvcs/poddefaults). Form field names match form.py setters.
 */
(function () {
  "use strict";
  const { api, currentNamespace, namespaceInput, snackbar, confirmDialog,
          statusIcon, resourceTable, poller, el,
          conditionsTable, eventsTable, objectView, logsViewer,
          yamlEditor } = window.TpuKF;

  const main = document.getElementById("main");
  let ns = currentNamespace();
  let listPoller = null;

  document.getElementById("ns-slot").appendChild(
    namespaceInput((value) => { ns = value; route(); })
  );
  document.getElementById("new-btn").addEventListener("click", () => {
    location.hash = "#/new";
  });

  // -------------------------------------------------------------- list
  function tpuLabel(tpu) {
    if (!tpu) return "—";
    return `${tpu.generation}${tpu.topology ? " " + tpu.topology : ""}` +
      (tpu.chips ? ` (${tpu.chips} chips)` : "");
  }

  async function renderList() {
    if (listPoller) listPoller.stop();
    if (!ns) {
      main.replaceChildren(el("div", { class: "card muted" },
        "Set a namespace to list notebooks."));
      return;
    }
    const container = el("div", { class: "card" });
    main.replaceChildren(container);

    async function refresh() {
      let data;
      try {
        data = await api("GET", `api/namespaces/${ns}/notebooks`);
      } catch (e) {
        // surface the failure in the card (403 vs empty list must be
        // distinguishable); rethrow so the poller backs off
        container.replaceChildren(el("div", { class: "muted" }, e.message));
        throw e;
      }
      const columns = [
        { title: "Status", render: (nb) => {
            const icon = statusIcon(nb.status.phase, nb.status.message);
            if (nb.status.phase === "parked") {
              // checkpoint-parked (scale-to-zero), not a dead stop:
              // state is committed and Start restores it — say so in
              // the row, not just the tooltip
              icon.appendChild(document.createTextNode(
                " (resume on open)"));
            }
            if (nb.queue && nb.queue.position) {
              // tpusched parking: show where the notebook stands instead
              // of an unexplained Pending (reason lives in the tooltip)
              icon.appendChild(document.createTextNode(
                ` (queued ${nb.queue.position}/${nb.queue.of})`));
            }
            return icon;
          } },
        { title: "Name", render: (nb) => nb.name },
        { title: "Type", render: (nb) => nb.serverType || "jupyter" },
        { title: "Image", render: (nb) => nb.shortImage },
        { title: "TPU", render: (nb) => tpuLabel(nb.tpu) },
        { title: "CPU", render: (nb) => nb.cpu },
        { title: "Memory", render: (nb) => nb.memory },
        { title: "", render: (nb) => rowActions(nb) },
      ];
      container.replaceChildren(
        resourceTable(columns, data.notebooks, "no notebooks in " + ns)
      );
    }

    function rowActions(nb) {
      const row = el("div", { class: "row" });
      // parked is a stopped state with committed checkpoint state: the
      // same Start action resumes it (the backend stamps the
      // resume-request when it sees the checkpoint annotation)
      const stopped = nb.status.phase === "stopped" ||
        nb.status.phase === "parked";
      row.appendChild(el("button", {
        onclick: async () => {
          try {
            await api("PATCH",
              `api/namespaces/${ns}/notebooks/${nb.name}`,
              { stopped: !stopped });
            snackbar(`${stopped ? "Starting" : "Stopping"} ${nb.name}…`);
            listPoller.reset();
          } catch (e) { snackbar(e.message, true); }
        },
      }, stopped ? "Start" : "Stop"));
      row.appendChild(el("button", {
        onclick: () => {
          window.open(`/notebook/${ns}/${nb.name}/`, "_blank");
        },
      }, "Connect"));
      row.appendChild(el("button", {
        onclick: () => { location.hash = `#/details/${nb.name}`; },
      }, "Details"));
      row.appendChild(el("button", {
        class: "danger",
        onclick: async () => {
          if (!(await confirmDialog("Delete notebook",
              `Delete ${nb.name} and keep its volumes?`))) return;
          try {
            await api("DELETE", `api/namespaces/${ns}/notebooks/${nb.name}`);
            snackbar(`Deleting ${nb.name}…`);
            listPoller.reset();
          } catch (e) { snackbar(e.message, true); }
        },
      }, "Delete"));
      return row;
    }

    listPoller = poller(refresh, 3000);
  }

  // -------------------------------------------------------------- form
  async function renderForm() {
    if (listPoller) listPoller.stop();
    const { config } = await api("GET", "api/config");
    const form = el("div", { class: "card" });

    // readOnly config sections (spawner_ui_config readOnly: true) are
    // admin-fixed: the control renders disabled and the field is OMITTED
    // from the POST body — the backend 400s on any readOnly key present
    // in the request (form.py get_form_value)
    const ro = (key) => !!((config[key] || {}).readOnly);

    const name = el("input", { placeholder: "my-notebook" });
    const image = el("select", {});
    for (const opt of config.image.options) {
      image.appendChild(el("option", { value: opt }, opt));
    }
    image.value = config.image.value;
    image.disabled = ro("image");
    const customImage = el("input",
      { placeholder: "custom image (optional)" });
    customImage.disabled = ro("image");
    const serverType = el("select", {});
    for (const t of ["jupyter", "group-one", "group-two"]) {
      serverType.appendChild(el("option", { value: t }, t));
    }
    const cpu = el("input", { value: config.cpu.value });
    cpu.disabled = ro("cpu");
    const memory = el("input", { value: config.memory.value });
    memory.disabled = ro("memory");

    // TPU picker (replaces the reference's GPU vendor dropdown)
    const tpuGen = el("select", {});
    tpuGen.appendChild(el("option", { value: "none" }, "none (CPU only)"));
    for (const g of config.tpu.generations) {
      tpuGen.appendChild(el("option", { value: g.key }, g.uiName));
    }
    tpuGen.disabled = ro("tpu");
    const tpuTopo = el("select", { disabled: "" });
    tpuGen.addEventListener("change", () => {
      tpuTopo.replaceChildren();
      const gen = config.tpu.generations.find((g) => g.key === tpuGen.value);
      if (!gen) { tpuTopo.disabled = true; return; }
      tpuTopo.disabled = false;
      for (const t of gen.topologies) {
        tpuTopo.appendChild(el("option", { value: t }, t));
      }
    });

    const wsSize = el("input", { value: "10Gi", style: "width:100px" });
    wsSize.disabled = ro("workspaceVolume");
    const shm = el("input", { type: "checkbox", checked: "" });
    shm.disabled = ro("shm");

    // data volumes: new-PVC or existing-PVC attach rows (reference JWA
    // form-data-volumes; backend: form.py volume_requests /
    // app.py existingSource handling)
    const dataVols = el("div", { class: "datavols" });
    let existingPvcs = [];
    if (ns) {
      api("GET", `api/namespaces/${ns}/pvcs`).then(({ pvcs }) => {
        existingPvcs = pvcs.map((p) => p.name);
        for (const sel of dataVols.querySelectorAll("select.pvc-pick")) {
          fillPvcOptions(sel);
        }
      }).catch((e) => snackbar(e.message, true));
    }
    function fillPvcOptions(sel) {
      sel.replaceChildren();
      if (!existingPvcs.length) {
        sel.appendChild(el("option", { value: "" }, "no PVCs found"));
      }
      for (const name of existingPvcs) {
        sel.appendChild(el("option", { value: name }, name));
      }
    }
    function addVolumeRow() {
      const type = el("select", { class: "vol-type" },
        el("option", { value: "new" }, "new volume"),
        el("option", { value: "existing" }, "existing volume"));
      const mount = el("input", {
        class: "vol-mount", value: `/mnt/vol-${dataVols.children.length + 1}`,
      });
      const size = el("input",
        { class: "vol-size", value: "5Gi", style: "width:80px" });
      const pvcPick = el("select",
        { class: "pvc-pick", style: "display:none" });
      fillPvcOptions(pvcPick);
      type.addEventListener("change", () => {
        const existing = type.value === "existing";
        size.style.display = existing ? "none" : "";
        pvcPick.style.display = existing ? "" : "none";
      });
      const remove = el("button", {
        onclick: () => { row.remove(); },
      }, "✕");
      const row = el("div", { class: "row vol-row" },
        type, el("span", { class: "muted" }, "mount"), mount,
        size, pvcPick, remove);
      dataVols.appendChild(row);
    }
    const addVolBtn = ro("dataVolumes")
      ? el("span", { class: "muted" }, "fixed by your administrator")
      : el("button", { onclick: addVolumeRow }, "+ add volume");

    function collectDataVolumes() {
      const vols = [];
      for (const row of dataVols.querySelectorAll(".vol-row")) {
        const type = row.querySelector(".vol-type").value;
        const mount = row.querySelector(".vol-mount").value.trim();
        if (type === "existing") {
          const pvc = row.querySelector(".pvc-pick").value;
          if (pvc) vols.push({ mount, existingSource: pvc });
        } else {
          vols.push({
            mount,
            newPvc: {
              metadata: { name: `{notebook-name}-vol-${vols.length + 1}` },
              spec: {
                resources: { requests: {
                  storage: row.querySelector(".vol-size").value,
                } },
                accessModes: ["ReadWriteOnce"],
              },
            },
          });
        }
      }
      return vols;
    }

    // affinity / tolerations: keyed option groups served by /api/config
    // (reference form-affinity-tolerations; backend form.py:207-224)
    const affinity = el("select", { class: "affinity" });
    affinity.appendChild(el("option", { value: "none" }, "none"));
    for (const opt of (config.affinityConfig || {}).options || []) {
      affinity.appendChild(el("option", { value: opt.configKey },
        opt.displayName || opt.configKey));
    }
    if ((config.affinityConfig || {}).value) {
      affinity.value = config.affinityConfig.value;
    }
    affinity.disabled = ro("affinityConfig");
    const tolerations = el("select", { class: "tolerations" });
    tolerations.appendChild(el("option", { value: "none" }, "none"));
    for (const opt of (config.tolerationGroup || {}).options || []) {
      tolerations.appendChild(el("option", { value: opt.groupKey },
        opt.displayName || opt.groupKey));
    }
    if ((config.tolerationGroup || {}).value) {
      tolerations.value = config.tolerationGroup.value;
    }
    tolerations.disabled = ro("tolerationGroup");

    // environment variables: key/value rows -> body.environment
    // (backend form.py set_environment)
    const envRows = el("div", { class: "env-rows" });
    function addEnvRow() {
      const row = el("div", { class: "row env-row" },
        el("input", { class: "env-key", placeholder: "NAME" }),
        el("input", { class: "env-value", placeholder: "value" }),
        el("button", { onclick: () => { row.remove(); } }, "✕"));
      envRows.appendChild(row);
    }
    const addEnvBtn = ro("environment")
      ? el("span", { class: "muted" }, "fixed by your administrator")
      : el("button", { onclick: addEnvRow }, "+ add variable");

    function collectEnvironment() {
      const env = {};
      for (const row of envRows.querySelectorAll(".env-row")) {
        const k = row.querySelector(".env-key").value.trim();
        if (k) env[k] = row.querySelector(".env-value").value;
      }
      return env;
    }

    // configurations = PodDefault labels (admission webhook matches them)
    const podDefaultsBox = el("div", {}, el("span", { class: "muted" },
      ns ? "loading…" : "set a namespace to list configurations"));
    if (ns) {
      api("GET", `api/namespaces/${ns}/poddefaults`).then(({ poddefaults }) => {
        podDefaultsBox.replaceChildren();
        if (!poddefaults.length) {
          podDefaultsBox.appendChild(
            el("span", { class: "muted" }, "none available"));
        }
        for (const pd of poddefaults) {
          podDefaultsBox.appendChild(el("label", { class: "chip" },
            el("input", { type: "checkbox", "data-label": pd.label }),
            " " + pd.desc));
        }
      }).catch((e) => snackbar(e.message, true));
    }

    const grid = el("div", { class: "form-grid" },
      el("label", {}, "Name"), name,
      el("label", {}, "Image"), image,
      el("label", {}, "Custom image"), customImage,
      el("label", {}, "Server type"), serverType,
      el("label", {}, "CPU"), cpu,
      el("label", {}, "Memory"), memory,
      el("label", {}, "TPU"), el("div", { class: "row" }, tpuGen, tpuTopo),
      el("label", {}, "Workspace size"), wsSize,
      el("label", {}, "Data volumes"), el("div", {}, dataVols, addVolBtn),
      el("label", {}, "Affinity"), affinity,
      el("label", {}, "Tolerations"), tolerations,
      el("label", {}, "Environment"), el("div", {}, envRows, addEnvBtn),
      el("label", {}, "Shared memory"), el("div", {}, shm),
      el("label", {}, "Configurations"), podDefaultsBox,
    );

    const submit = el("button", { class: "primary" }, "Launch");
    submit.addEventListener("click", async () => {
      // omit any readOnly-configured key: the backend takes its value
      // from the config and rejects the key's presence in the body
      const body = {
        name: name.value.trim(),
        serverType: serverType.value,
      };
      if (!ro("image")) {
        body.image = image.value;
        if (customImage.value.trim()) {
          body.customImage = customImage.value.trim();
        }
      }
      if (!ro("cpu")) body.cpu = cpu.value;
      if (!ro("memory")) body.memory = memory.value;
      if (!ro("shm")) body.shm = shm.checked;
      if (!ro("configurations")) {
        body.configurations =
          [...podDefaultsBox.querySelectorAll("input:checked")]
            .map((c) => c.dataset.label).filter(Boolean);
      }
      if (!ro("workspaceVolume")) {
        body.workspace = {
          mount: "/home/jovyan",
          newPvc: {
            metadata: { name: "{notebook-name}-workspace" },
            spec: {
              resources: { requests: { storage: wsSize.value } },
              accessModes: ["ReadWriteOnce"],
            },
          },
        };
      }
      if (!ro("dataVolumes")) {
        const vols = collectDataVolumes();
        if (vols.length) body.datavols = vols;
      }
      if (!ro("environment")) {
        const env = collectEnvironment();
        if (Object.keys(env).length) body.environment = env;
      }
      if (!ro("affinityConfig") && affinity.value !== "none") {
        body.affinityConfig = affinity.value;
      }
      if (!ro("tolerationGroup") && tolerations.value !== "none") {
        body.tolerationGroup = tolerations.value;
      }
      if (!ro("tpu") && tpuGen.value !== "none") {
        body.tpu = { generation: tpuGen.value, topology: tpuTopo.value };
      }
      submit.disabled = true;
      try {
        await api("POST", `api/namespaces/${ns}/notebooks`, body);
        snackbar("Notebook created");
        location.hash = "#/";
      } catch (e) {
        snackbar(e.message, true);
        submit.disabled = false;
      }
    });

    form.append(
      el("h3", { style: "margin-top:0" }, `New notebook in ${ns || "?"}`),
      grid,
      el("div", { class: "row", style: "margin-top:16px" },
        submit,
        el("button", { onclick: () => { location.hash = "#/"; } }, "Cancel")),
    );
    main.replaceChildren(form);
  }

  // ----------------------------------------------------------- details
  // (reference JWA notebook details page: overview/logs/events/yaml —
  // "why is my slice pod Pending/CrashLooping" answered in the UI)
  let detailPollers = [];
  let tabEpoch = 0;  // bumped on every tab switch / route change: async
                     // continuations from a superseded tab must not touch
                     // the pane or the poller list

  function stopDetailPollers() {
    tabEpoch++;
    for (const p of detailPollers) p.stop();
    detailPollers = [];
  }

  async function renderDetails(name) {
    if (listPoller) listPoller.stop();
    stopDetailPollers();
    const card = el("div", { class: "card" });
    const title = el("h3", { style: "margin-top:0" },
      `${ns}/${name}`);
    const tabBar = el("div", { class: "row tabs" });
    const pane = el("div", { class: "tab-pane" });
    card.append(
      el("div", { class: "row", style: "justify-content:space-between" },
        title,
        el("button", { onclick: () => { location.hash = "#/"; } }, "Back")),
      tabBar, pane);
    main.replaceChildren(card);

    async function overviewTab() {
      stopDetailPollers();
      const box = el("div", {});
      pane.replaceChildren(box);
      const p = poller(async () => {
        const data = await api(
          "GET", `api/namespaces/${ns}/notebooks/${name}`);
        const conds = (data.notebook.status || {}).conditions || [];
        box.replaceChildren(
          el("div", { class: "row" },
            statusIcon(data.summary.status.phase,
                       data.summary.status.message),
            el("span", { class: "muted" },
               data.summary.status.message || "")),
          el("h4", {}, "Conditions"), conditionsTable(conds),
          el("h4", {}, "Events"), eventsTable(data.events),
        );
      }, 4000);
      detailPollers.push(p);
    }

    async function logsTab() {
      stopDetailPollers();
      const epoch = tabEpoch;
      pane.replaceChildren(el("span", { class: "muted" }, "loading…"));
      let pods;
      try {
        pods = (await api(
          "GET", `api/namespaces/${ns}/notebooks/${name}/pod`)).pods;
      } catch (e) {
        if (epoch !== tabEpoch) return;
        pane.replaceChildren(el("div", { class: "muted" }, e.message));
        return;
      }
      // the user may have switched tabs while the pod fetch was in
      // flight; a stale continuation must not clobber the active pane
      if (epoch !== tabEpoch) return;
      const podSel = el("select", {});
      for (const p of pods) {
        podSel.appendChild(el("option", { value: p.metadata.name },
          p.metadata.name));
      }
      const holder = el("div", {});
      function showPod() {
        for (const p of detailPollers) p.stop();
        detailPollers = [];
        const viewer = logsViewer(async () => (await api("GET",
          `api/namespaces/${ns}/notebooks/${name}/pod/${podSel.value}/logs`
        )).logs);
        detailPollers.push(viewer.poller);
        holder.replaceChildren(viewer.node);
      }
      podSel.addEventListener("change", showPod);
      pane.replaceChildren(
        el("div", { class: "row" },
          el("span", { class: "muted" }, "host pod"), podSel), holder);
      showPod();
    }

    async function yamlTab() {
      stopDetailPollers();
      const epoch = tabEpoch;
      const data = await api("GET", `api/namespaces/${ns}/notebooks/${name}`);
      if (epoch !== tabEpoch) return;
      function readView(nb) {
        const editBtn = el("button", {
          class: "edit-yaml",
          onclick: () => { editView(nb); },
        }, "Edit");
        pane.replaceChildren(editBtn, objectView(nb));
      }
      function editView(nb) {
        // the in-UI editor (reference ships Monaco for this role): edit
        // the CR as YAML, PUT the whole object back
        const editor = yamlEditor(nb, async (parsed) => {
          await api("PUT",
            `api/namespaces/${ns}/notebooks/${name}`, parsed);
          snackbar("Notebook updated");
          const fresh = await api(
            "GET", `api/namespaces/${ns}/notebooks/${name}`);
          if (epoch !== tabEpoch) return;
          readView(fresh.notebook);
        }, () => { readView(nb); });
        pane.replaceChildren(editor.node);
      }
      readView(data.notebook);
    }

    const tabs = [
      ["Overview", overviewTab], ["Logs", logsTab], ["YAML", yamlTab],
    ];
    for (const [label, fn] of tabs) {
      const btn = el("button", {
        onclick: () => {
          for (const b of tabBar.children) b.classList.remove("primary");
          btn.classList.add("primary");
          fn().catch((e) => snackbar(e.message, true));
        },
      }, label);
      tabBar.appendChild(btn);
    }
    tabBar.children[0].classList.add("primary");
    await overviewTab();
  }

  // ------------------------------------------------------------- router
  function route() {
    stopDetailPollers();
    const details = location.hash.match(/^#\/details\/([^/]+)$/);
    if (location.hash === "#/new") renderForm().catch(
      (e) => snackbar(e.message, true));
    else if (details) renderDetails(
      decodeURIComponent(details[1])).catch(
      (e) => snackbar(e.message, true));
    else renderList().catch((e) => snackbar(e.message, true));
  }
  window.addEventListener("hashchange", route);
  route();
})();
