/* Jupyter web app — notebook list + spawner form.
 * API surface: webapps/jupyter/app.py (GET/POST/PATCH/DELETE notebooks,
 * GET config/pvcs/poddefaults). Form field names match form.py setters.
 */
(function () {
  "use strict";
  const { api, currentNamespace, namespaceInput, snackbar, confirmDialog,
          statusIcon, resourceTable, poller, el } = window.TpuKF;

  const main = document.getElementById("main");
  let ns = currentNamespace();
  let listPoller = null;

  document.getElementById("ns-slot").appendChild(
    namespaceInput((value) => { ns = value; route(); })
  );
  document.getElementById("new-btn").addEventListener("click", () => {
    location.hash = "#/new";
  });

  // -------------------------------------------------------------- list
  function tpuLabel(tpu) {
    if (!tpu) return "—";
    return `${tpu.generation}${tpu.topology ? " " + tpu.topology : ""}` +
      (tpu.chips ? ` (${tpu.chips} chips)` : "");
  }

  async function renderList() {
    if (listPoller) listPoller.stop();
    if (!ns) {
      main.replaceChildren(el("div", { class: "card muted" },
        "Set a namespace to list notebooks."));
      return;
    }
    const container = el("div", { class: "card" });
    main.replaceChildren(container);

    async function refresh() {
      let data;
      try {
        data = await api("GET", `api/namespaces/${ns}/notebooks`);
      } catch (e) {
        // surface the failure in the card (403 vs empty list must be
        // distinguishable); rethrow so the poller backs off
        container.replaceChildren(el("div", { class: "muted" }, e.message));
        throw e;
      }
      const columns = [
        { title: "Status", render: (nb) =>
            statusIcon(nb.status.phase, nb.status.message) },
        { title: "Name", render: (nb) => nb.name },
        { title: "Type", render: (nb) => nb.serverType || "jupyter" },
        { title: "Image", render: (nb) => nb.shortImage },
        { title: "TPU", render: (nb) => tpuLabel(nb.tpu) },
        { title: "CPU", render: (nb) => nb.cpu },
        { title: "Memory", render: (nb) => nb.memory },
        { title: "", render: (nb) => rowActions(nb) },
      ];
      container.replaceChildren(
        resourceTable(columns, data.notebooks, "no notebooks in " + ns)
      );
    }

    function rowActions(nb) {
      const row = el("div", { class: "row" });
      const stopped = nb.status.phase === "stopped";
      row.appendChild(el("button", {
        onclick: async () => {
          try {
            await api("PATCH",
              `api/namespaces/${ns}/notebooks/${nb.name}`,
              { stopped: !stopped });
            snackbar(`${stopped ? "Starting" : "Stopping"} ${nb.name}…`);
            listPoller.reset();
          } catch (e) { snackbar(e.message, true); }
        },
      }, stopped ? "Start" : "Stop"));
      row.appendChild(el("button", {
        onclick: () => {
          window.open(`/notebook/${ns}/${nb.name}/`, "_blank");
        },
      }, "Connect"));
      row.appendChild(el("button", {
        class: "danger",
        onclick: async () => {
          if (!(await confirmDialog("Delete notebook",
              `Delete ${nb.name} and keep its volumes?`))) return;
          try {
            await api("DELETE", `api/namespaces/${ns}/notebooks/${nb.name}`);
            snackbar(`Deleting ${nb.name}…`);
            listPoller.reset();
          } catch (e) { snackbar(e.message, true); }
        },
      }, "Delete"));
      return row;
    }

    listPoller = poller(refresh, 3000);
  }

  // -------------------------------------------------------------- form
  async function renderForm() {
    if (listPoller) listPoller.stop();
    const { config } = await api("GET", "api/config");
    const form = el("div", { class: "card" });

    const name = el("input", { placeholder: "my-notebook" });
    const image = el("select", {});
    for (const opt of config.image.options) {
      image.appendChild(el("option", { value: opt }, opt));
    }
    image.value = config.image.value;
    const customImage = el("input",
      { placeholder: "custom image (optional)" });
    const serverType = el("select", {});
    for (const t of ["jupyter", "group-one", "group-two"]) {
      serverType.appendChild(el("option", { value: t }, t));
    }
    const cpu = el("input", { value: config.cpu.value });
    const memory = el("input", { value: config.memory.value });

    // TPU picker (replaces the reference's GPU vendor dropdown)
    const tpuGen = el("select", {});
    tpuGen.appendChild(el("option", { value: "none" }, "none (CPU only)"));
    for (const g of config.tpu.generations) {
      tpuGen.appendChild(el("option", { value: g.key }, g.uiName));
    }
    const tpuTopo = el("select", { disabled: "" });
    tpuGen.addEventListener("change", () => {
      tpuTopo.replaceChildren();
      const gen = config.tpu.generations.find((g) => g.key === tpuGen.value);
      if (!gen) { tpuTopo.disabled = true; return; }
      tpuTopo.disabled = false;
      for (const t of gen.topologies) {
        tpuTopo.appendChild(el("option", { value: t }, t));
      }
    });

    const wsSize = el("input", { value: "10Gi", style: "width:100px" });
    const shm = el("input", { type: "checkbox", checked: "" });

    // configurations = PodDefault labels (admission webhook matches them)
    const podDefaultsBox = el("div", {}, el("span", { class: "muted" },
      ns ? "loading…" : "set a namespace to list configurations"));
    if (ns) {
      api("GET", `api/namespaces/${ns}/poddefaults`).then(({ poddefaults }) => {
        podDefaultsBox.replaceChildren();
        if (!poddefaults.length) {
          podDefaultsBox.appendChild(
            el("span", { class: "muted" }, "none available"));
        }
        for (const pd of poddefaults) {
          podDefaultsBox.appendChild(el("label", { class: "chip" },
            el("input", { type: "checkbox", "data-label": pd.label }),
            " " + pd.desc));
        }
      }).catch((e) => snackbar(e.message, true));
    }

    const grid = el("div", { class: "form-grid" },
      el("label", {}, "Name"), name,
      el("label", {}, "Image"), image,
      el("label", {}, "Custom image"), customImage,
      el("label", {}, "Server type"), serverType,
      el("label", {}, "CPU"), cpu,
      el("label", {}, "Memory"), memory,
      el("label", {}, "TPU"), el("div", { class: "row" }, tpuGen, tpuTopo),
      el("label", {}, "Workspace size"), wsSize,
      el("label", {}, "Shared memory"), el("div", {}, shm),
      el("label", {}, "Configurations"), podDefaultsBox,
    );

    const submit = el("button", { class: "primary" }, "Launch");
    submit.addEventListener("click", async () => {
      const body = {
        name: name.value.trim(),
        image: image.value,
        customImage: customImage.value.trim() || undefined,
        serverType: serverType.value,
        cpu: cpu.value, memory: memory.value,
        shm: shm.checked,
        configurations: [...podDefaultsBox.querySelectorAll("input:checked")]
          .map((c) => c.dataset.label).filter(Boolean),
        workspace: {
          mount: "/home/jovyan",
          newPvc: {
            metadata: { name: "{notebook-name}-workspace" },
            spec: {
              resources: { requests: { storage: wsSize.value } },
              accessModes: ["ReadWriteOnce"],
            },
          },
        },
      };
      if (tpuGen.value !== "none") {
        body.tpu = { generation: tpuGen.value, topology: tpuTopo.value };
      }
      submit.disabled = true;
      try {
        await api("POST", `api/namespaces/${ns}/notebooks`, body);
        snackbar("Notebook created");
        location.hash = "#/";
      } catch (e) {
        snackbar(e.message, true);
        submit.disabled = false;
      }
    });

    form.append(
      el("h3", { style: "margin-top:0" }, `New notebook in ${ns || "?"}`),
      grid,
      el("div", { class: "row", style: "margin-top:16px" },
        submit,
        el("button", { onclick: () => { location.hash = "#/"; } }, "Cancel")),
    );
    main.replaceChildren(form);
  }

  // ------------------------------------------------------------- router
  function route() {
    if (location.hash === "#/new") renderForm().catch(
      (e) => snackbar(e.message, true));
    else renderList().catch((e) => snackbar(e.message, true));
  }
  window.addEventListener("hashchange", route);
  route();
})();
