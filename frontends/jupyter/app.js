/* Jupyter web app — notebook list + spawner form.
 * API surface: webapps/jupyter/app.py (GET/POST/PATCH/DELETE notebooks,
 * GET config/pvcs/poddefaults). Form field names match form.py setters.
 */
(function () {
  "use strict";
  const { api, currentNamespace, namespaceInput, snackbar, confirmDialog,
          statusIcon, resourceTable, poller, el,
          conditionsTable, eventsTable, objectView, logsViewer } =
    window.TpuKF;

  const main = document.getElementById("main");
  let ns = currentNamespace();
  let listPoller = null;

  document.getElementById("ns-slot").appendChild(
    namespaceInput((value) => { ns = value; route(); })
  );
  document.getElementById("new-btn").addEventListener("click", () => {
    location.hash = "#/new";
  });

  // -------------------------------------------------------------- list
  function tpuLabel(tpu) {
    if (!tpu) return "—";
    return `${tpu.generation}${tpu.topology ? " " + tpu.topology : ""}` +
      (tpu.chips ? ` (${tpu.chips} chips)` : "");
  }

  async function renderList() {
    if (listPoller) listPoller.stop();
    if (!ns) {
      main.replaceChildren(el("div", { class: "card muted" },
        "Set a namespace to list notebooks."));
      return;
    }
    const container = el("div", { class: "card" });
    main.replaceChildren(container);

    async function refresh() {
      let data;
      try {
        data = await api("GET", `api/namespaces/${ns}/notebooks`);
      } catch (e) {
        // surface the failure in the card (403 vs empty list must be
        // distinguishable); rethrow so the poller backs off
        container.replaceChildren(el("div", { class: "muted" }, e.message));
        throw e;
      }
      const columns = [
        { title: "Status", render: (nb) =>
            statusIcon(nb.status.phase, nb.status.message) },
        { title: "Name", render: (nb) => nb.name },
        { title: "Type", render: (nb) => nb.serverType || "jupyter" },
        { title: "Image", render: (nb) => nb.shortImage },
        { title: "TPU", render: (nb) => tpuLabel(nb.tpu) },
        { title: "CPU", render: (nb) => nb.cpu },
        { title: "Memory", render: (nb) => nb.memory },
        { title: "", render: (nb) => rowActions(nb) },
      ];
      container.replaceChildren(
        resourceTable(columns, data.notebooks, "no notebooks in " + ns)
      );
    }

    function rowActions(nb) {
      const row = el("div", { class: "row" });
      const stopped = nb.status.phase === "stopped";
      row.appendChild(el("button", {
        onclick: async () => {
          try {
            await api("PATCH",
              `api/namespaces/${ns}/notebooks/${nb.name}`,
              { stopped: !stopped });
            snackbar(`${stopped ? "Starting" : "Stopping"} ${nb.name}…`);
            listPoller.reset();
          } catch (e) { snackbar(e.message, true); }
        },
      }, stopped ? "Start" : "Stop"));
      row.appendChild(el("button", {
        onclick: () => {
          window.open(`/notebook/${ns}/${nb.name}/`, "_blank");
        },
      }, "Connect"));
      row.appendChild(el("button", {
        onclick: () => { location.hash = `#/details/${nb.name}`; },
      }, "Details"));
      row.appendChild(el("button", {
        class: "danger",
        onclick: async () => {
          if (!(await confirmDialog("Delete notebook",
              `Delete ${nb.name} and keep its volumes?`))) return;
          try {
            await api("DELETE", `api/namespaces/${ns}/notebooks/${nb.name}`);
            snackbar(`Deleting ${nb.name}…`);
            listPoller.reset();
          } catch (e) { snackbar(e.message, true); }
        },
      }, "Delete"));
      return row;
    }

    listPoller = poller(refresh, 3000);
  }

  // -------------------------------------------------------------- form
  async function renderForm() {
    if (listPoller) listPoller.stop();
    const { config } = await api("GET", "api/config");
    const form = el("div", { class: "card" });

    const name = el("input", { placeholder: "my-notebook" });
    const image = el("select", {});
    for (const opt of config.image.options) {
      image.appendChild(el("option", { value: opt }, opt));
    }
    image.value = config.image.value;
    const customImage = el("input",
      { placeholder: "custom image (optional)" });
    const serverType = el("select", {});
    for (const t of ["jupyter", "group-one", "group-two"]) {
      serverType.appendChild(el("option", { value: t }, t));
    }
    const cpu = el("input", { value: config.cpu.value });
    const memory = el("input", { value: config.memory.value });

    // TPU picker (replaces the reference's GPU vendor dropdown)
    const tpuGen = el("select", {});
    tpuGen.appendChild(el("option", { value: "none" }, "none (CPU only)"));
    for (const g of config.tpu.generations) {
      tpuGen.appendChild(el("option", { value: g.key }, g.uiName));
    }
    const tpuTopo = el("select", { disabled: "" });
    tpuGen.addEventListener("change", () => {
      tpuTopo.replaceChildren();
      const gen = config.tpu.generations.find((g) => g.key === tpuGen.value);
      if (!gen) { tpuTopo.disabled = true; return; }
      tpuTopo.disabled = false;
      for (const t of gen.topologies) {
        tpuTopo.appendChild(el("option", { value: t }, t));
      }
    });

    const wsSize = el("input", { value: "10Gi", style: "width:100px" });
    const shm = el("input", { type: "checkbox", checked: "" });

    // configurations = PodDefault labels (admission webhook matches them)
    const podDefaultsBox = el("div", {}, el("span", { class: "muted" },
      ns ? "loading…" : "set a namespace to list configurations"));
    if (ns) {
      api("GET", `api/namespaces/${ns}/poddefaults`).then(({ poddefaults }) => {
        podDefaultsBox.replaceChildren();
        if (!poddefaults.length) {
          podDefaultsBox.appendChild(
            el("span", { class: "muted" }, "none available"));
        }
        for (const pd of poddefaults) {
          podDefaultsBox.appendChild(el("label", { class: "chip" },
            el("input", { type: "checkbox", "data-label": pd.label }),
            " " + pd.desc));
        }
      }).catch((e) => snackbar(e.message, true));
    }

    const grid = el("div", { class: "form-grid" },
      el("label", {}, "Name"), name,
      el("label", {}, "Image"), image,
      el("label", {}, "Custom image"), customImage,
      el("label", {}, "Server type"), serverType,
      el("label", {}, "CPU"), cpu,
      el("label", {}, "Memory"), memory,
      el("label", {}, "TPU"), el("div", { class: "row" }, tpuGen, tpuTopo),
      el("label", {}, "Workspace size"), wsSize,
      el("label", {}, "Shared memory"), el("div", {}, shm),
      el("label", {}, "Configurations"), podDefaultsBox,
    );

    const submit = el("button", { class: "primary" }, "Launch");
    submit.addEventListener("click", async () => {
      const body = {
        name: name.value.trim(),
        image: image.value,
        customImage: customImage.value.trim() || undefined,
        serverType: serverType.value,
        cpu: cpu.value, memory: memory.value,
        shm: shm.checked,
        configurations: [...podDefaultsBox.querySelectorAll("input:checked")]
          .map((c) => c.dataset.label).filter(Boolean),
        workspace: {
          mount: "/home/jovyan",
          newPvc: {
            metadata: { name: "{notebook-name}-workspace" },
            spec: {
              resources: { requests: { storage: wsSize.value } },
              accessModes: ["ReadWriteOnce"],
            },
          },
        },
      };
      if (tpuGen.value !== "none") {
        body.tpu = { generation: tpuGen.value, topology: tpuTopo.value };
      }
      submit.disabled = true;
      try {
        await api("POST", `api/namespaces/${ns}/notebooks`, body);
        snackbar("Notebook created");
        location.hash = "#/";
      } catch (e) {
        snackbar(e.message, true);
        submit.disabled = false;
      }
    });

    form.append(
      el("h3", { style: "margin-top:0" }, `New notebook in ${ns || "?"}`),
      grid,
      el("div", { class: "row", style: "margin-top:16px" },
        submit,
        el("button", { onclick: () => { location.hash = "#/"; } }, "Cancel")),
    );
    main.replaceChildren(form);
  }

  // ----------------------------------------------------------- details
  // (reference JWA notebook details page: overview/logs/events/yaml —
  // "why is my slice pod Pending/CrashLooping" answered in the UI)
  let detailPollers = [];
  let tabEpoch = 0;  // bumped on every tab switch / route change: async
                     // continuations from a superseded tab must not touch
                     // the pane or the poller list

  function stopDetailPollers() {
    tabEpoch++;
    for (const p of detailPollers) p.stop();
    detailPollers = [];
  }

  async function renderDetails(name) {
    if (listPoller) listPoller.stop();
    stopDetailPollers();
    const card = el("div", { class: "card" });
    const title = el("h3", { style: "margin-top:0" },
      `${ns}/${name}`);
    const tabBar = el("div", { class: "row tabs" });
    const pane = el("div", { class: "tab-pane" });
    card.append(
      el("div", { class: "row", style: "justify-content:space-between" },
        title,
        el("button", { onclick: () => { location.hash = "#/"; } }, "Back")),
      tabBar, pane);
    main.replaceChildren(card);

    async function overviewTab() {
      stopDetailPollers();
      const box = el("div", {});
      pane.replaceChildren(box);
      const p = poller(async () => {
        const data = await api(
          "GET", `api/namespaces/${ns}/notebooks/${name}`);
        const conds = (data.notebook.status || {}).conditions || [];
        box.replaceChildren(
          el("div", { class: "row" },
            statusIcon(data.summary.status.phase,
                       data.summary.status.message),
            el("span", { class: "muted" },
               data.summary.status.message || "")),
          el("h4", {}, "Conditions"), conditionsTable(conds),
          el("h4", {}, "Events"), eventsTable(data.events),
        );
      }, 4000);
      detailPollers.push(p);
    }

    async function logsTab() {
      stopDetailPollers();
      const epoch = tabEpoch;
      pane.replaceChildren(el("span", { class: "muted" }, "loading…"));
      let pods;
      try {
        pods = (await api(
          "GET", `api/namespaces/${ns}/notebooks/${name}/pod`)).pods;
      } catch (e) {
        if (epoch !== tabEpoch) return;
        pane.replaceChildren(el("div", { class: "muted" }, e.message));
        return;
      }
      // the user may have switched tabs while the pod fetch was in
      // flight; a stale continuation must not clobber the active pane
      if (epoch !== tabEpoch) return;
      const podSel = el("select", {});
      for (const p of pods) {
        podSel.appendChild(el("option", { value: p.metadata.name },
          p.metadata.name));
      }
      const holder = el("div", {});
      function showPod() {
        for (const p of detailPollers) p.stop();
        detailPollers = [];
        const viewer = logsViewer(async () => (await api("GET",
          `api/namespaces/${ns}/notebooks/${name}/pod/${podSel.value}/logs`
        )).logs);
        detailPollers.push(viewer.poller);
        holder.replaceChildren(viewer.node);
      }
      podSel.addEventListener("change", showPod);
      pane.replaceChildren(
        el("div", { class: "row" },
          el("span", { class: "muted" }, "host pod"), podSel), holder);
      showPod();
    }

    async function yamlTab() {
      stopDetailPollers();
      const epoch = tabEpoch;
      const data = await api("GET", `api/namespaces/${ns}/notebooks/${name}`);
      if (epoch !== tabEpoch) return;
      pane.replaceChildren(objectView(data.notebook));
    }

    const tabs = [
      ["Overview", overviewTab], ["Logs", logsTab], ["YAML", yamlTab],
    ];
    for (const [label, fn] of tabs) {
      const btn = el("button", {
        onclick: () => {
          for (const b of tabBar.children) b.classList.remove("primary");
          btn.classList.add("primary");
          fn().catch((e) => snackbar(e.message, true));
        },
      }, label);
      tabBar.appendChild(btn);
    }
    tabBar.children[0].classList.add("primary");
    await overviewTab();
  }

  // ------------------------------------------------------------- router
  function route() {
    stopDetailPollers();
    const details = location.hash.match(/^#\/details\/([^/]+)$/);
    if (location.hash === "#/new") renderForm().catch(
      (e) => snackbar(e.message, true));
    else if (details) renderDetails(
      decodeURIComponent(details[1])).catch(
      (e) => snackbar(e.message, true));
    else renderList().catch((e) => snackbar(e.message, true));
  }
  window.addEventListener("hashchange", route);
  route();
})();
