# tools/ is a package so `python -m tools.cplint` works from the repo
# root; the individual scripts (bench_gate.py, metrics_lint.py) remain
# directly runnable too.
