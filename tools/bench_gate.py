#!/usr/bin/env python3
"""Perf-regression gate over CONTROLPLANE_BENCH.json records.

Compares a fresh cpbench run against the committed record and fails on:

- churn ``controller_overhead`` p50 regressing more than the tolerance,
- notebook_ready ``create_to_ready`` p95 regressing more than the
  tolerance,
- the cached-read hit rate missing from either scenario's report, or
  below the floor (the delegating read client must keep serving reads
  AND reporting its evidence — a silent fall-back to live reads, e.g. a
  broken ``_informer_for`` counting every read as a miss, would
  otherwise look like a latency mystery and still slip under the
  smoke-vs-full latency headroom),
- ``apiserver_reads_per_reconcile`` missing or above its ceiling — the
  apiserver-side counter a controller-only regression cannot hide from
  behind the bench's own (cache-served) poll traffic,
- chaos invariant legs, for every chaos scenario present in the run:
  ``double_bookings > 0``, ``orphaned_children > 0``, any
  ``invariant_violations``, or missing recovery-time p50/p95 fields —
  surviving the injection without evidence of recovery doesn't count,
- SLO legs (``--slo-report``): every scenario in the run must carry a
  non-empty ``slo`` attainment record (obs/slo.py shape) and every
  objective in it must be met — a missed objective OR an absent
  attainment record fails (absence of evidence isn't attainment),
- profiler legs (``--prof-report``): every scenario must carry an
  ``extra.prof`` record naming its top hot stack, top contended lock
  site, and a non-empty per-client apiserver request split, and the
  run-level ``profiler_overhead`` A/B (CPPROF=0 vs 1 on notebook_ready)
  must exist with p95 ratio ≤ ``--prof-overhead-max`` (default 1.05) —
  a profiler you can't afford to leave on is not continuous profiling,
  and attribution that silently vanished is not attribution,
- store-lock legs (``--store-lock-max-share``, composes with
  ``--prof-report``): each scenario's store-lock wait share (contended
  wait on ``kube/fake.py`` locks over the scenario's wall time — can
  exceed 1.0 with several threads blocked concurrently; the
  pre-refactor fake measured 2.3 on sched_contention) must stay under
  the ceiling, and the fake may not be the top contended lock site
  with a meaningful share — the regression tripwire for the striped
  MVCC FakeKube (docs/fakekube.md): a re-serialized fake would make
  every bench number measure the fake, not the plane.

CI runs the smoke lane against the committed ``--full`` record: smoke is
smaller and faster, so the latency comparison only trips on gross
regressions (a hot loop, a lost cache, a serialized queue) — exactly the
failures a PR lane can catch deterministically on a shared runner. The
record itself is refreshed by a manual ``--full --chaos`` run
(BASELINE.md).

Exit 0 = within tolerance.  Usage:

    python tools/bench_gate.py --baseline CONTROLPLANE_BENCH.json \
        --run bench_out.json [--tolerance 1.2]

    # chaos lane: only the invariant legs, and all four scenarios
    # must be present in the run
    python tools/bench_gate.py --baseline CONTROLPLANE_BENCH.json \
        --run chaos_out.json --chaos-only

    # static-analysis lane: assert BOTH analyzer reports exist and hold
    # zero unsuppressed errors (python -m tools.cplint/jaxlint --json
    # wrote them; one report of each schema is required)
    python tools/bench_gate.py --lint-report cplint_report.json \
        --lint-report jaxlint_report.json
"""

from __future__ import annotations

import argparse
import json
import sys

#: (scenario, phase, percentile) latency gates
GATES = (
    ("churn", "controller_overhead", "p50"),
    ("notebook_ready", "create_to_ready", "p95"),
)
#: scenarios that must report a cached-read hit rate
HIT_RATE_SCENARIOS = ("notebook_ready", "churn")
#: minimum acceptable hit rate in those scenarios — every read on their
#: hot path is cache-servable (measured 1.0 at both --smoke and --full),
#: so anything below ~0.9 means reads are falling through to the
#: apiserver, not ordinary jitter
MIN_HIT_RATE = 0.9
#: ceiling on (GET+LIST)/reconciles. The hit rate alone can be diluted:
#: the bench's own poll loops route through the same shared CachedClient,
#: so a controller-side fall-back to live reads can hide under thousands
#: of poll hits. This counter is apiserver-side (FakeKube per-verb tally)
#: and immune to that — measured ≤1.06 cached (smoke and full), 3.5-7.7
#: with ENGINE_CACHED_READS=0
READS_PER_RECONCILE_MAX = 2.0
#: the chaos family (cpbench/chaos.py): every member present in a run
#: gets the invariant legs; --chaos-only additionally requires all five
CHAOS_SCENARIOS = ("chaos_relist", "chaos_blackout", "chaos_node_death",
                   "chaos_kubelet_stall", "chaos_429_storm",
                   "chaos_park_blackout", "chaos_alert_fidelity")


def chaos_scenarios_in(run: dict) -> list[str]:
    """Chaos scenarios to gate: the canonical family plus ANY
    ``chaos_*``-named scenario the run contains — a new member of the
    family must not ride along un-gated just because this tuple wasn't
    updated."""
    present = {n for n in run.get("scenarios", {}) if
               n.startswith("chaos_")}
    return sorted(set(CHAOS_SCENARIOS) | present)


def chaos_gate(run: dict, require_all: bool = False) -> list[str]:
    """Invariant legs over whichever chaos scenarios the run contains
    (the canonical four required when ``require_all``): zero double
    bookings, zero orphaned children, zero recorded invariant
    violations, and recovery-time p50/p95 actually present — a chaos
    run that can't show WHEN it recovered hasn't shown THAT it
    recovered."""
    failures = []
    scenarios = run.get("scenarios", {})
    for name in chaos_scenarios_in(run):
        s = scenarios.get(name)
        if s is None:
            if require_all:
                failures.append(f"{name}: missing from chaos run")
            continue
        extra = s.get("extra") or {}
        db = extra.get("double_bookings")
        if db is None or db > 0:
            failures.append(
                f"{name}: double_bookings={db} (must be reported and 0)"
            )
        orphans = extra.get("orphaned_children")
        if orphans is None or orphans > 0:
            failures.append(
                f"{name}: orphaned_children={orphans} "
                "(must be reported and 0)"
            )
        violations = extra.get("invariant_violations")
        if violations is None:
            failures.append(f"{name}: invariant_violations not reported")
        elif any(violations.values()):
            failures.append(
                f"{name}: invariant violations {violations}"
            )
        recovery = (extra.get("recovery_ms") or {}).get("all") or {}
        if "p50" not in recovery or "p95" not in recovery:
            failures.append(
                f"{name}: recovery_ms p50/p95 missing — no evidence the "
                "plane recovered from the injection"
            )
    return failures


def slo_gate(run: dict) -> list[str]:
    """--slo-report leg: per-scenario SLO attainment, uniformly. The
    record shape is obs/slo.py report(): {objective: {target_ms,
    objective, n, attainment, burn, met}}."""
    failures = []
    scenarios = run.get("scenarios", {})
    if not scenarios:
        return ["slo: run contains no scenarios"]
    for name in sorted(scenarios):
        slo = scenarios[name].get("slo")
        if not isinstance(slo, dict) or not slo:
            failures.append(
                f"{name}: no SLO attainment record — the scenario ran "
                "without declaring whether the product promise held"
            )
            continue
        for objective in sorted(slo):
            entry = slo[objective]
            if not entry.get("met"):
                failures.append(
                    f"{name}: SLO {objective} missed — attainment "
                    f"{entry.get('attainment')} over n={entry.get('n')} "
                    f"vs objective {entry.get('objective')} at "
                    f"{entry.get('target_ms')} ms (burn "
                    f"{entry.get('burn')})"
                )
    return failures


#: profiler A/B overhead ceiling: notebook_ready create→Ready p95 with
#: the sampler on may cost at most this ratio vs off (ISSUE/acceptance:
#: ≤5 %)
PROF_OVERHEAD_MAX = 1.05


#: creation-site fragment identifying the fake apiserver's own locks
#: (store stripes, family event locks) in lockwatch site labels
STORE_LOCK_SITE = "kube/fake.py"

#: below this store-lock wait share (contended wait on fake locks over
#: scenario wall time), the fake being the nominal "top contended lock"
#: is residual GIL-collision noise, not a serialization point: on a
#: loaded 1-core box EVERY lock's collision count swells (a holder
#: preempted mid-hold costs each waiter 10-20 ms of scheduler slices),
#: and whichever busy lock edges out the others by a few percent reads
#: as "top" — measured post-refactor runs bounce 0.004-0.09 on the
#: fake with the engine's own locks right beside them, vs 2.3-2.9
#: pre-refactor. The top-site leg only convicts above this floor; the
#: share ceiling (--store-lock-max-share) still gates absolutely.
STORE_LOCK_TOP_MIN_SHARE = 0.15


def prof_gate(run: dict, max_overhead: float = PROF_OVERHEAD_MAX,
              store_max_share: float | None = None) -> list[str]:
    """--prof-report leg: per-scenario cpprof attribution, uniformly.
    Record shape is cpbench's ``extra.prof`` (obs/prof.py report +
    lockwatch contention + per-client split) plus the run-level
    ``profiler_overhead`` A/B. With ``store_max_share`` set
    (--store-lock-max-share), additionally fails any scenario whose
    store-lock wait share (contended wait on kube/fake.py locks over
    the scenario's wall time) exceeds the ceiling, or where the fake is
    the top contended lock site with a meaningful share — the striped
    MVCC refactor's regression tripwire: at 10k-CR scale a
    re-serialized fake would be the thing the bench measures, not the
    plane."""
    failures = []
    scenarios = run.get("scenarios", {})
    if not scenarios:
        return ["prof: run contains no scenarios"]
    for name in sorted(scenarios):
        prof = (scenarios[name].get("extra") or {}).get("prof")
        if not isinstance(prof, dict) or not prof:
            failures.append(
                f"{name}: no extra.prof record — was cpbench run with "
                "--profile?"
            )
            continue
        top = prof.get("top_stack")
        if not isinstance(top, str) or not top.strip():
            failures.append(
                f"{name}: extra.prof.top_stack absent/empty — the "
                "sampler recorded nothing for this scenario"
            )
        lock = prof.get("top_contended_lock")
        if not isinstance(lock, str) or not lock.strip():
            failures.append(
                f"{name}: extra.prof.top_contended_lock absent — the "
                "lock-contention feed is dark (lockwatch not installed "
                "before the scenario ran?)"
            )
        by_client = prof.get("by_client")
        if not isinstance(by_client, dict) or not by_client:
            failures.append(
                f"{name}: extra.prof.by_client absent/empty — no "
                "per-client apiserver request split"
            )
        if store_max_share is not None:
            share = prof.get("store_lock_wait_share")
            if not isinstance(share, (int, float)):
                failures.append(
                    f"{name}: extra.prof.store_lock_wait_share absent — "
                    "no store-lock wait-share evidence (cpbench too old "
                    "for --store-lock-max-share?)"
                )
                continue
            if share > store_max_share:
                failures.append(
                    f"{name}: store-lock wait share {share} exceeds "
                    f"{store_max_share} — threads are queueing on the "
                    "fake apiserver's locks again"
                )
            # the top site only convicts alongside a meaningful share:
            # with little or no contention, the ranking falls back to
            # fast-path acquire bookkeeping (or a couple of GIL-slice
            # collision blips) and whoever is busiest — usually the
            # fake — sits on top without serializing anyone
            if isinstance(lock, str) and STORE_LOCK_SITE in lock \
                    and share > STORE_LOCK_TOP_MIN_SHARE:
                failures.append(
                    f"{name}: top contended lock {lock} is the FakeKube "
                    "store again — the apiserver is back to being the "
                    "serialization point the striped-store refactor "
                    "removed"
                )
    overhead = run.get("profiler_overhead")
    if not isinstance(overhead, dict) \
            or not isinstance(overhead.get("ratio"), (int, float)):
        failures.append(
            "profiler_overhead record absent/malformed — no CPPROF=0 "
            "vs 1 A/B evidence in the run"
        )
    else:
        if overhead["ratio"] > max_overhead:
            failures.append(
                f"profiler overhead ratio {overhead['ratio']} exceeds "
                f"{max_overhead} on {overhead.get('scenario')} p95 "
                f"(on={overhead.get('p95_on_ms')} ms, "
                f"off={overhead.get('p95_off_ms')} ms) — sampling is "
                "no longer cheap enough to leave on"
            )
        if overhead.get("runs_ok") is False:
            # a ratio computed over failed runs is garbage evidence —
            # p95s of non-converged notebooks measure the timeout, not
            # the sampler
            failures.append(
                "profiler_overhead A/B runs_ok=false — the overhead "
                "ratio was measured over failed notebook_ready runs"
            )
    return failures


#: --failover leg thresholds. The protected lane "holds" at ≤ this p95
#: ratio vs its no-storm baseline (the acceptance ±20%) OR under the
#: absolute floor — sub-millisecond in-memory ops flap a pure ratio on
#: shared-box scheduler jitter while a REAL squeeze measures ~10x
#: (cpbench/ha.py measures both arms). The storm counts as squeezed only
#: below this fraction of its unthrottled throughput.
APF_PROTECTED_MAX_RATIO = 1.2
APF_PROTECTED_FLOOR_MS = 2.0
APF_STORM_MAX_RATIO = 0.5


def failover_gate(run: dict) -> list[str]:
    """--failover leg over the ha_scale family (cpbench/ha.py):

    - ``ha_failover`` must be present with a failover_ms p95, its
      ``failover`` SLO met, 0 dual reconciles through the handoff and 0
      orphaned keys;
    - ``ha_scale`` (when present) must show 0 dual reconciles / 0
      orphaned keys across every replica arm;
    - ``ha_apf`` must be present with the protected lane holding its
      p95 (ratio ≤ 1.2 vs no-storm baseline, or under the absolute
      floor), the storming client measurably squeezed
      (throughput ratio ≤ 0.5, with > 0 attributed 429s), and zero
      429s on the protected lane."""
    failures = []
    scenarios = run.get("scenarios", {})
    fo = scenarios.get("ha_failover")
    if fo is None:
        failures.append(
            "ha_failover: missing from run — no leader-kill failover "
            "evidence"
        )
    else:
        extra = fo.get("extra") or {}
        failover = extra.get("failover_ms") or {}
        if "p95" not in failover:
            failures.append(
                "ha_failover: failover_ms p95 missing — the kill was "
                "not timed to recovery"
            )
        slo = (fo.get("slo") or {}).get("failover")
        if not isinstance(slo, dict) or not slo.get("met"):
            failures.append(
                "ha_failover: failover SLO missing or not met — "
                f"attainment {None if not isinstance(slo, dict) else slo.get('attainment')}"  # noqa: E501
            )
    for name in ("ha_scale", "ha_failover"):
        s = scenarios.get(name)
        if s is None:
            continue
        extra = s.get("extra") or {}
        dual = extra.get("dual_reconciles")
        if dual is None or dual > 0:
            failures.append(
                f"{name}: dual_reconciles={dual} (must be reported and "
                "0 — two replicas ran the same key concurrently)"
            )
        orphaned = extra.get("orphaned_keys")
        if orphaned is None or orphaned > 0:
            failures.append(
                f"{name}: orphaned_keys={orphaned} (must be reported "
                "and 0 — a handoff may delay a key, never lose it)"
            )
    apf = scenarios.get("ha_apf")
    if apf is None:
        failures.append(
            "ha_apf: missing from run — no priority-and-fairness A/B "
            "evidence"
        )
        return failures
    a = ((apf.get("extra") or {}).get("apf")) or {}
    ratio = a.get("protected_p95_ratio")
    p95 = ((a.get("storm_apf") or {}).get("protected_p95_ms"))
    if not isinstance(ratio, (int, float)):
        failures.append(
            "ha_apf: protected_p95_ratio absent — the protected lane "
            "was never measured against its baseline"
        )
    elif ratio > APF_PROTECTED_MAX_RATIO and not (
            isinstance(p95, (int, float))
            and p95 <= APF_PROTECTED_FLOOR_MS):
        failures.append(
            f"ha_apf: protected lane squeezed — p95 ratio {ratio} vs "
            f"baseline exceeds {APF_PROTECTED_MAX_RATIO} (abs "
            f"{p95} ms above the {APF_PROTECTED_FLOOR_MS} ms floor)"
        )
    storm_ratio = a.get("storm_throughput_ratio")
    if not isinstance(storm_ratio, (int, float)):
        failures.append(
            "ha_apf: storm_throughput_ratio absent — no with/without "
            "flow-schema throughput comparison"
        )
    elif storm_ratio > APF_STORM_MAX_RATIO:
        failures.append(
            f"ha_apf: storming client NOT squeezed — throughput ratio "
            f"{storm_ratio} with flow schemas on exceeds "
            f"{APF_STORM_MAX_RATIO} of unthrottled"
        )
    if not a.get("storm_429s"):
        failures.append(
            "ha_apf: storm_429s=0 — flow control never rejected the "
            "storming client (was APF actually enabled in the arm?)"
        )
    if a.get("protected_429s"):
        failures.append(
            f"ha_apf: protected lane got {a['protected_429s']} 429s — "
            "flow control throttled the flow it exists to protect"
        )
    return failures


#: --fleet leg thresholds (obs/fleet.py via cpbench/ha.py fleet arms and
#: cpbench/chaos.py chaos_alert_fidelity). Stitched traces must attribute
#: ≥ this fraction of every multi-replica trace's wall time to spans
#: (synthetic handoff-gap spans included — the point is that handoff cost
#: is VISIBLE, not that it is zero). The scrape A/B may cost at most this
#: p95 ratio on create→Ready, with an absolute floor for the same
#: shared-box-jitter reason as APF_PROTECTED_FLOOR_MS: these are
#: sub-25-ms in-memory arms whose p95 over a smoke-sized sample swings
#: by a full scheduler slice (~10 ms) run to run — the on-leg measures
#: FASTER than the off-leg about half the time — so the delta floor
#: must absorb one slice or a pure ratio flaps.
FLEET_ATTRIBUTED_MIN = 0.95
FLEET_OVERHEAD_MAX_RATIO = 1.05
#: the absolute-delta floor is SCALE-AWARE: max(10 ms, 1% of the
#: baseline p95). A flat 10 ms was tuned for the sub-25-ms smoke arms;
#: the storm regime's p95s are hundreds of ms to seconds, where 10 ms
#: is below measurement noise and the floor would stop absorbing
#: anything — 1% of the off-leg p95 keeps the floor meaning "one
#: scheduler slice OR noise-sized, whichever is larger" at every scale.
FLEET_OVERHEAD_FLOOR_MS = 10.0
FLEET_OVERHEAD_FLOOR_FRAC = 0.01


def fleet_overhead_floor_ms(p95_off_ms) -> float:
    """The scrape-overhead delta floor for a given baseline p95 — ONE
    definition shared by the gate below and any scenario that wants to
    mirror the verdict."""
    if not isinstance(p95_off_ms, (int, float)):
        return FLEET_OVERHEAD_FLOOR_MS
    return max(FLEET_OVERHEAD_FLOOR_MS,
               FLEET_OVERHEAD_FLOOR_FRAC * float(p95_off_ms))


def fleet_gate(run: dict) -> list[str]:
    """--fleet leg: cross-replica observability held end to end.

    - ``ha_scale`` multi-replica arms carry a fleet record with
      duration-weighted attributed_fraction ≥ 0.95 over stitched
      traces (weighted, not per-trace min: micro-traces would grade a
      single scheduler slice as half a lifecycle);
    - the 4-replica arm stitched at least one multi-replica trace AND
      synthesized at least one ``shard.handoff_gap`` span — a handed-off
      key renders as ONE lifecycle with its dark window visible;
    - the scrape-overhead A/B held (p95 ratio ≤ 1.05, or within the
      absolute floor);
    - ``chaos_alert_fidelity``: the page alert FIRED during the injected
      blackout, RESOLVED after recovery, and fired ZERO times in the
      healthy phase — an alert that can't show all three is either deaf
      or crying wolf."""
    failures = []
    scenarios = run.get("scenarios", {})
    scale = scenarios.get("ha_scale")
    if scale is None:
        failures.append(
            "ha_scale: missing from run — no multi-replica fleet "
            "evidence"
        )
    else:
        extra = scale.get("extra") or {}
        sweep = extra.get("replica_sweep") or {}
        fleet_arms = 0
        for arm_key in sorted(sweep):
            arm = sweep[arm_key]
            if (arm.get("replicas") or 0) < 2:
                continue
            fleet = arm.get("fleet")
            if not isinstance(fleet, dict):
                failures.append(
                    f"ha_scale[{arm_key}]: multi-replica arm has no "
                    "fleet record — the aggregator never scraped it"
                )
                continue
            fleet_arms += 1
            att = (fleet.get("attributed_fraction") or {})
            aw, n = att.get("weighted"), att.get("n")
            if not isinstance(aw, (int, float)) or not n:
                failures.append(
                    f"ha_scale[{arm_key}]: fleet attributed_fraction "
                    f"absent (weighted={aw}, n={n}) — stitching "
                    "produced no gradeable traces"
                )
            elif aw < FLEET_ATTRIBUTED_MIN:
                failures.append(
                    f"ha_scale[{arm_key}]: fleet attributed_fraction "
                    f"weighted {aw} < {FLEET_ATTRIBUTED_MIN} over "
                    f"n={n} stitched traces — lifecycle time went dark"
                )
            if (arm.get("replicas") or 0) >= 4:
                if not fleet.get("stitched_multi_replica"):
                    failures.append(
                        f"ha_scale[{arm_key}]: no stitched multi-replica "
                        "trace — the induced handoff never rendered as "
                        "one lifecycle"
                    )
                if not fleet.get("handoff_gap_spans"):
                    failures.append(
                        f"ha_scale[{arm_key}]: no shard.handoff_gap "
                        "span — the handoff's dark window is invisible"
                    )
        if fleet_arms == 0:
            failures.append(
                "ha_scale: no multi-replica arm carried a fleet record"
            )
        overhead = extra.get("fleet_overhead")
        if not isinstance(overhead, dict):
            failures.append(
                "ha_scale: fleet_overhead A/B record missing — scrape "
                "cost was never measured"
            )
        else:
            ratio = overhead.get("ratio")
            on = overhead.get("p95_on_ms")
            off = overhead.get("p95_off_ms")
            delta = (on - off if isinstance(on, (int, float))
                     and isinstance(off, (int, float)) else None)
            if not isinstance(ratio, (int, float)):
                failures.append(
                    f"ha_scale: fleet_overhead ratio absent "
                    f"(on={on}, off={off})"
                )
            elif ratio > FLEET_OVERHEAD_MAX_RATIO and not (
                    delta is not None
                    and delta <= fleet_overhead_floor_ms(off)):
                failures.append(
                    f"ha_scale: fleet scrape overhead {ratio} exceeds "
                    f"{FLEET_OVERHEAD_MAX_RATIO} on create→Ready p95 "
                    f"({off} → {on} ms, above the "
                    f"{round(fleet_overhead_floor_ms(off), 1)} ms "
                    "scale-aware floor)"
                )
    fid = scenarios.get("chaos_alert_fidelity")
    if fid is None:
        failures.append(
            "chaos_alert_fidelity: missing from run — no alert-fidelity "
            "evidence"
        )
        return failures
    rec = ((fid.get("extra") or {}).get("alert_fidelity")) or {}
    false_fires = rec.get("false_fires")
    if false_fires is None or false_fires > 0:
        failures.append(
            f"chaos_alert_fidelity: false_fires={false_fires} (must be "
            "reported and 0 — the page alert cried wolf on a healthy "
            "plane)"
        )
    if not rec.get("fired_during_blackout"):
        failures.append(
            "chaos_alert_fidelity: page alert never fired during the "
            "apiserver blackout — the alert is deaf"
        )
    if not rec.get("resolved_after_recovery"):
        failures.append(
            "chaos_alert_fidelity: page alert never resolved after "
            "recovery — it would page forever"
        )
    return failures


#: the learned-placement A/B family (cpbench/policy.py): both members
#: must be present under --policy — the fragmentation-heavy variant is
#: exactly the shape a policy regression hides in
POLICY_SCENARIOS = ("sched_policy", "sched_policy_frag")
#: smoke-scale attainment is quantized (one sample moves it by 1/n);
#: the learned arm may trail best_fit by at most max(this, one
#: sample's worth) before the leg calls it "worse" — at --full scale
#: one sample is 1/48 and the comparison tightens automatically
POLICY_ATTAINMENT_SLACK = 0.051


def policy_gate(run: dict) -> list[str]:
    """--policy leg over the sched_policy A/B family:

    - both family members present, each with a best_fit AND a learned
      arm (a missing learned arm usually means training failed — the
      recorded ``train_error`` is quoted);
    - per arm: ``double_bookings`` reported and 0 (chip-accounted —
      the one invariant that matters), the workload drained, ttp
      p50/p95 present, fragmentation reported;
    - learned arm: ``illegal_choices`` reported and 0 (a learned pick
      outside the shared feasibility mask — unrepresentable by
      construction, and this counter is the proof), and > 0 actual
      learned decisions (an all-fallback arm is not an A/B);
    - SLO attainment no worse: per objective, the learned arm may not
      miss one best_fit met, nor trail its attainment beyond the
      smoke-quantization slack."""
    failures = []
    scenarios = run.get("scenarios", {})
    for name in POLICY_SCENARIOS:
        s = scenarios.get(name)
        if s is None:
            failures.append(f"{name}: missing from run — no learned-"
                            "placement A/B evidence")
            continue
        extra = s.get("extra") or {}
        arms = extra.get("arms") or {}
        learned = arms.get("learned")
        if learned is None:
            failures.append(
                f"{name}: no learned arm — training failed? "
                f"(train_error={extra.get('train_error')!r})"
            )
        for arm_name in ("best_fit", "learned"):
            arm = arms.get(arm_name)
            if arm is None:
                if arm_name == "best_fit":
                    failures.append(f"{name}: no best_fit arm")
                continue
            db = arm.get("double_bookings")
            if db is None or db > 0:
                failures.append(
                    f"{name}/{arm_name}: double_bookings={db} (must "
                    "be reported and 0)"
                )
            if not arm.get("drained"):
                failures.append(
                    f"{name}/{arm_name}: workload did not drain — "
                    "placements stalled"
                )
            ttp = arm.get("ttp_ms") or {}
            if "p50" not in ttp or "p95" not in ttp:
                failures.append(
                    f"{name}/{arm_name}: ttp_ms p50/p95 missing"
                )
            frag = arm.get("fragmentation") or {}
            if not frag.get("decisions") \
                    or frag.get("leftover_chips_mean") is None \
                    or frag.get("stranded_free_chips_mean") is None:
                failures.append(
                    f"{name}/{arm_name}: fragmentation record "
                    "absent/empty — no leftover-chip evidence"
                )
        if learned is None:
            continue
        illegal = learned.get("illegal_choices")
        if illegal is None or illegal > 0:
            failures.append(
                f"{name}: illegal_choices={illegal} — the policy "
                "chose (or would have chosen) a pool the shared "
                "feasibility check rejects (must be reported and 0)"
            )
        n_learned = (learned.get("decisions") or {}).get("learned", 0)
        if not n_learned:
            failures.append(
                f"{name}: 0 learned decisions — every placement fell "
                f"back to best_fit (fallbacks="
                f"{learned.get('fallbacks')}); the arm judged nothing"
            )
        base_slo = (arms.get("best_fit") or {}).get("slo") or {}
        learned_slo = learned.get("slo") or {}
        for objective in sorted(base_slo):
            base = base_slo[objective]
            got = learned_slo.get(objective)
            if got is None:
                failures.append(
                    f"{name}: learned arm has no {objective} SLO "
                    "record while best_fit does"
                )
                continue
            base_att = base.get("attainment") or 0.0
            got_att = got.get("attainment") or 0.0
            # one-sample tolerance: at smoke n a single quantum is
            # 1/n, which can exceed the flat slack — a lone missed
            # sample must not flake CI (met derives from attainment,
            # so the attainment comparison subsumes a met flip)
            slack = max(POLICY_ATTAINMENT_SLACK,
                        1.0 / max(got.get("n") or 1, 1) + 1e-6)
            if got_att < base_att - slack:
                failures.append(
                    f"{name}: learned {objective} attainment "
                    f"{got_att} worse than best_fit's {base_att} "
                    f"(beyond the {round(slack, 4)} one-sample "
                    "slack) — the policy loses to the heuristic it "
                    "replaced"
                )
    return failures


#: the checkpoint-park family (cpbench/park.py): all four members must
#: be present under --park — latency, herd, gang-interleave, and the
#: oversubscription A/B each guard a different failure shape
PARK_SCENARIOS = ("park_resume_cycle", "park_resume_storm",
                  "park_during_gang", "park_oversubscribe")
#: the headline acceptance: chips served per physical chip with
#: oversubscription on — below this, parking never actually multiplied
#: the fleet
PARK_OVERSUB_MIN_RATIO = 1.5


def park_gate(run: dict) -> list[str]:
    """--park leg over the park_resume family (cpbench/park.py):

    - all four family members present;
    - cycle/storm: every parked notebook resumed, zero lost checkpoints
      (each ref round-trips the store), zero pods while parked (the
      chips were actually free), park/resume latency p50/p95 present,
      and the ``resume_latency`` SLO met;
    - park_during_gang: zero double bookings and zero lost checkpoints
      through the park→second-wave→resume interleave;
    - park_oversubscribe: oversubscription ratio ≥ 1.5× physical, above
      its non-oversubscribed baseline arm, with create→Ready SLO
      attainment no worse than that baseline, zero double bookings and
      zero lost checkpoints — the paper's scale-to-zero headline."""
    failures = []
    scenarios = run.get("scenarios", {})
    for name in PARK_SCENARIOS:
        s = scenarios.get(name)
        if s is None:
            failures.append(f"{name}: missing from run — no "
                            "checkpoint-park evidence")
            continue
        extra = s.get("extra") or {}
        lost = extra.get("lost_checkpoints")
        if lost is None or lost > 0:
            failures.append(
                f"{name}: lost_checkpoints={lost} (must be reported "
                "and 0 — a parked notebook whose ref no longer "
                "restores is a lost notebook)"
            )
        if name in ("park_resume_cycle", "park_resume_storm"):
            parked, resumed = extra.get("parked"), extra.get("resumed")
            if not parked or resumed != parked:
                failures.append(
                    f"{name}: parked={parked} resumed={resumed} — "
                    "every parked notebook must resume"
                )
            pods = extra.get("pods_while_parked")
            if pods is None or pods > 0:
                failures.append(
                    f"{name}: pods_while_parked={pods} (must be "
                    "reported and 0 — parked notebooks still holding "
                    "pods are not scale-to-zero)"
                )
            for leg in ("park_ms", "resume_ms"):
                dist = extra.get(leg) or {}
                if "p50" not in dist or "p95" not in dist:
                    failures.append(f"{name}: {leg} p50/p95 missing")
            slo = (s.get("slo") or {}).get("resume_latency")
            if not isinstance(slo, dict) or not slo.get("met"):
                failures.append(
                    f"{name}: resume_latency SLO missing or not met — "
                    f"attainment {None if not isinstance(slo, dict) else slo.get('attainment')}"  # noqa: E501
                )
        if name in ("park_during_gang", "park_oversubscribe"):
            db = extra.get("double_bookings")
            if db is None or db > 0:
                failures.append(
                    f"{name}: double_bookings={db} (must be reported "
                    "and 0)"
                )
        if name == "park_oversubscribe":
            ratio = extra.get("oversubscription_ratio")
            base = extra.get("baseline_ratio")
            if not isinstance(ratio, (int, float)) \
                    or ratio < PARK_OVERSUB_MIN_RATIO:
                failures.append(
                    f"{name}: oversubscription_ratio={ratio} below "
                    f"{PARK_OVERSUB_MIN_RATIO} — parking never "
                    "multiplied the fleet"
                )
            elif isinstance(base, (int, float)) and ratio <= base:
                failures.append(
                    f"{name}: oversubscription_ratio={ratio} does not "
                    f"beat the non-oversubscribed baseline {base}"
                )
            if not extra.get("slo_attainment_held"):
                failures.append(
                    f"{name}: create→Ready SLO attainment fell below "
                    "the non-oversubscribed baseline — the extra "
                    "tenants were paid for with the product promise"
                )
    return failures


#: the storm_scale family (cpbench/storm.py): trace-driven arrivals at
#: the 100k-CR regime plus the saturation-driven autoscaler loop. The
#: hot-path A/B margin is SCALE-AWARE like the fleet floor above: at
#: ≥ STORM_AB_FULL_N the optimizations must actually win (p95 ratio ≤
#: STORM_AB_MAX_RATIO, or throughput up by STORM_AB_MIN_SPEEDUP); at
#: smoke scale the arms are sub-second and a hard margin would grade
#: scheduler jitter, so only the noise bound applies — the full-scale
#: arm is where "gated by A/B numbers, not vibes" gets its teeth.
STORM_SCENARIOS = ("storm_scale", "storm_autoscale", "storm_chaos")
STORM_AB_FULL_N = 10_000
STORM_AB_MAX_RATIO = 0.95
STORM_AB_MIN_SPEEDUP = 1.05
STORM_AB_NOISE_RATIO = 1.5
#: the million-watch-event floor, per CR: 4 replica informers + the
#: ready informer each see ADDED + status-MODIFIED = 10 events/CR at
#: the main arm's shape; below 8 the fanout was not actually exercised
STORM_MIN_EVENTS_PER_CR = 8


def storm_gate(run: dict) -> list[str]:
    """--storm leg over the storm_scale family (cpbench/storm.py):

    - all three members present (scale, autoscale, chaos-composed);
    - ``storm_scale``: the hot-path A/B record present with its
      scale-aware margin held, the main storm arm invariant-clean
      (0 dual reconciles, 0 orphaned CRs) and actually fanning out
      (≥ 8 watch events per CR);
    - ``storm_autoscale``: the autoscaler scaled up under the storm
      AND back down on the ebb, scale-up-under-storm SLO met, flap
      count 0, membership never past bounds, invariant-clean;
    - ``storm_chaos``: 429-storm + blackout composed with the workshop
      storm lost zero CRs, double-reconciled nothing, and the
      autoscaler neither flapped nor left its bounds."""
    failures = []
    scenarios = run.get("scenarios", {})
    for name in STORM_SCENARIOS:
        if name not in scenarios:
            failures.append(f"{name}: missing from run — no storm-scale "
                            "evidence")
    scale = scenarios.get("storm_scale")
    if scale is not None:
        extra = scale.get("extra") or {}
        ab = extra.get("hotpath_ab")
        if not isinstance(ab, dict):
            failures.append(
                "storm_scale: hotpath_ab record missing — the "
                "optimizations were never A/B-measured"
            )
        else:
            n = ab.get("n") or 0
            p95_ratio = ab.get("p95_ratio")
            tput_ratio = ab.get("throughput_ratio")
            if not isinstance(p95_ratio, (int, float)) \
                    or not isinstance(tput_ratio, (int, float)):
                failures.append(
                    f"storm_scale: hotpath_ab ratios absent "
                    f"(p95_ratio={p95_ratio}, "
                    f"throughput_ratio={tput_ratio})"
                )
            elif n >= STORM_AB_FULL_N:
                if p95_ratio > STORM_AB_MAX_RATIO \
                        and tput_ratio < STORM_AB_MIN_SPEEDUP:
                    failures.append(
                        f"storm_scale: hot-path optimizations show no "
                        f"gated win at n={n} — create→Ready p95 ratio "
                        f"{p95_ratio} > {STORM_AB_MAX_RATIO} and "
                        f"throughput ratio {tput_ratio} < "
                        f"{STORM_AB_MIN_SPEEDUP}"
                    )
            elif p95_ratio > STORM_AB_NOISE_RATIO:
                failures.append(
                    f"storm_scale: smoke-scale hotpath_ab p95 ratio "
                    f"{p95_ratio} > noise bound {STORM_AB_NOISE_RATIO} "
                    "— the optimized arms regressed past jitter"
                )
        storm = extra.get("storm") or {}
        for field in ("dual_reconciles", "orphaned_keys"):
            v = storm.get(field)
            if v is None or v > 0:
                failures.append(
                    f"storm_scale: {field}={v} (must be reported and 0)"
                )
        per_cr = storm.get("events_per_cr")
        if not isinstance(per_cr, (int, float)) \
                or per_cr < STORM_MIN_EVENTS_PER_CR:
            failures.append(
                f"storm_scale: events_per_cr={per_cr} below "
                f"{STORM_MIN_EVENTS_PER_CR} — the watch fanout was "
                "not exercised at storm shape"
            )
    for name in ("storm_autoscale", "storm_chaos"):
        s = scenarios.get(name)
        if s is None:
            continue
        extra = s.get("extra") or {}
        for field in ("dual_reconciles", "orphaned_keys"):
            v = extra.get(field)
            if v is None or v > 0:
                what = ("lost CRs" if field == "orphaned_keys"
                        else "dual reconciles")
                failures.append(
                    f"{name}: {field}={v} (must be reported and 0 — "
                    f"{what} under storm)"
                )
        asc = extra.get("autoscale")
        if not isinstance(asc, dict):
            failures.append(f"{name}: autoscale record missing — the "
                            "autoscaler never ran")
            continue
        flaps = asc.get("flaps")
        if flaps is None or flaps > 0:
            failures.append(
                f"{name}: autoscaler flaps={flaps} (must be reported "
                "and 0 — tides may not thrash membership)"
            )
        lo, hi = asc.get("min_replicas"), asc.get("max_replicas")
        seen_lo = asc.get("min_active_observed")
        seen_hi = asc.get("max_active_observed")
        if None in (lo, hi, seen_lo, seen_hi) \
                or seen_lo < lo or seen_hi > hi:
            failures.append(
                f"{name}: membership left its bounds — observed "
                f"[{seen_lo}, {seen_hi}] vs configured [{lo}, {hi}]"
            )
        if name == "storm_autoscale":
            if not asc.get("scale_ups"):
                failures.append(
                    "storm_autoscale: the storm never scaled up — "
                    "no scale_up decision recorded"
                )
            if not asc.get("scale_downs"):
                failures.append(
                    "storm_autoscale: the ebb never scaled down — "
                    "no scale_down decision recorded"
                )
            if asc.get("final_replicas") != lo:
                failures.append(
                    f"storm_autoscale: final_replicas="
                    f"{asc.get('final_replicas')} != min_replicas={lo} "
                    "— the tide's ebb did not return to baseline"
                )
            slo = (s.get("slo") or {}).get("scale_up_latency")
            if not isinstance(slo, dict) or not slo.get("met"):
                failures.append(
                    "storm_autoscale: scale_up_latency SLO missing or "
                    "not met — attainment "
                    f"{None if not isinstance(slo, dict) else slo.get('attainment')}"  # noqa: E501
                )
    return failures


#: passes each lint report must PROVE ran (names in report["passes"]),
#: keyed by report schema — the three ISSUE 13 cplint dataflow passes
#: plus the five ISSUE 14 jaxlint passes: a report written by an older
#: analyzer (or a --pass subset) silently missing them would read as
#: clean while guarding nothing. LINT_REQUIRED_PASSES keeps its
#: historical name/shape (the cplint trio) for the cplint leg.
LINT_REQUIRED_PASSES = ("blocking-under-lock", "check-then-act",
                        "mvcc-escape", "autoscale-journal")
JAXLINT_REQUIRED_PASSES = ("host-sync-in-step", "retrace-hazard",
                           "rng-key-reuse", "donation-after-donate",
                           "mesh-axis-consistency")
#: schema -> (required passes, the CLI that writes the report)
LINT_SCHEMAS = {
    "cplint/v1": (LINT_REQUIRED_PASSES, "python -m tools.cplint"),
    "jaxlint/v1": (JAXLINT_REQUIRED_PASSES, "python -m tools.jaxlint"),
}


def lint_gate(report: dict) -> list[str]:
    """lint-report leg: the report must be a real cplint OR jaxlint
    record and carry zero unsuppressed errors — a missing or malformed
    report must read as a failure, not as "no findings" (the same
    asymmetry as the chaos recovery-evidence leg: absence of evidence
    isn't cleanliness). The schema's required passes must additionally
    be PRESENT in the report's pass list — ran, not merely
    clean-by-absence — and their per-pass finding counts are reported
    either way. main() further requires the --lint-report set to cover
    BOTH schemas, so dropping one analyzer's report from CI fails."""
    failures = []
    schema = report.get("schema")
    if schema not in LINT_SCHEMAS:
        failures.append(
            "lint report schema is "
            f"{schema!r}, want 'cplint/v1' or 'jaxlint/v1' — was this "
            "written by python -m tools.cplint/jaxlint --json?"
        )
        return failures
    required, writer = LINT_SCHEMAS[schema]
    ran = {p.get("name") for p in report.get("passes") or []}
    missing = [name for name in required if name not in ran]
    if missing:
        failures.append(
            f"lint report ({schema}) is missing pass(es) "
            f"{', '.join(missing)} — they did not run (older analyzer "
            f"or a --pass subset of {writer}?)"
        )
    counts: dict[str, list[int]] = {}
    for f in report.get("findings") or []:
        row = counts.setdefault(f.get("pass"), [0, 0])
        row[1 if f.get("suppressed") else 0] += 1
    for name in required:
        active, suppressed = counts.get(name, [0, 0])
        print(f"bench_gate: lint pass {name}: {active} finding(s), "
              f"{suppressed} suppressed", file=sys.stderr)
    errors = (report.get("counts") or {}).get("errors")
    if errors is None:
        failures.append("lint report has no counts.errors field")
    elif errors > 0:
        examples = [
            f"{f.get('path')}:{f.get('line')} [{f.get('pass')}] "
            f"{f.get('message')}"
            for f in (report.get("findings") or [])
            if not f.get("suppressed")
        ][:5]
        failures.append(
            f"{schema.split('/')[0]} reported {errors} unsuppressed "
            "finding(s): " + "; ".join(examples)
        )
    if not report.get("ok") and not failures:
        failures.append("lint report ok=false with zero errors — "
                        "inconsistent record")
    return failures


def gate(baseline: dict, run: dict, tolerance: float,
         min_hit_rate: float = MIN_HIT_RATE) -> list[str]:
    """Return a list of failure messages (empty = pass)."""
    failures = []
    for scenario, phase, pct in GATES:
        try:
            base = baseline["scenarios"][scenario]["phases_ms"][phase][pct]
        except KeyError:
            failures.append(
                f"{scenario}.{phase}.{pct}: missing from baseline"
            )
            continue
        try:
            got = run["scenarios"][scenario]["phases_ms"][phase][pct]
        except KeyError:
            failures.append(f"{scenario}.{phase}.{pct}: missing from run")
            continue
        limit = base * tolerance
        if got > limit:
            failures.append(
                f"{scenario}.{phase}.{pct}: {got:.1f} ms exceeds "
                f"{limit:.1f} ms ({tolerance:.0%} of baseline "
                f"{base:.1f} ms)"
            )
    for scenario in HIT_RATE_SCENARIOS:
        extra = (run.get("scenarios", {}).get(scenario, {})
                 .get("extra") or {})
        rate = (extra.get("cached_reads") or {}).get("hit_rate")
        if rate is None:
            failures.append(
                f"{scenario}: cached_reads.hit_rate not reported"
            )
        elif rate < min_hit_rate:
            failures.append(
                f"{scenario}: cached_reads.hit_rate {rate} below "
                f"{min_hit_rate} — reads are falling through to the "
                "apiserver"
            )
        rpr = extra.get("apiserver_reads_per_reconcile")
        if rpr is None:
            failures.append(
                f"{scenario}: apiserver_reads_per_reconcile not reported"
            )
        elif rpr > READS_PER_RECONCILE_MAX:
            failures.append(
                f"{scenario}: apiserver_reads_per_reconcile {rpr} "
                f"exceeds {READS_PER_RECONCILE_MAX} — controllers are "
                "round-tripping the apiserver on the read path"
            )
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline",
                    help="committed CONTROLPLANE_BENCH.json (unused — "
                         "and optional — with --chaos-only: the chaos "
                         "legs are invariants, not comparisons)")
    ap.add_argument("--run", help="fresh cpbench output (required "
                                  "unless only --lint-report is given)")
    ap.add_argument("--tolerance", type=float, default=1.2,
                    help="allowed ratio vs baseline (default 1.2 = +20%%)")
    ap.add_argument("--min-hit-rate", type=float, default=MIN_HIT_RATE,
                    help="cached-read hit-rate floor "
                         f"(default {MIN_HIT_RATE})")
    ap.add_argument("--chaos-only", action="store_true",
                    help="check only the chaos invariant legs and "
                         "require all four chaos scenarios in the run "
                         "(the CI chaos smoke step)")
    ap.add_argument("--lint-report", metavar="PATH", action="append",
                    help="lint JSON report to assert clean (repeatable; "
                         "the CI static-analysis step passes BOTH the "
                         "cplint and jaxlint reports — the leg fails "
                         "unless one report of each schema is given, so "
                         "dropping an analyzer can't read as clean); "
                         "usable alone or alongside the bench legs")
    ap.add_argument("--policy", action="store_true",
                    help="fail on missing/violated learned-placement "
                         "A/B evidence in --run (cpbench --policy; "
                         "both sched_policy scenarios, 0 double "
                         "bookings and 0 illegal choices per arm, "
                         "learned SLO attainment no worse than "
                         "best_fit; composes with the other legs)")
    ap.add_argument("--park", action="store_true",
                    help="fail on missing/violated checkpoint-park "
                         "evidence in --run (cpbench --park; all four "
                         "park_resume scenarios, every park resumed, 0 "
                         "lost checkpoints / double bookings / pods "
                         "while parked, resume_latency SLO met, "
                         "oversubscription ratio >= 1.5x at attainment "
                         "no worse than baseline; composes with the "
                         "other legs)")
    ap.add_argument("--failover", action="store_true",
                    help="fail on missing/violated failover p95, dual "
                         "reconciles or orphaned keys in the ha_scale "
                         "family, or a squeezed protected lane / "
                         "un-squeezed storm in the APF A/B in --run "
                         "(cpbench --ha; composes with the other legs)")
    ap.add_argument("--fleet", action="store_true",
                    help="fail on missing/violated cross-replica "
                         "observability evidence in --run (cpbench "
                         "--scenario ha_scale --scenario "
                         "chaos_alert_fidelity): stitched-trace "
                         "attributed_fraction >= 0.95 in multi-replica "
                         "arms, a stitched multi-replica trace with a "
                         "shard.handoff_gap span in the 4-replica arm, "
                         "scrape-overhead A/B <= 1.05, and the page "
                         "alert firing during the blackout / resolving "
                         "after / 0 false fires when healthy (composes "
                         "with the other legs)")
    ap.add_argument("--storm", action="store_true",
                    help="fail on missing/violated storm-scale "
                         "evidence in --run (cpbench --storm; all "
                         "three storm scenarios, hot-path A/B margin "
                         "at scale, 0 dual reconciles / 0 lost CRs, "
                         "scale-up-under-storm SLO met, autoscaler "
                         "flap count 0 and membership within bounds; "
                         "composes with the other legs)")
    ap.add_argument("--slo-report", action="store_true",
                    help="fail on any missed SLO objective or absent "
                         "per-scenario attainment record in --run "
                         "(obs/slo.py; composes with the other legs)")
    ap.add_argument("--prof-report", action="store_true",
                    help="fail on absent/malformed cpprof attribution "
                         "(extra.prof per scenario) or profiler A/B "
                         "overhead beyond --prof-overhead-max in --run "
                         "(cpbench --profile; composes with the other "
                         "legs)")
    ap.add_argument("--prof-overhead-max", type=float,
                    default=PROF_OVERHEAD_MAX,
                    help="profiler-on vs -off p95 ratio ceiling "
                         f"(default {PROF_OVERHEAD_MAX})")
    ap.add_argument("--store-lock-max-share", type=float, default=None,
                    metavar="FRACTION",
                    help="fail any scenario whose top contended lock is "
                         "the FakeKube store, or whose store-lock wait "
                         "share exceeds FRACTION (composes with "
                         "--prof-report; the striped-store regression "
                         "tripwire)")
    args = ap.parse_args(argv)
    failures = []
    if args.lint_report:
        schemas_seen: set = set()
        for path in args.lint_report:
            try:
                with open(path) as f:
                    lint = json.load(f)
            except (OSError, ValueError) as e:
                failures.append(f"lint report unreadable: {e}")
                continue
            if isinstance(lint, dict):
                failures += lint_gate(lint)
                if lint.get("schema") in LINT_SCHEMAS:
                    schemas_seen.add(lint["schema"])
            else:
                # parsed but not an object (list/null/string): a
                # truncated or corrupted report must fail, not read
                # as clean
                failures.append(
                    "lint report is not a JSON object "
                    f"(got {type(lint).__name__}) — was this written "
                    "by python -m tools.cplint/jaxlint --json?"
                )
        for schema, (_, writer) in sorted(LINT_SCHEMAS.items()):
            if schema not in schemas_seen:
                failures.append(
                    f"no {schema} lint report given — the "
                    f"{schema.split('/')[0]} passes did not run "
                    f"({writer} --json writes it; pass it as another "
                    "--lint-report)"
                )
    if args.run is None:
        if not args.lint_report:
            ap.error("--run is required unless --lint-report is given")
        if args.slo_report:
            # same asymmetry as --chaos-only: an explicitly requested
            # leg silently skipped is a misconfigured CI step passing
            ap.error("--slo-report requires --run")
        if args.failover:
            ap.error("--failover requires --run")
        if args.fleet:
            ap.error("--fleet requires --run")
        if args.policy:
            ap.error("--policy requires --run")
        if args.park:
            ap.error("--park requires --run")
        if args.storm:
            ap.error("--storm requires --run")
        if args.prof_report:
            ap.error("--prof-report requires --run")
        if args.store_lock_max_share is not None:
            ap.error("--store-lock-max-share requires --run")
        if args.chaos_only:
            # --chaos-only explicitly requests the chaos invariant
            # legs; silently skipping them because --run was forgotten
            # would greenlight a misconfigured CI step
            ap.error("--chaos-only requires --run")
        run = None
    else:
        with open(args.run) as f:
            run = json.load(f)
    if run is not None and args.slo_report:
        failures += slo_gate(run)
    if run is not None and args.failover:
        failures += failover_gate(run)
    if run is not None and args.fleet:
        failures += fleet_gate(run)
    if run is not None and args.policy:
        failures += policy_gate(run)
    if run is not None and args.park:
        failures += park_gate(run)
    if run is not None and args.storm:
        failures += storm_gate(run)
    if args.store_lock_max_share is not None and not args.prof_report:
        # the share rides the per-scenario prof records: requesting it
        # without the leg that reads them is a misconfigured CI step
        ap.error("--store-lock-max-share requires --prof-report")
    if run is not None and args.prof_report:
        failures += prof_gate(run, args.prof_overhead_max,
                              args.store_lock_max_share)
    baseline = None
    if run is not None and args.chaos_only:
        failures += chaos_gate(run, require_all=True)
    elif run is not None and (args.baseline
                              or not (args.slo_report
                                      or args.prof_report
                                      or args.failover
                                      or args.fleet
                                      or args.policy
                                      or args.park
                                      or args.storm)):
        # latency legs need the committed record; a pure --slo-report /
        # --prof-report / --failover / --fleet / --policy / --park /
        # --storm invocation legitimately runs without one
        if not args.baseline:
            ap.error("--baseline is required unless --chaos-only, "
                     "--slo-report, --prof-report, --failover, "
                     "--fleet, --policy, --park or --storm")
        with open(args.baseline) as f:
            baseline = json.load(f)
        failures += gate(baseline, run, args.tolerance,
                         args.min_hit_rate)
        # chaos scenarios riding along in a mixed run (--chaos) get
        # their invariant legs too
        failures += chaos_gate(run, require_all=False)
    for f in failures:
        print(f"bench_gate FAIL: {f}", file=sys.stderr)
    if not failures:
        if args.lint_report:
            print("bench_gate ok: cplint + jaxlint reports clean "
                  "(0 unsuppressed findings)", file=sys.stderr)
        if run is None:
            pass
        elif args.chaos_only:
            for name in chaos_scenarios_in(run):
                rec = (run["scenarios"][name]["extra"]["recovery_ms"]
                       ["all"])
                print(f"bench_gate ok: {name} recovery p50/p95 "
                      f"{rec['p50']:.0f}/{rec['p95']:.0f} ms, "
                      "invariants clean", file=sys.stderr)
        elif baseline is not None:
            for scenario, phase, pct in GATES:
                base = baseline["scenarios"][scenario]["phases_ms"][
                    phase][pct]
                got = run["scenarios"][scenario]["phases_ms"][phase][pct]
                print(f"bench_gate ok: {scenario}.{phase}.{pct} "
                      f"{got:.1f} ms vs baseline {base:.1f} ms",
                      file=sys.stderr)
        if run is not None and args.slo_report:
            n = len(run.get("scenarios", {}))
            print(f"bench_gate ok: SLO attainment met in all "
                  f"{n} scenario(s)", file=sys.stderr)
        if run is not None and args.failover:
            fo = (run["scenarios"]["ha_failover"]["extra"]
                  .get("failover_ms") or {})
            a = (run["scenarios"]["ha_apf"]["extra"].get("apf") or {})
            print(f"bench_gate ok: failover p95 "
                  f"{fo.get('p95', float('nan')):.0f} ms, 0 dual "
                  "reconciles / 0 orphaned keys; APF protected-lane "
                  f"p95 ratio {a.get('protected_p95_ratio')} with "
                  f"storm squeezed to {a.get('storm_throughput_ratio')}"
                  " of unthrottled", file=sys.stderr)
        if run is not None and args.fleet:
            sweep = (run["scenarios"]["ha_scale"]["extra"]
                     .get("replica_sweep") or {})
            fleet4 = (sweep.get("4") or {}).get("fleet") or {}
            overhead = (run["scenarios"]["ha_scale"]["extra"]
                        .get("fleet_overhead") or {})
            fid = (run["scenarios"]["chaos_alert_fidelity"]["extra"]
                   .get("alert_fidelity") or {})
            print("bench_gate ok: fleet attributed_fraction "
                  f"{(fleet4.get('attributed_fraction') or {}).get('weighted')}"
                  f" with {fleet4.get('stitched_multi_replica')} stitched"
                  f" multi-replica trace(s) / "
                  f"{fleet4.get('handoff_gap_spans')} handoff gap(s); "
                  f"scrape overhead ratio {overhead.get('ratio')}; page "
                  "alert fired-then-resolved with "
                  f"{fid.get('false_fires')} false fires",
                  file=sys.stderr)
        if run is not None and args.policy:
            for name in POLICY_SCENARIOS:
                arms = (run["scenarios"][name]["extra"]["arms"])
                bf, ln = arms["best_fit"], arms["learned"]
                print(
                    f"bench_gate ok: {name} ttp p50/p95 best_fit "
                    f"{bf['ttp_ms'].get('p50', float('nan')):.0f}/"
                    f"{bf['ttp_ms'].get('p95', float('nan')):.0f} ms "
                    f"vs learned "
                    f"{ln['ttp_ms'].get('p50', float('nan')):.0f}/"
                    f"{ln['ttp_ms'].get('p95', float('nan')):.0f} ms, "
                    f"stranded free chips "
                    f"{bf['fragmentation']['stranded_free_chips_mean']}"
                    f" vs "
                    f"{ln['fragmentation']['stranded_free_chips_mean']}"
                    f", 0 double bookings / 0 illegal choices",
                    file=sys.stderr)
        if run is not None and args.park:
            cyc = (run["scenarios"]["park_resume_cycle"]["extra"])
            osub = (run["scenarios"]["park_oversubscribe"]["extra"])
            print(
                f"bench_gate ok: park p50/p95 "
                f"{cyc['park_ms'].get('p50', float('nan')):.0f}/"
                f"{cyc['park_ms'].get('p95', float('nan')):.0f} ms, "
                f"resume p50/p95 "
                f"{cyc['resume_ms'].get('p50', float('nan')):.0f}/"
                f"{cyc['resume_ms'].get('p95', float('nan')):.0f} ms, "
                f"oversubscription "
                f"{osub.get('oversubscription_ratio')}x (baseline "
                f"{osub.get('baseline_ratio')}x) with SLO attainment "
                "held, 0 lost checkpoints / 0 double bookings",
                file=sys.stderr)
        if run is not None and args.storm:
            ab = (run["scenarios"]["storm_scale"]["extra"]
                  .get("hotpath_ab") or {})
            storm = (run["scenarios"]["storm_scale"]["extra"]
                     .get("storm") or {})
            asc = (run["scenarios"]["storm_autoscale"]["extra"]
                   .get("autoscale") or {})
            print(
                f"bench_gate ok: storm hot-path A/B p95 ratio "
                f"{ab.get('p95_ratio')} / throughput ratio "
                f"{ab.get('throughput_ratio')} at n={ab.get('n')}; "
                f"main arm {storm.get('n')} CRs, "
                f"{storm.get('watch_events_delivered')} watch events "
                f"({storm.get('events_per_cr')}/CR), 0 dual reconciles"
                f" / 0 lost CRs; autoscaler {asc.get('scale_ups')} "
                f"up / {asc.get('scale_downs')} down, "
                f"{asc.get('flaps')} flaps, scale-up SLO met",
                file=sys.stderr)
        if run is not None and args.prof_report:
            ov = run.get("profiler_overhead") or {}
            print(f"bench_gate ok: cpprof attribution present in all "
                  f"{len(run.get('scenarios', {}))} scenario(s), "
                  f"profiler overhead ratio {ov.get('ratio')} "
                  f"<= {args.prof_overhead_max}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
