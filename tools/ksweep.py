"""Flash-attention block-size sweep (run on the TPU box).

Each point re-runs kbench.py in a fresh process with SATPU_FLASH_* block
preferences (the kernels read them at trace time — in-process sweeping
would hit the jit cache). Prints achieved TFLOP/s per point and the best
combination; results land in KSWEEP.json.

Usage:
    python tools/ksweep.py                # fwd+bwd grid at kbench shapes
    python tools/ksweep.py --timeout 300
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import re
import subprocess
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent

# (fwd_bq, fwd_bk, dq_bq, dq_bk, dkv_bq, dkv_bk)
POINTS = [
    (256, 512, 256, 512, 256, 256),   # current defaults
    (128, 512, 256, 512, 256, 256),
    (512, 512, 256, 512, 256, 256),
    (256, 256, 256, 512, 256, 256),
    (256, 1024, 256, 512, 256, 256),
    (256, 512, 128, 512, 256, 256),
    (256, 512, 512, 512, 256, 256),
    (256, 512, 256, 512, 128, 256),
    (256, 512, 256, 512, 512, 256),
    (256, 512, 256, 512, 256, 128),
    (256, 512, 256, 512, 256, 512),
]

FLOAT = r"([0-9]+\.?[0-9]*)"


def run_point(point, timeout):
    names = ("FWD_BQ", "FWD_BK", "DQ_BQ", "DQ_BK", "DKV_BQ", "DKV_BK")
    env = dict(os.environ)
    for n, v in zip(names, point):
        env[f"SATPU_FLASH_{n}"] = str(v)
    try:
        proc = subprocess.run(
            [sys.executable, str(ROOT / "kbench.py")],
            env=env, cwd=ROOT, capture_output=True, text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return {"error": "timeout"}
    if proc.returncode != 0:
        return {"error": (proc.stderr or proc.stdout)[-300:]}
    out = {}
    m = re.search(rf"flash fwd\s+{FLOAT} ms\s+{FLOAT} TF", proc.stdout)
    if m:
        out["fwd_ms"], out["fwd_tflops"] = float(m[1]), float(m[2])
    m = re.search(rf"flash fwd\+bwd\s+{FLOAT} ms\s+{FLOAT} TF", proc.stdout)
    if m:
        out["fwdbwd_ms"], out["fwdbwd_tflops"] = float(m[1]), float(m[2])
    return out or {"error": f"unparsed: {proc.stdout[-200:]}"}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--timeout", type=float, default=420.0)
    args = ap.parse_args()
    results = []
    for point in POINTS:
        out = run_point(point, args.timeout)
        row = dict(zip(("fwd_bq", "fwd_bk", "dq_bq", "dq_bk",
                        "dkv_bq", "dkv_bk"), point), **out)
        results.append(row)
        tag = "/".join(map(str, point))
        if "error" in out:
            print(f"{tag:30s} ERROR {out['error'][:80]}")
        else:
            print(f"{tag:30s} fwd {out.get('fwd_ms', 0):7.2f} ms   "
                  f"fwd+bwd {out.get('fwdbwd_ms', 0):7.2f} ms")
    ok = [r for r in results if "fwdbwd_ms" in r]
    if ok:
        best = min(ok, key=lambda r: r["fwdbwd_ms"])
        print("\nbest fwd+bwd:", json.dumps(best))
    (ROOT / "KSWEEP.json").write_text(json.dumps(results, indent=1))
    print(f"wrote {ROOT / 'KSWEEP.json'}")


if __name__ == "__main__":
    main()
