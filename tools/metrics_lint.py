#!/usr/bin/env python3
"""Static lint for Prometheus metric declarations — compat shim.

The rules moved into the cplint framework (tools/cplint/passes/
metrics.py) so they share its AST infra and run as one pass among six
(``python -m tools.cplint``). This shim keeps the historical surface —
``python -m tools.metrics_lint`` / ``python tools/metrics_lint.py``,
plus the ``lint_file``/``run_lint``/``metric_calls`` helpers
tests/test_metrics_lint.py exercises — delegating to the pass.

Rules (unchanged):

- **counters end ``_total``** (and nothing else does);
- **histograms declare buckets explicitly**;
- **no duplicate metric family names across modules**.
"""

from __future__ import annotations

import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # direct `python tools/metrics_lint.py`
    sys.path.insert(0, str(REPO))

from tools.cplint.passes import metrics as _pass  # noqa: E402

#: re-exported for callers that introspect the scan scope
SCAN_ROOTS = _pass.SCAN_ROOTS
METRIC_KINDS = _pass.METRIC_KINDS
metric_calls = _pass.metric_calls


def lint_file(path: pathlib.Path) -> tuple[list[str], list[tuple]]:
    """(findings, declarations) for one file — historical signature;
    paths are relativized against the module-level ``REPO`` (tests
    monkeypatch it)."""
    path = pathlib.Path(path)
    findings, decls = _pass.lint_file(path, REPO)
    try:
        rel = path.relative_to(REPO)
    except ValueError:
        rel = path
    return [f"{rel}:{lineno}: {msg}" for msg, lineno in findings], decls


def run_lint(repo: pathlib.Path = None) -> list[str]:
    out = []
    for msg, rel, lineno, located in _pass.run_lint(
            pathlib.Path(repo) if repo else REPO):
        out.append(f"{rel}:{lineno}: {msg}" if located else msg)
    return out


def main() -> int:
    findings = run_lint()
    for f in findings:
        print(f, file=sys.stderr)
    print(f"metrics_lint: {len(findings)} finding(s)",
          file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
