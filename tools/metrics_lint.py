#!/usr/bin/env python3
"""Static lint for Prometheus metric declarations.

Walks the package tree's ASTs for ``Counter(...)`` / ``Gauge(...)`` /
``Histogram(...)`` constructions with a literal name and enforces the
conventions a scrape-side consumer (and our own exposition renderer)
depends on:

- **counters end ``_total``** (and nothing else does) — the Prometheus
  naming convention alerting rules pattern-match on;
- **histograms declare buckets explicitly** — the silent default hid a
  time-to-placement histogram whose real range (minutes under
  contention) sailed past the 60 s top bucket;
- **no duplicate metric family names across modules** — two modules
  declaring one name (worse: with different label sets) break the first
  process that registers both; the registry raises at runtime, this
  catches it at review time.

Runs as a tier-1 test (tests/test_metrics_lint.py) and as a step in the
controlplane bench workflow (ci/workflows.py). Exit 0 = clean.
"""

from __future__ import annotations

import ast
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
#: where metric declarations live; tests/ is excluded on purpose — tests
#: declare throwaway metrics (including intentional duplicates)
SCAN_ROOTS = ("service_account_auth_improvements_tpu",)
METRIC_KINDS = ("Counter", "Gauge", "Histogram")


def _call_kind(node: ast.Call) -> str | None:
    fn = node.func
    name = None
    if isinstance(fn, ast.Name):
        name = fn.id
    elif isinstance(fn, ast.Attribute):
        name = fn.attr
    return name if name in METRIC_KINDS else None


def metric_calls(tree: ast.AST):
    """Yield (kind, metric_name, node) for literal-name constructions."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        kind = _call_kind(node)
        if kind is None:
            continue
        if not node.args or not isinstance(node.args[0], ast.Constant) \
                or not isinstance(node.args[0].value, str):
            continue
        yield kind, node.args[0].value, node


def _has_buckets(node: ast.Call) -> bool:
    if any(kw.arg == "buckets" for kw in node.keywords):
        return True
    # Histogram(name, help_, labels, buckets, ...) — 4th positional
    return len(node.args) >= 4


def lint_file(path: pathlib.Path) -> tuple[list[str], list[tuple]]:
    """(findings, declarations) for one file; declarations feed the
    cross-module duplicate check."""
    findings: list[str] = []
    decls: list[tuple] = []
    rel = path.relative_to(REPO)
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except SyntaxError as e:
        return [f"{rel}: unparseable: {e}"], []
    for kind, name, node in metric_calls(tree):
        where = f"{rel}:{node.lineno}"
        decls.append((name, kind, str(rel), node.lineno))
        if kind == "Counter" and not name.endswith("_total"):
            findings.append(
                f"{where}: counter {name!r} must end with '_total'"
            )
        if kind != "Counter" and name.endswith("_total"):
            findings.append(
                f"{where}: {kind.lower()} {name!r} must not end with "
                "'_total' (counters only)"
            )
        if kind == "Histogram" and not _has_buckets(node):
            findings.append(
                f"{where}: histogram {name!r} must declare buckets "
                "explicitly"
            )
    return findings, decls


def run_lint(repo: pathlib.Path = REPO) -> list[str]:
    findings: list[str] = []
    by_name: dict[str, list[tuple]] = {}
    for root in SCAN_ROOTS:
        for path in sorted((repo / root).rglob("*.py")):
            file_findings, decls = lint_file(path)
            findings += file_findings
            for name, kind, rel, lineno in decls:
                by_name.setdefault(name, []).append((rel, lineno, kind))
    for name, sites in sorted(by_name.items()):
        modules = {rel for rel, _, _ in sites}
        if len(modules) > 1:
            where = ", ".join(
                f"{rel}:{lineno}" for rel, lineno, _ in sorted(sites)
            )
            findings.append(
                f"metric {name!r} declared in multiple modules: {where}"
            )
    return findings


def main() -> int:
    findings = run_lint()
    for f in findings:
        print(f, file=sys.stderr)
    print(f"metrics_lint: {len(findings)} finding(s)",
          file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
