"""Shared jaxlint infrastructure: scan scope, jit-scope resolution.

jaxlint rides cplint's pass architecture unchanged (tools/cplint/core:
PassContext, Finding, suppression index) — the context is constructed
with ``tool="jaxlint"`` so ``# jaxlint: disable=<pass>`` comments are the
suppression surface, disjoint from cplint's. What is jaxlint-specific
lives here: the four JAX package roots, and the **jit-scope resolver**
every traced-context pass shares (host-sync, retrace-hazard,
donation-after-donate all need to know "is this function's body traced
code?" and "what is marked static / donated?").

A function is *jit scope* when any of:

- it carries a ``@jax.jit`` / ``@jit`` / ``@pjit`` / ``@shard_map``
  decorator, directly or through ``functools.partial`` (the
  ``@partial(jax.jit, static_argnames=...)`` idiom);
- its NAME is passed to a ``jit``/``pjit``/``shard_map`` call anywhere
  in the module (``return jax.jit(step_fn, donate_argnums=(0,))`` — the
  make_train_step factory shape), matched conservatively by name;
- it is lexically nested inside a jit-scope function (``loss_fn`` /
  ``micro`` inside ``step_fn``: their bodies trace in the same call).
"""

from __future__ import annotations

import ast
import dataclasses

from tools.cplint import astutil
from tools.cplint.core import (  # noqa: F401  (re-exports for passes)
    Finding,
    PassContext,
    report_dict,
    run_passes,
)

#: the JAX half of the tree — the ONE place the scan scope lives.
#: scheduler/policy is the control plane's one JAX consumer (the
#: learned-placement training loop, docs/scheduler.md): its policy-
#: training code lands under the same five-pass discipline as train/
JAX_ROOTS = (
    "service_account_auth_improvements_tpu/train",
    "service_account_auth_improvements_tpu/parallel",
    "service_account_auth_improvements_tpu/ops",
    "service_account_auth_improvements_tpu/models",
    "service_account_auth_improvements_tpu/controlplane/scheduler/policy",
)

#: the mesh builder module the mesh-axis pass reads declarations from
MESH_MODULE = "service_account_auth_improvements_tpu/parallel/mesh.py"

#: call names that enter a traced context
JIT_WRAPPERS = frozenset({"jit", "pjit", "shard_map"})


def jax_context(repo=None) -> PassContext:
    """A PassContext reading ``# jaxlint: disable=`` suppressions."""
    return PassContext(repo=repo, tool="jaxlint")


@dataclasses.dataclass
class JitInfo:
    """How one function enters jit scope."""
    fn: ast.AST                     # the FunctionDef node
    static_names: set               # params marked static (by name)
    donate_nums: tuple              # positional argnums donated
    donate_names: tuple             # argnames donated
    via: str                        # "decorator" | "wrapped" | "nested"


def _tuple_of_ints(node) -> tuple:
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, int))
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    return ()


def _tuple_of_strs(node) -> tuple:
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    return ()


def jit_call_meta(call: ast.Call) -> dict | None:
    """{'target': name|None, 'static_names', 'static_nums',
    'donate_nums', 'donate_names'} when ``call`` is a
    jit/pjit/shard_map application, else None."""
    name = astutil.call_name(call)
    if name not in JIT_WRAPPERS:
        return None
    target = None
    if call.args and isinstance(call.args[0], ast.Name):
        target = call.args[0].id
    meta = {"target": target, "static_names": set(), "static_nums": (),
            "donate_nums": (), "donate_names": ()}
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            meta["static_names"] = set(_tuple_of_strs(kw.value))
        elif kw.arg == "static_argnums":
            meta["static_nums"] = _tuple_of_ints(kw.value)
        elif kw.arg == "donate_argnums":
            meta["donate_nums"] = _tuple_of_ints(kw.value)
        elif kw.arg == "donate_argnames":
            meta["donate_names"] = _tuple_of_strs(kw.value)
    return meta


def _decorator_meta(fn) -> dict | None:
    """jit metadata from a decorator list, if any decorator is a jit
    entry: ``@jax.jit``, ``@jit``, ``@partial(jax.jit, ...)``, or a
    direct ``@jax.jit(...)``/``@shard_map(...)`` factory call."""
    for dec in fn.decorator_list:
        if isinstance(dec, (ast.Name, ast.Attribute)):
            chain = astutil.attr_chain(dec) or []
            if chain and chain[-1] in JIT_WRAPPERS:
                return {"target": fn.name, "static_names": set(),
                        "static_nums": (), "donate_nums": (),
                        "donate_names": ()}
            continue
        if not isinstance(dec, ast.Call):
            continue
        call = dec
        if astutil.call_name(dec) == "partial" and dec.args:
            # @partial(jax.jit, static_argnames=...): the partial's
            # keywords ARE the jit keywords
            chain = astutil.attr_chain(dec.args[0]) or []
            if not (chain and chain[-1] in JIT_WRAPPERS):
                continue
            call = ast.Call(func=dec.args[0], args=[],
                            keywords=dec.keywords)
        meta = jit_call_meta(call)
        if meta is not None:
            meta["target"] = fn.name
            return meta
    return None


def param_names(fn) -> list:
    a = fn.args
    return [p.arg for p in (a.posonlyargs + a.args)] + \
           [p.arg for p in a.kwonlyargs]


def positional_params(fn) -> list:
    a = fn.args
    return [p.arg for p in (a.posonlyargs + a.args)]


def jit_scopes(tree: ast.AST) -> dict:
    """{FunctionDef node: JitInfo} for every jit-scope function in the
    module (see module docstring for the three entry shapes)."""
    # 1) every jit/pjit/shard_map call wrapping a plain name, module-wide
    wrapped: dict = {}       # target fn name -> meta
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            meta = jit_call_meta(node)
            if meta and meta["target"]:
                wrapped[meta["target"]] = meta

    scopes: dict = {}
    for fn in astutil.iter_functions(tree):
        meta = _decorator_meta(fn)
        via = "decorator"
        if meta is None and fn.name in wrapped:
            meta = wrapped[fn.name]
            via = "wrapped"
        if meta is None:
            continue
        pos = positional_params(fn)
        static = set(meta["static_names"])
        for i in meta["static_nums"]:
            if 0 <= i < len(pos):
                static.add(pos[i])
        scopes[fn] = JitInfo(fn=fn, static_names=static,
                             donate_nums=meta["donate_nums"],
                             donate_names=meta["donate_names"], via=via)

    # 2) nested defs inside a jit-scope function trace in the same
    # call (ast.walk is transitive, so nested-of-nested is covered)
    for fn in list(scopes):
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) and \
                    node is not fn and node not in scopes:
                scopes[node] = JitInfo(
                    fn=node, static_names=set(), donate_nums=(),
                    donate_names=(), via="nested")
    return scopes
