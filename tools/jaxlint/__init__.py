"""jaxlint: machine-checked discipline for the JAX train/inference
stack — the numerics-side sibling of tools/cplint (docs/jaxlint.md)."""
