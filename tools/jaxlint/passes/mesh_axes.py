"""mesh-axis-consistency: axis-name strings validated against the mesh.

A typo'd axis name doesn't error — ``psum(x, "dpp")`` over an axis the
mesh never declared fails at run time deep in lowering, and a
``PartitionSpec`` naming a ghost axis silently REPLICATES the tensor:
the SPMD collective you wrote becomes a full gather plus redundant
compute on every chip, visible only as a throughput cliff.

Two-way diff (the cplint rbac-check shape):

- **declared**: the axis tuple the repo's mesh builders actually build
  from — ``MESH_AXES`` in ``parallel/mesh.py`` (plus any literal
  ``Mesh(..., ("a", "b"))`` axis tuples there);
- **used**: every axis-name string literal at a spec/collective site
  across the scan scope — ``PartitionSpec``/``P`` arguments (nested
  tuples included), ``axis_name=``/``axis_names=`` keyword values AND
  parameter defaults (also ``batch_axes``/``head_axis``/
  ``kv_head_axis`` defaults, the sp-attention wrapper convention),
  positional axis arguments of the collective family
  (``psum``/``pmean``/``ppermute``/``all_gather``/``all_to_all``/
  ``axis_index``/``axis_size``...), and the mesh-axis VALUES of logical
  sharding rule tables (``DEFAULT_RULES``-shaped dicts mapping logical
  names to mesh axes);
- **unknown** axis → finding at the use site; **declared-but-never-
  used** axis → finding at the declaration (dead parallelism dimension:
  either the mesh wastes a factor of the chip count or code stopped
  exercising it — both worth a human look).
"""

from __future__ import annotations

import ast

from tools.cplint import astutil
from tools.jaxlint.core import JAX_ROOTS, MESH_MODULE

NAME = "mesh-axis-consistency"
DESCRIPTION = (
    "axis names at PartitionSpec/shard_map/collective sites diffed "
    "both ways against the axes the mesh builders declare"
)

#: spec constructors whose string args are mesh axis names
SPEC_CTORS = frozenset({"PartitionSpec", "P"})
#: collectives whose axis argument is positional arg 1
COLLECTIVES_ARG1 = frozenset({
    "psum", "pmean", "pmax", "pmin", "ppermute", "all_gather",
    "all_to_all", "psum_scatter", "pswapaxes",
})
#: collectives whose axis argument is positional arg 0
COLLECTIVES_ARG0 = frozenset({"axis_index", "axis_size"})
#: keyword names that carry axis names wherever they appear
AXIS_KWARGS = frozenset({"axis_name", "axis_names", "batch_axes",
                         "head_axis", "kv_head_axis"})
#: rule-table names whose dict VALUES are mesh axes
RULE_TABLES = frozenset({"DEFAULT_RULES"})


def _strings_in(node) -> list:
    """(value, lineno) for every string constant in a literal
    str/tuple/list/set expression."""
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            out.append((sub.value, sub.lineno))
    return out


def declared_axes(ctx) -> tuple:
    """(axes set, decl_path, decl_line) from the mesh module."""
    path = ctx.repo / MESH_MODULE
    parsed = ctx.parse(path)
    if parsed is None:
        return set(), path, 1
    tree, _ = parsed
    axes: set = set()
    line = 1
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        for tgt in targets:
            if isinstance(tgt, ast.Name) and tgt.id == "MESH_AXES":
                axes.update(v for v, _ in _strings_in(value))
                line = node.lineno
    return axes, path, line


def run(ctx) -> list:
    axes, decl_path, decl_line = declared_axes(ctx)
    findings = []
    if not axes:
        findings.append(ctx.finding(
            NAME, decl_path, decl_line,
            "could not resolve MESH_AXES from the mesh module — the "
            "axis diff has nothing to validate against",
        ))
        return findings

    used: dict = {}   # axis -> first use (path, line)

    def check(value: str, path, line) -> None:
        used.setdefault(value, (path, line))
        if value not in axes:
            findings.append(ctx.finding(
                NAME, path, line,
                f"axis name {value!r} is not declared by the mesh "
                f"builders (MESH_AXES = {tuple(sorted(axes))}) — a "
                "PartitionSpec over it silently replicates; a "
                "collective over it fails at run time",
            ))

    for path in ctx.files(*JAX_ROOTS):
        parsed = ctx.parse(path)
        if parsed is None:
            continue
        tree, _ = parsed
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                name = astutil.call_name(node)
                if name in SPEC_CTORS:
                    for v, ln in _strings_in_args(node.args):
                        check(v, path, ln)
                elif name in COLLECTIVES_ARG1 and len(node.args) >= 2:
                    for v, ln in _strings_in(node.args[1]):
                        check(v, path, ln)
                elif name in COLLECTIVES_ARG0 and len(node.args) >= 1:
                    for v, ln in _strings_in(node.args[0]):
                        check(v, path, ln)
                for kw in node.keywords:
                    if kw.arg in AXIS_KWARGS:
                        for v, ln in _strings_in(kw.value):
                            check(v, path, ln)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                for pname, default in _default_pairs(node):
                    if pname in AXIS_KWARGS and default is not None:
                        for v, ln in _strings_in(default):
                            check(v, path, ln)
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    if isinstance(tgt, ast.Name) and \
                            tgt.id in RULE_TABLES and \
                            isinstance(node.value, ast.Dict):
                        # keys are LOGICAL names; the VALUES are mesh
                        # axes (str / tuple-of-str / None)
                        for val in node.value.values:
                            for v, ln in _strings_in(val):
                                check(v, path, ln)

    for axis in sorted(axes - set(used)):
        findings.append(ctx.finding(
            NAME, decl_path, decl_line,
            f"mesh axis {axis!r} is declared in MESH_AXES but never "
            "referenced by any spec, collective, or sharding rule — a "
            "dead parallelism dimension",
        ))
    return findings


def _strings_in_args(args) -> list:
    out = []
    for a in args:
        out.extend(_strings_in(a))
    return out


def _default_pairs(fn):
    a = fn.args
    pos = a.posonlyargs + a.args
    for p, d in zip(pos[len(pos) - len(a.defaults):], a.defaults):
        yield p.arg, d
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        yield p.arg, d
