"""donation-after-donate: a donated buffer read after the donating call.

``donate_argnums`` hands the argument's HBM buffers to XLA for in-place
reuse — the standard train-state idiom (``state, m = step(state, ...)``
re-binds the name, so the dead buffer is never touched). Reading the
OLD value after the donating call dereferences a deleted buffer:
``RuntimeError: Array has been deleted`` on TPU, and silently-working
garbage on backends where donation is a no-op (CPU) — the worst kind of
portability bug.

Two sweeps:

- **registry** (whole scan scope, cross-module by name): which
  callables donate which argument positions/names — direct
  ``jax.jit(f, donate_argnums=...)`` bindings, ``@partial(jax.jit,
  donate_argnums=...)`` decorations, and FACTORY functions whose
  ``return jax.jit(..., donate_argnums=...)`` hands back a donating
  callable (``make_train_step`` → every ``step_fn =
  make_train_step(...)`` call site donates).
- **check** (per function, flow-ordered): at a call through a donating
  callable, positional args that are plain names become donated-dead —
  unless the same statement re-binds them (the sanctioned idiom). Any
  later read of a dead name is the finding; re-binding revives it.
"""

from __future__ import annotations

import ast

from tools.cplint import astutil
from tools.jaxlint.core import (
    JAX_ROOTS,
    jit_call_meta,
    jit_scopes,
    positional_params,
)

NAME = "donation-after-donate"
DESCRIPTION = (
    "an argument donated via donate_argnums/donate_argnames read after "
    "the donating call in the same scope"
)


def _donation_of(call: ast.Call):
    """(donate_nums, donate_names) when call is a donating jit."""
    meta = jit_call_meta(call)
    if meta and (meta["donate_nums"] or meta["donate_names"]):
        return meta["donate_nums"], meta["donate_names"], meta["target"]
    return None


def _build_registry(ctx) -> dict:
    """{callable name: set of donated positional indices} across the
    scan scope. donate_argnames resolve to positions via the wrapped
    function's signature when it is defined in the same module."""
    registry: dict = {}
    for path in ctx.files(*JAX_ROOTS):
        parsed = ctx.parse(path)
        if parsed is None:
            continue
        tree, _ = parsed
        fns = {f.name: f for f in astutil.iter_functions(tree)}

        def positions(nums, names, target):
            pos = set(nums)
            if names and target and target in fns:
                params = positional_params(fns[target])
                pos |= {params.index(n) for n in names if n in params}
            return pos

        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                d = _donation_of(node)
                if d is None:
                    continue
                nums, names, target = d
                pos = positions(nums, names, target)
                if pos and target:
                    # jax.jit(step_fn, donate_argnums=...): calls
                    # through the wrapped NAME donate
                    registry.setdefault(target + "@jit",
                                        set()).update(pos)
        # factories: a function whose return IS a donating jit hands
        # back a donating callable (the make_train_step shape)
        for fn in fns.values():
            for node in astutil.walk_no_nested_functions(fn):
                if isinstance(node, ast.Return) and \
                        isinstance(node.value, ast.Call):
                    d = _donation_of(node.value)
                    if d:
                        nums, names, target = d
                        pos = positions(nums, names, target)
                        if pos:
                            registry.setdefault(fn.name, set()).update(pos)
        # decorated functions donate when CALLED by name
        for fn, info in jit_scopes(tree).items():
            pos = set(info.donate_nums)
            if info.donate_names:
                params = positional_params(fn)
                pos |= {params.index(n) for n in info.donate_names
                        if n in params}
            if pos:
                registry.setdefault(fn.name + "@jit", set()).update(pos)
    return registry


def run(ctx) -> list:
    registry = _build_registry(ctx)
    findings = []
    for path in ctx.files(*JAX_ROOTS):
        parsed = ctx.parse(path)
        if parsed is None:
            continue
        tree, _ = parsed
        for fn in astutil.iter_functions(tree):
            findings.extend(_check_fn(ctx, path, fn, registry))
    return findings


def _assigned_names(targets) -> set:
    names: set = set()
    for tgt in targets:
        if isinstance(tgt, ast.Name):
            names.add(tgt.id)
        else:
            names.update(e.id for e in getattr(tgt, "elts", [])
                         if isinstance(e, ast.Name))
    return names


def _check_fn(ctx, path, fn, registry) -> list:
    findings = []
    #: local var -> donated position set (a donating callable binding)
    donating: dict = {}
    #: var name -> (line donated at, callee) for donated-dead values
    dead: dict = {}

    def donated_positions(call: ast.Call):
        name = astutil.call_name(call)
        if name is None:
            return None
        if isinstance(call.func, ast.Name) and name in donating:
            return donating[name]
        if name + "@jit" in registry:
            return registry[name + "@jit"]
        return None

    def mark_dead(call, bound: set, stmt) -> None:
        pos = donated_positions(call)
        if not pos:
            return
        callee = astutil.call_name(call)
        end = getattr(stmt, "end_lineno", None) or stmt.lineno
        for i in pos:
            if i < len(call.args) and \
                    isinstance(call.args[i], ast.Name):
                var = call.args[i].id
                if var not in bound:   # same-stmt re-binding revives
                    dead[var] = (stmt.lineno, end, callee)

    nodes = [n for n in astutil.walk_no_nested_functions(fn)
             if hasattr(n, "lineno")]
    nodes.sort(key=lambda n: (n.lineno, n.col_offset))
    seen_calls: set = set()    # Call nodes handled via their Assign
    for node in nodes:
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load) and node.id in dead:
            line, end, callee = dead[node.id]
            if node.lineno <= end:
                continue   # part of the donating statement itself
            findings.append(ctx.finding(
                NAME, path, node.lineno,
                f"{node.id!r} was donated to {callee!r} at line {line} "
                "and is read here — its buffers belong to XLA now "
                "(Array-deleted error on TPU, silent garbage where "
                "donation is a no-op); re-bind the result or drop "
                "the donation",
            ))
            del dead[node.id]     # one report per donation site
            continue
        if isinstance(node, ast.Assign):
            # donating-callable binding: step = make_train_step(...)
            if isinstance(node.value, ast.Call):
                cal = astutil.call_name(node.value)
                if cal in registry:
                    for n in _assigned_names(node.targets):
                        donating[n] = registry[cal]
                d = _donation_of(node.value)
                if d and d[0]:
                    for n in _assigned_names(node.targets):
                        donating[n] = set(d[0])
            bound = _assigned_names(node.targets)
            for call in ast.walk(node.value):
                if isinstance(call, ast.Call):
                    seen_calls.add(id(call))
                    mark_dead(call, bound, node)
            # plain re-binding revives donated-dead names
            for n in bound:
                dead.pop(n, None)
                if not isinstance(node.value, ast.Call):
                    donating.pop(n, None)
        elif isinstance(node, ast.Call) and id(node) not in seen_calls:
            mark_dead(node, set(), node)
    return findings
