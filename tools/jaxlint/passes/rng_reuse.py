"""rng-key-reuse: one PRNG key value consumed twice.

JAX keys are VALUES, not stateful generators: two primitives fed the
same key draw perfectly correlated randomness (the reused-dropout-mask
bug that silently flattens a training curve). The sanctioned discipline
is split/fold_in-then-consume — every consumption sees a fresh key.

Per function, flow-ordered dataflow over key-typed locals:

- **key sources**: ``jax.random.key/PRNGKey(...)``, elements of
  ``jax.random.split(...)`` (tuple-unpacked), ``fold_in(...)`` results,
  and parameters named like keys (``key``, ``rng``, ``*_key``,
  ``*_rng``, ``prng*``);
- **consumption**: the key passed as any argument to any call —
  sampling primitives, model ``init``s, ``split`` itself (two
  ``split(key)`` calls yield IDENTICAL children). ``fold_in(key, i)``
  is the sanctioned re-derivation shape (distinct data per call) and
  does not consume;
- **reuse**: a second consumption with no intervening re-binding is the
  finding. Branches of an ``if``/``else`` are mutually exclusive and
  merge by max-count, not sum;
- **loop-carried reuse**: a loop body that consumes a key bound
  OUTSIDE the loop and never re-binds it in the body feeds every
  iteration the same key — flagged even though each textual
  consumption appears once. Arrays of keys (``split(key, n)`` kept
  whole and indexed/scanned per element) are key POOLS and exempt.
"""

from __future__ import annotations

import ast
import re

from tools.cplint import astutil
from tools.jaxlint.core import JAX_ROOTS, param_names

NAME = "rng-key-reuse"
DESCRIPTION = (
    "a PRNG key consumed by two primitives without an intervening "
    "split/fold_in, or threaded through loop iterations unchanged"
)

_KEY_PARAM_RE = re.compile(r"(^|_)(key|rng)s?$|^prng")
_SOURCE_CALLS = frozenset({"key", "PRNGKey", "fold_in"})


def _is_random_call(node: ast.Call, name: str) -> bool:
    chain = astutil.attr_chain(node.func) or []
    return chain[-1:] == [name] and ("random" in chain or len(chain) == 1)


def _is_key_source(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = astutil.call_name(node)
    return name in _SOURCE_CALLS and _is_random_call(node, name)


def _is_split(node) -> bool:
    return isinstance(node, ast.Call) and \
        astutil.call_name(node) == "split" and _is_random_call(node, "split")


def _is_fold_in(node) -> bool:
    return isinstance(node, ast.Call) and \
        astutil.call_name(node) == "fold_in" and \
        _is_random_call(node, "fold_in")


def run(ctx) -> list:
    findings = []
    for path in ctx.files(*JAX_ROOTS):
        parsed = ctx.parse(path)
        if parsed is None:
            continue
        tree, _ = parsed
        for fn in astutil.iter_functions(tree):
            findings.extend(_Fn(ctx, path, fn).scan())
    return findings


class _Fn:
    def __init__(self, ctx, path, fn):
        self.ctx = ctx
        self.path = path
        self.fn = fn
        self.findings: list = []

    def scan(self) -> list:
        uses: dict = {}   # key var -> consumption count
        for p in param_names(self.fn):
            if _KEY_PARAM_RE.search(p):
                uses[p] = 0
        self._block(self.fn.body, uses)
        return self.findings

    # ------------------------------------------------------- statements

    def _block(self, stmts, uses: dict) -> None:
        for stmt in stmts:
            self._stmt(stmt, uses)

    def _stmt(self, stmt, uses: dict) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return   # separate dynamic context; scanned on its own
        if isinstance(stmt, ast.Assign):
            self._consume_expr(stmt.value, uses)
            self._bind_targets(stmt.targets, stmt.value, uses)
            return
        if isinstance(stmt, ast.AugAssign):
            self._consume_expr(stmt.value, uses)
            return
        if isinstance(stmt, ast.If):
            self._consume_expr(stmt.test, uses)
            then_uses = dict(uses)
            self._block(stmt.body, then_uses)
            else_uses = dict(uses)
            self._block(stmt.orelse, else_uses)
            # exclusive branches: a key used once in EACH branch was
            # still used once per execution — merge by max
            for k in set(then_uses) | set(else_uses):
                uses[k] = max(then_uses.get(k, 0), else_uses.get(k, 0))
            return
        if isinstance(stmt, (ast.For, ast.While)):
            self._loop(stmt, uses)
            return
        if isinstance(stmt, (ast.With,)):
            for item in stmt.items:
                self._consume_expr(item.context_expr, uses)
            self._block(stmt.body, uses)
            return
        if isinstance(stmt, (ast.Return, ast.Expr)):
            if stmt.value is not None:
                self._consume_expr(stmt.value, uses)
            return
        if isinstance(stmt, ast.Try):
            self._block(stmt.body, uses)
            for handler in stmt.handlers:
                self._block(handler.body, uses)
            self._block(stmt.orelse, uses)
            self._block(stmt.finalbody, uses)
            return
        # default: consume any calls inside
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                self._consume_call(node, uses)

    def _loop(self, stmt, uses: dict) -> None:
        if isinstance(stmt, ast.For):
            self._consume_expr(stmt.iter, uses)
            # the loop target binds per-iteration values: a key-named
            # target (``for k in keys:``) is a fresh key each pass,
            # anything else shadows whatever was tracked under the name
            for elt in ([stmt.target]
                        if isinstance(stmt.target, ast.Name)
                        else getattr(stmt.target, "elts", [])):
                if isinstance(elt, ast.Name):
                    if _KEY_PARAM_RE.search(elt.id):
                        uses[elt.id] = 0
                    else:
                        uses.pop(elt.id, None)
        outer = set(uses)    # keys live (bound) before the loop body
        consumed, rebound = self._body_key_flow(stmt.body)
        for k in sorted(consumed & outer - rebound):
            self.findings.append(self.ctx.finding(
                NAME, self.path, stmt.lineno,
                f"key {k!r} is consumed inside this loop but never "
                "re-bound in the body — every iteration draws from the "
                "SAME key (split or fold_in per iteration)",
            ))
        # body effects on the outer state: run the body once normally
        # (counts accumulate; rebindings reset)
        self._block(stmt.body, uses)

    def _body_key_flow(self, stmts) -> tuple:
        """(consumed, rebound) key-var names across a loop body."""
        consumed: set = set()
        rebound: set = set()
        for stmt in stmts:
            for node in astutil.walk_no_nested_functions(stmt):
                if isinstance(node, ast.Call) and not _is_fold_in(node):
                    for a in list(node.args) + [kw.value
                                                for kw in node.keywords]:
                        if isinstance(a, ast.Name):
                            consumed.add(a.id)
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        for elt in ([tgt] if isinstance(tgt, ast.Name)
                                    else getattr(tgt, "elts", [])):
                            if isinstance(elt, ast.Name):
                                rebound.add(elt.id)
        return consumed, rebound

    # ------------------------------------------------------ expressions

    def _consume_expr(self, expr, uses: dict) -> None:
        for node in astutil.walk_no_nested_functions(expr):
            if isinstance(node, (ast.ListComp, ast.SetComp,
                                 ast.GeneratorExp, ast.DictComp)):
                self._comprehension(node, uses)
            if isinstance(node, ast.Call):
                self._consume_call(node, uses)

    def _comprehension(self, comp, uses: dict) -> None:
        """``[normal(key, ...) for _ in r]`` consumes ``key`` once per
        ELEMENT — the loop-carry bug in expression clothing. Keys bound
        by the comprehension's own targets (``for k in keys``) are
        fresh per element and fine."""
        bound: set = set()
        for gen in comp.generators:
            for elt in ([gen.target]
                        if isinstance(gen.target, ast.Name)
                        else getattr(gen.target, "elts", [])):
                if isinstance(elt, ast.Name):
                    bound.add(elt.id)
        elements = ([comp.key, comp.value]
                    if isinstance(comp, ast.DictComp) else [comp.elt])
        for element in elements:
            for node in astutil.walk_no_nested_functions(element):
                if isinstance(node, ast.Call) and not _is_fold_in(node):
                    for a in (list(node.args)
                              + [kw.value for kw in node.keywords]):
                        if isinstance(a, ast.Name) and a.id in uses \
                                and a.id not in bound:
                            self.findings.append(self.ctx.finding(
                                NAME, self.path, node.lineno,
                                f"key {a.id!r} is consumed once per "
                                "element of this comprehension — every "
                                "element draws from the SAME key "
                                "(split a key pool outside, or fold_in "
                                "the element index)",
                            ))

    def _consume_call(self, call: ast.Call, uses: dict) -> None:
        if _is_fold_in(call):
            return   # sanctioned re-derivation: does not consume
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(a, ast.Name) and a.id in uses:
                uses[a.id] += 1
                if uses[a.id] == 2:
                    self.findings.append(self.ctx.finding(
                        NAME, self.path, call.lineno,
                        f"key {a.id!r} is consumed a second time here "
                        "with no intervening split/fold_in — both "
                        "consumers draw IDENTICAL randomness",
                    ))

    # --------------------------------------------------------- binding

    def _bind_targets(self, targets, value, uses: dict) -> None:
        names: list = []
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                names.append(tgt.id)
            else:
                names.extend(e.id for e in getattr(tgt, "elts", [])
                             if isinstance(e, ast.Name))
        if _is_key_source(value) or _is_split(value):
            is_split = _is_split(value)
            unpacked = any(isinstance(t, (ast.Tuple, ast.List))
                           for t in targets)
            for n in names:
                if is_split and not unpacked and len(names) == 1:
                    # keys = split(key, n): a key POOL — per-element
                    # consumption (scan/index/iter) is the idiom;
                    # drop any tracked state rather than miscount
                    uses.pop(n, None)
                else:
                    uses[n] = 0
            return
        if isinstance(value, ast.Subscript) and \
                isinstance(value.value, ast.Call) and \
                _is_split(value.value):
            # sub = split(key, n)[0]
            for n in names:
                uses[n] = 0
            return
        # any other (re)binding makes the old tracked value dead
        for n in names:
            if n in uses:
                del uses[n]
