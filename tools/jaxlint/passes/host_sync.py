"""host-sync-in-step: device→host synchronization in traced or per-step
code.

A single ``float(loss)`` inside the hot path serializes the TPU: the
host blocks until the step's whole computation flushes, the device then
idles until the host re-dispatches — the exact stall Podracer-style
throughput engineering exists to avoid (arXiv:2104.06272). Two scopes
are checked:

- **jit scope** (``tools/jaxlint/core.jit_scopes``): ``float()`` /
  ``int()`` / ``bool()`` / ``.item()`` / ``.tolist()`` /
  ``np.asarray()`` / ``np.array()`` / ``jax.device_get()`` /
  ``.block_until_ready()`` / ``print()`` applied to traced values
  inside a jit/pjit/shard_map-traced function. These either force a
  sync per call or fail under trace; ``jax.debug.print`` /
  ``jax.debug.callback`` are the sanctioned shapes and stay silent.
  Shape/dtype reads (``x.shape``/``x.ndim``/``len(x)``) are static at
  trace time and exempt.

- **step path**: a function that calls a step function (callable whose
  name contains ``step``) and host-syncs a value derived from its
  result. Cadence-gated sites (inside an ``if`` whose test contains a
  ``%`` — the ``(i + 1) % log_every == 0`` logging idiom) are
  loop-BOUNDARY logging and exempt; a sync executed per iteration (or
  per call of a loop-less helper invoked from the batch loop) is the
  finding. Syncs after the loop ends (final metrics, checkpoint step
  stamps) are loop-boundary by construction and exempt.
"""

from __future__ import annotations

import ast

from tools.cplint import astutil
from tools.jaxlint.core import JAX_ROOTS, jit_scopes, param_names

NAME = "host-sync-in-step"
DESCRIPTION = (
    "device-to-host sync (float/int/bool/.item/np.asarray/print/"
    "block_until_ready) inside a traced function or the per-step path"
)

#: builtins whose call on a device value forces a sync
SYNC_BUILTINS = frozenset({"float", "int", "bool"})
#: method calls that force a sync
SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})
#: numpy-namespace converters (receiver np/numpy/onp)
NP_CONVERTERS = frozenset({"asarray", "array"})
NP_NAMES = frozenset({"np", "numpy", "onp"})


def run(ctx) -> list:
    findings = []
    for path in ctx.files(*JAX_ROOTS):
        parsed = ctx.parse(path)
        if parsed is None:
            continue
        tree, _ = parsed
        scopes = jit_scopes(tree)
        for fn in scopes:
            findings.extend(_check_jit_fn(ctx, path, fn, scopes[fn]))
        for fn in astutil.iter_functions(tree):
            if fn not in scopes:
                findings.extend(_check_step_path(ctx, path, fn))
    return findings


# --------------------------------------------------------- jit scope

def _is_static_read(expr: ast.AST) -> bool:
    """shape/dtype/ndim/size reads and len() are static at trace time."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in (
                "shape", "ndim", "dtype", "size"):
            return True
        if isinstance(node, ast.Call) and \
                astutil.call_name(node) == "len":
            return True
    return False


def _sync_call(node: ast.Call) -> str | None:
    """Describe the sync a call performs, or None."""
    name = astutil.call_name(node)
    fn = node.func
    if isinstance(fn, ast.Name):
        if name in SYNC_BUILTINS:
            if node.args and not isinstance(node.args[0], ast.Constant) \
                    and not _is_static_read(node.args[0]):
                return f"{name}() on a traced value"
            return None
        if name == "print":
            if any(not isinstance(a, ast.Constant) for a in node.args):
                return "print() of traced values (use jax.debug.print)"
            return None
        return None
    if isinstance(fn, ast.Attribute):
        chain = astutil.attr_chain(fn) or []
        if chain[:2] == ["jax", "debug"]:
            return None          # jax.debug.print/callback: sanctioned
        if name in SYNC_METHODS:
            # covers both x.block_until_ready() and the module-level
            # jax.block_until_ready(x) spelling
            return f".{name}()"
        if name == "device_get" and chain[:1] == ["jax"]:
            return "jax.device_get()"
        if name in NP_CONVERTERS and chain[0] in NP_NAMES:
            if node.args and not isinstance(node.args[0], ast.Constant):
                return f"{chain[0]}.{name}() on a traced value"
    return None


def _check_jit_fn(ctx, path, fn, info) -> list:
    findings = []
    for node in astutil.walk_no_nested_functions(fn):
        if not isinstance(node, ast.Call):
            continue
        how = _sync_call(node)
        if how:
            findings.append(ctx.finding(
                NAME, path, node.lineno,
                f"{how} inside jit-scope function {fn.name!r} — forces "
                "a device-to-host sync (or fails) under trace; keep "
                "values on device and sync at the loop boundary",
            ))
    return findings


# --------------------------------------------------------- step path

def _is_step_call(node: ast.Call) -> bool:
    name = astutil.call_name(node)
    return bool(name) and "step" in name


def _cadence_gated(node: ast.AST, parents: dict) -> bool:
    """True when any enclosing ``if``'s test contains a ``%`` — the
    ``(i + 1) % every == 0`` logging/checkpoint cadence idiom."""
    cur = parents.get(id(node))
    while cur is not None:
        if isinstance(cur, ast.If):
            for sub in ast.walk(cur.test):
                if isinstance(sub, ast.BinOp) and \
                        isinstance(sub.op, ast.Mod):
                    return True
        cur = parents.get(id(cur))
    return False


def _enclosing_loop(node: ast.AST, parents: dict):
    cur = parents.get(id(node))
    while cur is not None:
        if isinstance(cur, (ast.For, ast.While)):
            return cur
        cur = parents.get(id(cur))
    return None


def _check_step_path(ctx, path, fn) -> list:
    # 1) names carrying step results (state, metrics, s, n, ...)
    derived: set = set()
    nodes = [n for n in astutil.walk_no_nested_functions(fn)]
    parents: dict = {}
    for parent in nodes:
        for child in ast.iter_child_nodes(parent):
            parents[id(child)] = parent

    has_step_call = False
    for node in nodes:
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                _is_step_call(node.value):
            has_step_call = True
            for tgt in node.targets:
                for elt in ([tgt] if isinstance(tgt, ast.Name)
                            else getattr(tgt, "elts", [])):
                    if isinstance(elt, ast.Name):
                        derived.add(elt.id)
        elif isinstance(node, ast.Call) and _is_step_call(node):
            has_step_call = True
    if not has_step_call or not derived:
        return []

    # one propagation sweep: x = f(derived) keeps x derived
    for node in nodes:
        if isinstance(node, ast.Assign):
            reads = {n.id for n in ast.walk(node.value)
                     if isinstance(n, ast.Name)}
            if reads & derived:
                for tgt in node.targets:
                    for elt in ([tgt] if isinstance(tgt, ast.Name)
                                else getattr(tgt, "elts", [])):
                        if isinstance(elt, ast.Name):
                            derived.add(elt.id)

    fn_has_loop = any(isinstance(n, (ast.For, ast.While)) for n in nodes)

    findings = []
    for node in nodes:
        if not isinstance(node, ast.Call):
            continue
        how = _sync_call(node)
        if how is None:
            continue
        reads = {n.id for n in ast.walk(node)
                 if isinstance(n, ast.Name)}
        if not (reads & derived):
            continue
        in_loop = _enclosing_loop(node, parents) is not None
        if in_loop:
            if _cadence_gated(node, parents):
                continue       # loop-boundary logging cadence
        elif fn_has_loop:
            continue           # after/before the loop: boundary sync
        findings.append(ctx.finding(
            NAME, path, node.lineno,
            f"{how} on a step result in the per-step path of "
            f"{fn.name!r} — blocks the host every iteration; move the "
            "sync to a cadence-gated loop boundary or keep the "
            "accumulator on device",
        ))
    return findings
