"""Pass registry: one module per pass, each exposing NAME / DESCRIPTION
/ run(ctx) — the cplint shape, over the JAX scan scope."""

from tools.jaxlint.passes import (
    donation,
    host_sync,
    mesh_axes,
    retrace_hazard,
    rng_reuse,
)

ALL_PASSES = (
    host_sync,
    retrace_hazard,
    rng_reuse,
    donation,
    mesh_axes,
)
