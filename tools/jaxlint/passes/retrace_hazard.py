"""retrace-hazard: shapes that retrace (or fail) a jitted function.

A retrace storm burns TPU time silently: the step runs, just 100×
slower, recompiling every call. The statically-catchable shapes, all
checked inside jit-scope functions (``tools/jaxlint/core.jit_scopes``):

- **unhashable static arg**: a parameter marked static via
  ``static_argnums``/``static_argnames`` whose default is a mutable
  literal (``[]``/``{}``/``set()``...) — jit hashes static args for the
  cache key, so the first call raises ``TypeError: unhashable``; a
  custom ``__eq__``-less object retraces per instance.
- **Python control flow on traced values**: ``if``/``while`` whose test
  reads a traced parameter (or a value derived from one) — under trace
  this raises ``TracerBoolConversionError`` or, with shape-polymorphic
  revisions, silently forks the trace. ``x is None`` / ``x is not
  None`` identity tests are Python-level structure checks and exempt;
  ``.shape``/``.dtype``/``len()`` derivations are static and exempt
  (flow-sensitive taint, the mvcc-escape alias-tracking style).
- **f-string/format of a tracer**: ``f"{loss}"`` / ``"".format(loss)``
  materializes ``Traced<...>`` junk at trace time (once), not the
  value — almost always a logging bug that also hides a future sync.
- **closure over a mutable module global**: a jit-scope function
  reading a module-level name bound to a ``dict``/``list``/``set``
  literal — the closure value is baked at FIRST trace; later mutations
  are silently ignored (or force callers into manual cache-busting).
"""

from __future__ import annotations

import ast

from tools.cplint import astutil
from tools.jaxlint.core import (
    JAX_ROOTS,
    jit_scopes,
    param_names,
)

NAME = "retrace-hazard"
DESCRIPTION = (
    "jit retrace/trace-failure hazards: unhashable static args, Python "
    "control flow or string-formatting on traced values, closure over "
    "mutable module globals"
)

_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)
_MUTABLE_CTORS = frozenset({"list", "dict", "set", "bytearray",
                            "defaultdict", "OrderedDict", "deque"})


def _is_mutable_value(node) -> bool:
    if isinstance(node, _MUTABLE_LITERALS):
        return True
    return isinstance(node, ast.Call) and \
        astutil.call_name(node) in _MUTABLE_CTORS


def _mutable_globals(tree) -> dict:
    """{name: lineno} of module-level names bound to mutable values."""
    out: dict = {}
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        if not _is_mutable_value(value):
            continue
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                out[tgt.id] = node.lineno
    return out


def run(ctx) -> list:
    findings = []
    for path in ctx.files(*JAX_ROOTS):
        parsed = ctx.parse(path)
        if parsed is None:
            continue
        tree, _ = parsed
        mut_globals = _mutable_globals(tree)
        scopes = jit_scopes(tree)
        for fn, info in scopes.items():
            findings.extend(
                _check_fn(ctx, path, fn, info, mut_globals))
    return findings


def _default_pairs(fn):
    """(param_name, default_node) pairs, positional and kw-only."""
    a = fn.args
    pos = a.posonlyargs + a.args
    for p, d in zip(pos[len(pos) - len(a.defaults):], a.defaults):
        yield p.arg, d
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if d is not None:
            yield p.arg, d


class _Taint:
    """Traced-value taint over local names, flow-ordered."""

    def __init__(self, fn, info):
        self.tainted: set = set()
        for p in param_names(fn):
            if p not in info.static_names and p != "self":
                self.tainted.add(p)

    @staticmethod
    def _static_derivation(expr) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute) and node.attr in (
                    "shape", "ndim", "dtype", "size"):
                return True
            if isinstance(node, ast.Call) and \
                    astutil.call_name(node) in ("len", "int", "bool",
                                                "float", "isinstance"):
                # int()/bool() of a tracer is the host-sync pass's
                # finding; for taint purposes the RESULT is concrete
                return True
        return False

    def reads_tainted(self, expr) -> set:
        """Tainted names the expression reads. ``x is None`` identity
        tests are Python-level structure checks — Name occurrences
        inside them don't count (tracked by node identity, so the same
        name still counts when ALSO read outside the identity test)."""
        if self._static_derivation(expr):
            return set()
        ident_nodes = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot))
                    for op in node.ops):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name):
                        ident_nodes.add(id(sub))
        hits = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and \
                    node.id in self.tainted and \
                    id(node) not in ident_nodes:
                hits.add(node.id)
        return hits

    def assign(self, node: ast.Assign):
        value_tainted = bool(self.reads_tainted(node.value))
        for tgt in node.targets:
            for elt in ([tgt] if isinstance(tgt, ast.Name)
                        else getattr(tgt, "elts", [])):
                if isinstance(elt, ast.Name):
                    if value_tainted:
                        self.tainted.add(elt.id)
                    else:
                        self.tainted.discard(elt.id)


def _check_fn(ctx, path, fn, info, mut_globals) -> list:
    findings = []

    # --- unhashable static args
    for pname, default in _default_pairs(fn):
        if pname in info.static_names and _is_mutable_value(default):
            findings.append(ctx.finding(
                NAME, path, default.lineno,
                f"static arg {pname!r} of jitted {fn.name!r} has an "
                "unhashable (mutable) default — jit hashes static args "
                "for its cache key: this raises TypeError on first "
                "call, and an object default retraces per instance",
            ))

    taint = _Taint(fn, info)
    local_names = set(param_names(fn))
    nodes = [n for n in astutil.walk_no_nested_functions(fn)
             if hasattr(n, "lineno")]
    nodes.sort(key=lambda n: (n.lineno, n.col_offset))
    for node in nodes:
        if isinstance(node, ast.Assign):
            taint.assign(node)
            for tgt in node.targets:
                for elt in ([tgt] if isinstance(tgt, ast.Name)
                            else getattr(tgt, "elts", [])):
                    if isinstance(elt, ast.Name):
                        local_names.add(elt.id)
        elif isinstance(node, (ast.If, ast.While)):
            hits = taint.reads_tainted(node.test)
            if hits:
                kind = "while" if isinstance(node, ast.While) else "if"
                findings.append(ctx.finding(
                    NAME, path, node.lineno,
                    f"Python `{kind}` on traced value(s) "
                    f"{', '.join(sorted(hits))} inside jitted "
                    f"{fn.name!r} — raises under trace (or forks the "
                    "program); use jnp.where / lax.cond, or mark the "
                    "arg static",
                ))
        elif isinstance(node, ast.IfExp):
            hits = taint.reads_tainted(node.test)
            if hits:
                findings.append(ctx.finding(
                    NAME, path, node.lineno,
                    f"conditional expression on traced value(s) "
                    f"{', '.join(sorted(hits))} inside jitted "
                    f"{fn.name!r} — raises under trace; use jnp.where "
                    "/ lax.cond, or mark the arg static",
                ))
        elif isinstance(node, ast.JoinedStr):
            hits = set()
            for part in node.values:
                if isinstance(part, ast.FormattedValue):
                    hits |= taint.reads_tainted(part.value)
            if hits:
                findings.append(ctx.finding(
                    NAME, path, node.lineno,
                    f"f-string formats traced value(s) "
                    f"{', '.join(sorted(hits))} inside jitted "
                    f"{fn.name!r} — renders Traced<...> at trace time, "
                    "not the runtime value (jax.debug.print formats "
                    "runtime values)",
                ))
        elif isinstance(node, ast.Call):
            if astutil.call_name(node) == "format" and \
                    isinstance(node.func, ast.Attribute):
                hits = set()
                for a in list(node.args) + [kw.value
                                            for kw in node.keywords]:
                    hits |= taint.reads_tainted(a)
                if hits:
                    findings.append(ctx.finding(
                        NAME, path, node.lineno,
                        f".format() of traced value(s) "
                        f"{', '.join(sorted(hits))} inside jitted "
                        f"{fn.name!r} — renders Traced<...> at trace "
                        "time, not the runtime value",
                    ))

    # --- closure over mutable module globals (reads not shadowed by a
    # local binding)
    flagged = set()
    for node in nodes:
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load) and \
                node.id in mut_globals and \
                node.id not in local_names and node.id not in flagged:
            flagged.add(node.id)
            findings.append(ctx.finding(
                NAME, path, node.lineno,
                f"jitted {fn.name!r} closes over mutable module global "
                f"{node.id!r} (bound at line {mut_globals[node.id]}) — "
                "the value is baked into the trace on first call; "
                "later mutations are silently ignored",
            ))
    return findings
