"""CLI: ``python -m tools.jaxlint`` — run every pass over the JAX
packages, print findings, exit nonzero on unsuppressed errors.

    python -m tools.jaxlint                       # all five passes
    python -m tools.jaxlint --pass rng-key-reuse --pass host-sync-in-step
    python -m tools.jaxlint --json jaxlint_report.json   # CI record
    python -m tools.jaxlint --list-passes         # machine-readable catalog
    python -m tools.jaxlint --mutations           # seeded-mutant validation
"""

from __future__ import annotations

import argparse
import json
import sys

from tools.cplint.core import report_dict, run_passes
from tools.jaxlint.core import jax_context
from tools.jaxlint.passes import ALL_PASSES


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.jaxlint",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("--pass", dest="passes", action="append",
                    metavar="NAME",
                    help="run only the named pass (repeatable); "
                         "names: " + ", ".join(p.NAME for p in ALL_PASSES))
    ap.add_argument("--list-passes", action="store_true",
                    help="print the pass catalog as JSON to stdout and "
                         "exit (same jaxlint-passes/v1 shape as cplint's "
                         "catalog; CI builds --pass subsets from it)")
    ap.add_argument("--json", dest="json_out", metavar="PATH",
                    help="write the SARIF-ish JSON report "
                         "(bench_gate --lint-report asserts it clean)")
    ap.add_argument("--mutations", action="store_true",
                    help="run the seeded-mutant validation suite: every "
                         "hand-seeded JAX-discipline bug must be caught "
                         "by its pass while clean HEAD stays clean "
                         "(tools/jaxlint/mutants.py)")
    ap.add_argument("--repo", default=None,
                    help="repo root override (tests)")
    args = ap.parse_args(argv)

    if args.list_passes:
        print(json.dumps({
            "schema": "jaxlint-passes/v1",
            "passes": [{"name": p.NAME, "description": p.DESCRIPTION}
                       for p in ALL_PASSES],
        }, indent=2))
        return 0

    if args.mutations:
        from tools.jaxlint import mutants
        record = mutants.run_mutations(repo=args.repo)
        if args.json_out:
            with open(args.json_out, "w") as f:
                json.dump(record, f, indent=2)
        return mutants.print_record(record)

    known = {p.NAME for p in ALL_PASSES}
    only = set(args.passes or ())
    unknown = only - known
    if unknown:
        ap.error(f"unknown pass(es): {', '.join(sorted(unknown))}")

    ctx = jax_context(repo=args.repo)
    findings = run_passes(ALL_PASSES, ctx, only=only or None)
    report = report_dict(findings, ALL_PASSES, schema="jaxlint/v1")
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(report, f, indent=2)
    for finding in findings:
        print(finding.format(), file=sys.stderr)
    counts = report["counts"]
    print(
        f"jaxlint: {counts['errors']} finding(s), "
        f"{counts['suppressed']} suppressed",
        file=sys.stderr,
    )
    return 1 if counts["errors"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
