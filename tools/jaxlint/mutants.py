"""Mutation validation: a lint that can't re-find seeded bugs guards
nothing (the schedsim discipline applied to jaxlint).

Each mutant is ONE hand-seeded JAX-discipline bug — a textual patch
against a REAL file in the scan scope (``old`` must match exactly once,
so tree drift fails loud instead of silently testing nothing) — paired
with the pass expected to catch it. The runner copies the scanned
packages into a scratch repo, applies one mutant at a time, runs the
expected pass, and requires (a) at least one unsuppressed finding from
that pass in the mutated file, and (b) the un-mutated tree clean. The
whole suite is deterministic: no sampling, no seeds — AST analysis
either proves the property or it doesn't, so "caught" here is a
stable CI gate, not a probabilistic budget.

    python -m tools.jaxlint --mutations [--json record.json]
"""

from __future__ import annotations

import dataclasses
import pathlib
import shutil
import sys
import tempfile

from tools.cplint.core import run_passes
from tools.jaxlint.core import JAX_ROOTS, jax_context
from tools.jaxlint.passes import ALL_PASSES

_TRAIN = "service_account_auth_improvements_tpu/train"
_PARALLEL = "service_account_auth_improvements_tpu/parallel"
_MODELS = "service_account_auth_improvements_tpu/models"


@dataclasses.dataclass(frozen=True)
class Mutant:
    name: str
    path: str          # repo-relative file the bug is seeded into
    old: str           # exact source snippet (must match exactly once)
    new: str           # the seeded bug
    expect: str        # pass NAME expected to catch it


#: the seeded-bug matrix — every entry must be CAUGHT by its pass.
#: Mutants are lint-only (never executed), so a patch may be
#: semantically silly as long as it is the SHAPE of the bug family.
MUTANTS = (
    # 1. the canonical stall: a per-step float() of the loss in the
    # train loop, ungated by any logging cadence
    Mutant(
        name="per_step_float_loss",
        path=f"{_TRAIN}/loop.py",
        old="state, metrics = step_fn(state, batch, mask)",
        new="state, metrics = step_fn(state, batch, mask)\n"
            "            loss_now = float(metrics[\"loss\"])",
        expect="host-sync-in-step",
    ),
    # 2. a sync INSIDE the jitted step function itself
    Mutant(
        name="float_in_jitted_step",
        path=f"{_TRAIN}/step.py",
        old="        gnorm = optax.global_norm(grads)",
        new="        gnorm = float(optax.global_norm(grads))",
        expect="host-sync-in-step",
    ),
    # 3. reused sampling key: the rejection-threshold draw re-consumes
    # the round key that the later correction split consumes again
    Mutant(
        name="reused_round_key",
        path=f"{_MODELS}/speculative.py",
        old="        u = jax.random.uniform(ukey, (gamma,))",
        new="        u = jax.random.uniform(key, (gamma,))",
        expect="rng-key-reuse",
    ),
    # 4. loop-carried key: every LoRA target initialized from the SAME
    # key (split-per-target dropped)
    Mutant(
        name="loop_carried_lora_key",
        path=f"{_TRAIN}/lora.py",
        old="        key, ka = jax.random.split(key)",
        new="        ka = jax.random.split(key)[0]",
        expect="rng-key-reuse",
    ),
    # 5. donated-then-read params: the train loop keeps a reference to
    # the state it just donated to the step
    Mutant(
        name="donated_state_read",
        path=f"{_TRAIN}/loop.py",
        old="            state, metrics = step_fn(state, batch, mask)",
        new="            new_state, metrics = step_fn(state, batch, mask)\n"
            "            stale_params = state.params\n"
            "            state = new_state",
        expect="donation-after-donate",
    ),
    # 6. typo'd axis in a PartitionSpec: the batch sharding silently
    # replicates instead of splitting over fsdp
    Mutant(
        name="typo_axis_partitionspec",
        path=f"{_TRAIN}/data.py",
        old="P((\"dp\", \"fsdp\"), None)",
        new="P((\"dp\", \"fsdpp\"), None)",
        expect="mesh-axis-consistency",
    ),
    # 7. typo'd axis in a collective default: ring attention permutes
    # over an axis no mesh declares
    Mutant(
        name="typo_axis_collective_default",
        path=f"{_PARALLEL}/ring.py",
        old="def ring_attention_local(q, k, v, *, axis_name: str = \"sp\",",
        new="def ring_attention_local(q, k, v, *, axis_name: str = \"spp\",",
        expect="mesh-axis-consistency",
    ),
    # 8. unhashable static arg: a mutable default on a static_argnames
    # parameter — TypeError on first call, per-instance retrace for
    # object defaults
    Mutant(
        name="unhashable_static_arg",
        path=f"{_MODELS}/generate.py",
        old="def _sample_jit(logits, key, temperature, top_p, *, top_k, "
            "greedy,\n                use_top_p):",
        new="def _sample_jit(logits, key, temperature, top_p, *, top_k, "
            "greedy,\n                use_top_p=[]):",
        expect="retrace-hazard",
    ),
    # 9. Python branch on a traced value inside the jitted step
    Mutant(
        name="python_if_on_traced",
        path=f"{_TRAIN}/step.py",
        old="        if grad_accum == 1:",
        new="        if tokens[0, 0] == 0 or grad_accum == 1:",
        expect="retrace-hazard",
    ),
    # 10. double-consumed stream key: the first-token sample reuses the
    # stream key that the decode split consumes again
    Mutant(
        name="reused_stream_key",
        path=f"{_MODELS}/generate.py",
        old="    first = _sample_jit(logits, first_key, t, p, top_k=k_, "
            "greedy=greedy,",
        new="    first = _sample_jit(logits, key, t, p, top_k=k_, "
            "greedy=greedy,",
        expect="rng-key-reuse",
    ),
)


def _pass_by_name(name: str):
    for p in ALL_PASSES:
        if p.NAME == name:
            return p
    raise KeyError(name)


def _copy_scope(src_repo: pathlib.Path, dst_repo: pathlib.Path) -> None:
    for root in JAX_ROOTS:
        shutil.copytree(src_repo / root, dst_repo / root)


def run_mutations(repo=None) -> dict:
    """Apply each mutant to a scratch copy of the scan scope; the
    expected pass must flag the mutated file. Returns the JSON record
    (schema jaxlint-mutants/v1)."""
    src = pathlib.Path(repo) if repo else \
        pathlib.Path(__file__).resolve().parent.parent.parent

    # clean-HEAD gate first: a dirty baseline would let any mutant
    # "pass" on pre-existing noise
    base_ctx = jax_context(repo=src)
    baseline = [f for f in run_passes(ALL_PASSES, base_ctx)
                if not f.suppressed]

    results = []
    for m in MUTANTS:
        scratch = pathlib.Path(tempfile.mkdtemp(prefix="jaxlint_mut_"))
        try:
            _copy_scope(src, scratch)
            target = scratch / m.path
            text = target.read_text()
            occurrences = text.count(m.old)
            if occurrences != 1:
                results.append({
                    "name": m.name, "pass": m.expect, "caught": False,
                    "error": f"patch anchor matched {occurrences} times "
                             f"in {m.path} (want exactly 1) — tree "
                             "drifted; update the mutant",
                })
                continue
            target.write_text(text.replace(m.old, m.new))
            ctx = jax_context(repo=scratch)
            findings = [
                f for f in _pass_by_name(m.expect).run(ctx)
                if not f.suppressed and f.path == m.path
            ]
            results.append({
                "name": m.name, "pass": m.expect,
                "caught": bool(findings),
                "findings": [f.to_dict() for f in findings[:3]],
            })
        finally:
            shutil.rmtree(scratch, ignore_errors=True)

    return {
        "schema": "jaxlint-mutants/v1",
        "clean_head_findings": [f.to_dict() for f in baseline],
        "clean_head_ok": not baseline,
        "mutants": results,
        "caught": sum(1 for r in results if r["caught"]),
        "total": len(results),
        "ok": not baseline and all(r["caught"] for r in results),
    }


def print_record(record: dict) -> int:
    """Human summary to stderr; exit status for the CLI."""
    if not record["clean_head_ok"]:
        print("jaxlint mutations: clean HEAD is NOT clean — fix or "
              "suppress baseline findings first:", file=sys.stderr)
        for f in record["clean_head_findings"][:10]:
            print(f"  {f['path']}:{f['line']} [{f['pass']}] "
                  f"{f['message']}", file=sys.stderr)
    for r in record["mutants"]:
        status = "caught" if r["caught"] else "NOT CAUGHT"
        extra = f" — {r['error']}" if r.get("error") else ""
        print(f"jaxlint mutations: {r['name']} [{r['pass']}] "
              f"{status}{extra}", file=sys.stderr)
    print(f"jaxlint mutations: {record['caught']}/{record['total']} "
          f"caught, clean head "
          f"{'ok' if record['clean_head_ok'] else 'DIRTY'}",
          file=sys.stderr)
    return 0 if record["ok"] else 1
