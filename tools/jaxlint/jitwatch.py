"""jitwatch: runtime recompile/transfer watcher for step functions.

The static retrace-hazard pass catches the shapes it can prove; only
the running program shows whether a step function ACTUALLY recompiles
(a float32/weak-type flip, a shape wobble from a ragged tail batch, a
config object whose __hash__ churns) — the lockwatch idea applied to
the XLA compile cache. Two instruments, one wrapper:

- **per-call-site compile counter**: ``watch.wrap(step_fn, site=...,
  budget=N)`` counts executables minted for THAT callable —
  primarily via the jit wrapper's own cache size (``_cache_size()``,
  exact and per-function), falling back to the global
  ``jax.log_compiles`` stream (``start_logs()`` hooks the ``jax``
  logger the way ``jax.config.jax_log_compiles`` emits) when the
  attribute is absent or broken — the stream is started AUTOMATICALLY
  at wrap time in that case (an inert watcher passing budget asserts
  vacuously is the failure mode this guards), and executables minted
  DURING each wrapped call are attributed to the wrapper (in-call
  windowing: closures around inner jits count too; concurrent
  compiles from other threads conflate, a documented
  over-approximation). The first compile is expected; the budget
  bounds each WRAPPER's own executable count (a fresh ``fit()``
  legitimately builds a fresh jit), and a call that pushes a wrapper
  past it raises :class:`RecompileBudgetExceeded` AT the offending
  call — the test fails pointing at the call site, not at a
  slow-suite symptom. The site's snapshot additionally reports the
  cumulative cross-wrapper total (``compiles``) and the worst single
  wrapper (``wrapper_max``, what ``over_budget()`` judges) — a
  re-jit-per-call pattern reads as ``compiles ≈ calls`` there.
- **transfer attribution**: each wrapped call runs under
  ``jax.transfer_guard_device_to_host("disallow")``, so an unexpected
  device→host pull inside the step raises with the call site in the
  traceback. On the CPU backend host==device and XLA never routes a
  guarded transfer, so the guard is structurally quiet there — the
  recompile counter is the CPU-testable half; the guard earns its keep
  on real TPU runs (documented in docs/jaxlint.md).

Enablement follows lockwatch: ``JAXLINT_JITWATCH=1`` turns
:func:`maybe_wrap` from an identity function into real
instrumentation — zero cost when off (one env read at wrap time, no
per-call overhead), so the train loop wires it unconditionally.
``JAXLINT_JITWATCH_BUDGET`` overrides the default per-site budget.
"""

from __future__ import annotations

import logging
import os
import re

DEFAULT_BUDGET = 3

#: jax_log_compiles messages that mark one executable build; both the
#: pxla "Compiling <name> with global shapes" line (one per executable)
#: and older dispatch variants are matched, keyed by function name
_COMPILE_RE = re.compile(
    r"Compiling ([A-Za-z0-9_<>.-]+) with global shapes"
)


class RecompileBudgetExceeded(AssertionError):
    """A wrapped step minted more executables than its budget."""

    def __init__(self, site: str, compiles: int, budget: int):
        self.site, self.compiles, self.budget = site, compiles, budget
        super().__init__(
            f"jitwatch: {site!r} compiled {compiles} executables "
            f"(budget {budget}) — a retrace per call burns the "
            "accelerator silently; check static args, shapes, and "
            "weak types (docs/jaxlint.md)"
        )


class _LogCounter(logging.Handler):
    """Counts compile events off the jax logger: per function name
    (the human-readable view) and in total (the in-call attribution
    window the wrap fallback uses)."""

    def __init__(self):
        super().__init__(level=logging.DEBUG)
        self.counts: dict = {}
        self.total = 0

    def emit(self, record):
        m = _COMPILE_RE.search(record.getMessage())
        if m:
            name = m.group(1)
            self.counts[name] = self.counts.get(name, 0) + 1
            self.total += 1


class JitWatch:
    """Recompile/transfer watcher; one per process when installed."""

    def __init__(self, budget: int | None = None):
        env = os.environ.get("JAXLINT_JITWATCH_BUDGET")
        self.budget = budget if budget is not None else (
            int(env) if env else DEFAULT_BUDGET)
        self.sites: dict = {}     # site -> {calls, compiles, budget}
        self._log_counter: _LogCounter | None = None
        self._saved_log_compiles = None

    # ------------------------------------------------------- wrapping

    def wrap(self, fn, site: str | None = None, budget: int | None = None,
             guard_transfers: bool = True):
        """Instrument a jitted callable. Returns a callable with the
        same signature that raises RecompileBudgetExceeded when the
        site's executable count passes its budget, and (on backends
        where host != device) fails loud on device→host transfers
        inside the call."""
        import jax

        site = site or getattr(fn, "__name__", repr(fn))
        limit = budget if budget is not None else self.budget
        stats = self.sites.setdefault(
            site, {"calls": 0, "compiles": 0, "wrapper_max": 0,
                   "budget": limit})
        cache_size = getattr(fn, "_cache_size", None)
        if cache_size is not None:
            try:
                cache_size()     # probe NOW: a renamed/broken private
            except Exception:    # API must fall back, not go inert
                cache_size = None
        if cache_size is None:
            # the promised jax.log_compiles fallback must actually
            # ENGAGE on this path — without it the watcher would count
            # zero forever and every budget assert passes vacuously.
            # Executables minted DURING each wrapped call are
            # attributed to this wrapper (in-call windowing — no name
            # matching, so closures around inner jits count too;
            # concurrent compiles from OTHER threads inside the window
            # conflate, a documented over-approximation).
            self.start_logs()
        # The budget bounds each WRAPPER's own executable count — a
        # fresh fit() legitimately builds a fresh jit (its own cache),
        # so several wrappers may share one site. The site additionally
        # accumulates the cumulative delta across wrappers in
        # "compiles" (reporting: total executables the site minted —
        # a per-call re-jit pattern shows up there as compiles≈calls)
        # and tracks the worst single wrapper in "wrapper_max" (what
        # over_budget() judges).
        seen = {"compiles": 0}

        def wrapped(*args, **kwargs):
            stats["calls"] += 1
            counter = self._log_counter
            pre = counter.total if counter is not None else 0
            if guard_transfers:
                with jax.transfer_guard_device_to_host("disallow"):
                    out = fn(*args, **kwargs)
            else:
                out = fn(*args, **kwargs)
            if cache_size is not None:
                try:
                    now = cache_size()
                except Exception:
                    now = seen["compiles"]
            elif counter is not None:
                now = seen["compiles"] + max(0, counter.total - pre)
            else:
                now = seen["compiles"]
            if now > seen["compiles"]:
                stats["compiles"] += now - seen["compiles"]
                seen["compiles"] = now
            if now > stats["wrapper_max"]:
                stats["wrapper_max"] = now
            if now > stats["budget"]:
                raise RecompileBudgetExceeded(
                    site, now, stats["budget"])
            return out

        wrapped.__name__ = getattr(fn, "__name__", site)
        wrapped._jitwatch_site = site
        return wrapped

    # ------------------------------------------------- log_compiles hook

    def start_logs(self) -> None:
        """Hook ``jax.log_compiles``: flip the config flag and attach a
        counting handler to the ``jax`` logger — the global view (and
        the _cache_size fallback)."""
        import jax

        if self._log_counter is not None:
            return
        self._log_counter = _LogCounter()
        logger = logging.getLogger("jax")
        logger.addHandler(self._log_counter)
        self._saved_level = logger.level
        if logger.level > logging.WARNING or logger.level == 0:
            logger.setLevel(logging.WARNING)
        self._saved_log_compiles = jax.config.jax_log_compiles
        jax.config.update("jax_log_compiles", True)

    def stop_logs(self) -> None:
        import jax

        if self._log_counter is None:
            return
        logger = logging.getLogger("jax")
        logger.removeHandler(self._log_counter)
        logger.setLevel(self._saved_level)
        jax.config.update("jax_log_compiles",
                          bool(self._saved_log_compiles))
        self._log_counter = None

    def compile_counts(self) -> dict:
        """{function name: compile events} from the log stream (the
        human-readable view; the wrap fallback windows the TOTAL)."""
        return dict(self._log_counter.counts) if self._log_counter else {}

    # -------------------------------------------------------- reporting

    def snapshot(self) -> dict:
        return {site: dict(st) for site, st in self.sites.items()}

    def over_budget(self) -> list:
        """Sites where some single wrapper out-compiled its budget
        (the per-wrapper semantics the raise enforces; "compiles" in
        the snapshot is the cumulative cross-wrapper total)."""
        return [site for site, st in self.sites.items()
                if st["wrapper_max"] > st["budget"]]


# --------------------------------------------------------- installation

_GLOBAL: JitWatch | None = None


def enabled() -> bool:
    return bool(os.environ.get("JAXLINT_JITWATCH"))


def active() -> JitWatch | None:
    return _GLOBAL


def install(budget: int | None = None) -> JitWatch:
    """Create (or return) the process-global watch. Idempotent — but an
    EXPLICIT budget always takes effect for subsequent wraps, even when
    a watch already exists (an earlier maybe_wrap may have created it
    with the default; silently keeping that would enforce a budget the
    caller never asked for). Sites already wrapped keep the budget they
    were wrapped with."""
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = JitWatch(budget=budget)
    elif budget is not None:
        _GLOBAL.budget = budget
    return _GLOBAL


def uninstall() -> None:
    global _GLOBAL
    if _GLOBAL is not None:
        _GLOBAL.stop_logs()
    _GLOBAL = None


def maybe_wrap(fn, site: str, budget: int | None = None):
    """The production seam (train/loop.py): identity when
    JAXLINT_JITWATCH is unset — one env read at wrap time, zero
    per-call cost — else wrap under the global watch."""
    if not enabled():
        return fn
    return install().wrap(fn, site=site, budget=budget)
