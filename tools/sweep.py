"""Perf sweep for the MFU search (VERDICT r4 #2): runs bench.py's child
across {remat_policy × loss_chunk × batch × mu/param dtype} points and
prints one result line per point plus the best configuration.

Usage (on the TPU box):
    python tools/sweep.py                 # default grid, bench_800m
    python tools/sweep.py --preset bench_400m --points quick

Each point runs in a fresh subprocess (the TPU runtime wants one client,
and a crashed point must not take the sweep down). Results also land in
SWEEP.json for BASELINE.md.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import pathlib
import subprocess
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent

# (remat_policy, loss_chunk, batch, mu_dtype, param_dtype, grad_accum)
GRIDS = {
    # the axes most likely to move MFU, one at a time from the r4 baseline
    "quick": [
        ("full", 512, 8, "", "", 1),         # r5 default (chunked CE)
        ("full", 0, 8, "", "", 1),           # r4 baseline control
        ("full", 512, 12, "", "", 1),        # bigger batch w/ freed HBM
        ("full", 512, 16, "", "", 1),
        ("full", 512, 8, "bfloat16", "", 1),  # lean first moment
        ("dots_saveable", 512, 4, "bfloat16", "bfloat16", 1),  # no-recompute
        ("dots_saveable", 512, 8, "bfloat16", "bfloat16", 1),
        # grad accumulation: micro-batch activations pay for the lighter
        # remat policy at full global batch
        ("dots_saveable", 512, 16, "bfloat16", "", 2),
        ("dots_saveable", 512, 16, "bfloat16", "", 4),
    ],
    "full": [
        (rp, lc, b, mu, pd, ga)
        for rp in ("full", "dots_saveable")
        for lc in (0, 256, 512, 1024)
        for b in (8, 12, 16)
        for mu in ("", "bfloat16")
        for pd in ("",)
        for ga in (1, 2)
    ],
}


def run_point(preset, rp, lc, batch, mu, pd, ga, timeout):
    env = dict(
        os.environ,
        SATPU_BENCH_CHILD="1",
        SATPU_BENCH_PRESET=preset,
        SATPU_BENCH_MATRIX="0",
        # never let a previously committed SWEEP.json winner leak into
        # the grid points (float32 rows leave the dtype envs unset)
        SATPU_BENCH_SWEEPING="1",
        SATPU_BENCH_REMAT_POLICY=rp,
        SATPU_BENCH_LOSS_CHUNK=str(lc),
        SATPU_BENCH_BATCH=str(batch),
        SATPU_BENCH_GRAD_ACCUM=str(ga),
    )
    if mu:
        env["SATPU_BENCH_MU_DTYPE"] = mu
    if pd:
        env["SATPU_BENCH_PARAM_DTYPE"] = pd
    try:
        proc = subprocess.run(
            [sys.executable, str(ROOT / "bench.py")],
            env=env, cwd=ROOT, capture_output=True, text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return {"error": "timeout"}
    if proc.returncode != 0:
        return {"error": (proc.stderr or proc.stdout)[-300:]}
    lines = [l for l in proc.stdout.splitlines() if l.lstrip().startswith("{")]
    return json.loads(lines[-1]) if lines else {"error": "no output"}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="bench_800m")
    ap.add_argument("--points", default="quick", choices=sorted(GRIDS))
    ap.add_argument("--timeout", type=float, default=900.0)
    args = ap.parse_args()

    results = []
    for rp, lc, batch, mu, pd, ga in GRIDS[args.points]:
        tag = (f"remat={rp} chunk={lc} b={batch} "
               f"mu={mu or 'f32'} pdt={pd or 'f32'} ga={ga}")
        out = run_point(args.preset, rp, lc, batch, mu, pd, ga,
                        args.timeout)
        row = {"remat": rp, "loss_chunk": lc, "batch": batch,
               "mu_dtype": mu or "float32",
               "param_dtype": pd or "float32", "grad_accum": ga, **out}
        results.append(row)
        if "error" in out:
            print(f"{tag:55s} ERROR {out['error'][:80]}")
        else:
            print(f"{tag:55s} {out['value']:>9.1f} tok/s  "
                  f"mfu={out['mfu']:.4f}")
    ok = [r for r in results if "mfu" in r]
    if ok:
        best = max(ok, key=lambda r: r["mfu"])
        print(f"\nbest: mfu={best['mfu']:.4f} "
              f"remat={best['remat']} chunk={best['loss_chunk']} "
              f"b={best['batch']} mu={best['mu_dtype']} "
              f"pdt={best['param_dtype']} ga={best['grad_accum']}")
    (ROOT / "SWEEP.json").write_text(json.dumps(
        {"preset": args.preset, "results": results}, indent=1))
    print(f"wrote {ROOT / 'SWEEP.json'}")


if __name__ == "__main__":
    main()
