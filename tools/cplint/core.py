"""Shared cplint infrastructure: file discovery, suppressions, findings.

Every pass is a module exposing ``NAME`` (the suppression handle),
``DESCRIPTION`` and ``run(ctx) -> list[Finding]``. The context owns the
parsed-AST cache so five passes cost one parse per file, and the
suppression index so ``# cplint: disable=<pass>`` comments are honored
uniformly (same line or the line above; a file-level
``# cplint: disable-file=<pass>`` in the first 20 lines silences the
pass for the whole file — every suppression is expected to carry a
justification after the pass name).
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re

REPO = pathlib.Path(__file__).resolve().parent.parent.parent

#: scan roots for the control-plane passes — the ONE place the package
#: path lives (lock-discipline/cache-mutation/queue-span/clock-injection
#: all import this as their SCOPE)
CONTROLPLANE = (
    "service_account_auth_improvements_tpu/controlplane",
)

#: pass names are bare kebab-case tokens; the list ends at the first
#: token not joined by a comma, so free-text justification after the
#: names ("— handed off, all closers run in the worker") can never be
#: mis-read as more pass names
_NAMES = r"[A-Za-z0-9_-]+(?:\s*,\s*[A-Za-z0-9_-]+)*"


def suppression_res(tool: str) -> tuple:
    """(line-disable, file-disable) regexes for one analyzer's comment
    namespace — cplint and jaxlint share the suppression machinery but
    read disjoint ``# <tool>: disable=`` comments, so silencing a
    control-plane pass can never accidentally silence a numerics pass."""
    return (
        re.compile(r"#\s*" + tool + r":\s*disable=(" + _NAMES + ")"),
        re.compile(r"#\s*" + tool + r":\s*disable-file=(" + _NAMES + ")"),
    )


_DISABLE_RE, _DISABLE_FILE_RE = suppression_res("cplint")


@dataclasses.dataclass
class Finding:
    pass_name: str
    path: str          # repo-relative, posix
    line: int
    message: str
    severity: str = "error"
    suppressed: bool = False

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}: [{self.pass_name}] " \
               f"{self.message}{tag}"

    def to_dict(self) -> dict:
        return {
            "pass": self.pass_name,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "severity": self.severity,
            "suppressed": self.suppressed,
        }


@dataclasses.dataclass
class Suppressions:
    #: line number -> set of pass names disabled on that line
    lines: dict
    #: pass names disabled for the whole file
    file_level: set

    def covers(self, pass_name: str, line: int) -> bool:
        if pass_name in self.file_level or "all" in self.file_level:
            return True
        for candidate in (line, line - 1):
            names = self.lines.get(candidate)
            if names and (pass_name in names or "all" in names):
                return True
        return False


def load_suppressions(source: str, tool: str = "cplint") -> Suppressions:
    lines: dict = {}
    file_level: set = set()
    # re.compile results are cached by the re module, so deriving the
    # pair per call costs nothing and keeps ONE pattern definition
    disable_re, disable_file_re = suppression_res(tool)
    def names_in(spec: str):
        # the regex already guarantees a comma-separated token list
        return {chunk.strip() for chunk in spec.split(",")
                if chunk.strip()}

    for i, raw in enumerate(source.splitlines(), 1):
        m = disable_re.search(raw)
        if m:
            lines.setdefault(i, set()).update(names_in(m.group(1)))
        if i <= 20:
            fm = disable_file_re.search(raw)
            if fm:
                file_level.update(names_in(fm.group(1)))
    return Suppressions(lines=lines, file_level=file_level)


class PassContext:
    """Parsed-module cache + suppression index shared across passes.

    ``tool`` names the suppression-comment namespace this context reads
    (``# <tool>: disable=<pass>``); jaxlint constructs the same context
    with ``tool="jaxlint"``.
    """

    def __init__(self, repo: pathlib.Path | None = None,
                 tool: str = "cplint"):
        self.repo = pathlib.Path(repo) if repo else REPO
        self.tool = tool
        self._parsed: dict = {}   # path -> (tree, source) | None
        self._suppr: dict = {}    # path -> Suppressions

    # ------------------------------------------------------------ files

    def files(self, *roots: str) -> list[pathlib.Path]:
        """Python files under the given repo-relative roots, sorted;
        __pycache__ and the cplint fixture corpus are skipped."""
        out: list[pathlib.Path] = []
        for root in roots:
            base = self.repo / root
            if base.is_file():
                out.append(base)
                continue
            for p in sorted(base.rglob("*.py")):
                if "__pycache__" in p.parts:
                    continue
                out.append(p)
        return out

    def parse(self, path: pathlib.Path):
        """(tree, source) for one file, or None when unparseable —
        passes report unparseable files once via :meth:`parse_findings`."""
        key = str(path)
        if key not in self._parsed:
            try:
                source = path.read_text()
                tree = ast.parse(source, filename=str(path))
                self._parsed[key] = (tree, source)
                self._suppr[key] = load_suppressions(source, self.tool)
            except (OSError, SyntaxError):
                self._parsed[key] = None
        return self._parsed[key]

    def rel(self, path: pathlib.Path) -> str:
        try:
            return path.relative_to(self.repo).as_posix()
        except ValueError:
            return path.as_posix()

    # ------------------------------------------------------ suppressions

    def finding(self, pass_name: str, path: pathlib.Path, line: int,
                message: str) -> Finding:
        """Build a Finding, marking it suppressed when the source carries
        a matching ``# cplint: disable=`` comment."""
        suppr = self._suppr.get(str(path))
        suppressed = bool(suppr and suppr.covers(pass_name, line))
        return Finding(pass_name=pass_name, path=self.rel(path),
                       line=line, message=message, suppressed=suppressed)


def run_passes(passes, ctx: PassContext | None = None,
               only: set | None = None) -> list[Finding]:
    ctx = ctx or PassContext()
    findings: list[Finding] = []
    for mod in passes:
        if only and mod.NAME not in only:
            continue
        findings.extend(mod.run(ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.pass_name))
    return findings


def report_dict(findings, passes, schema: str = "cplint/v1") -> dict:
    """The SARIF-ish JSON record: CI uploads it ``if: always()`` and
    ``tools/bench_gate.py --lint-report`` asserts errors == 0."""
    active = [f for f in findings if not f.suppressed]
    return {
        "schema": schema,
        "ok": not active,
        "counts": {
            "errors": len(active),
            "suppressed": len(findings) - len(active),
        },
        "passes": [
            {"name": p.NAME, "description": p.DESCRIPTION}
            for p in passes
        ],
        "findings": [f.to_dict() for f in findings],
    }
