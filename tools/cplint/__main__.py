"""CLI: ``python -m tools.cplint`` — run every pass, print findings,
exit nonzero on unsuppressed errors.

    python -m tools.cplint                      # all passes
    python -m tools.cplint --pass lock-discipline --pass rbac-check
    python -m tools.cplint --json cplint_report.json   # CI record
    python -m tools.cplint --list-passes        # machine-readable catalog
"""

from __future__ import annotations

import argparse
import json
import sys

from tools.cplint.core import PassContext, report_dict, run_passes
from tools.cplint.passes import ALL_PASSES


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.cplint",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("--pass", dest="passes", action="append",
                    metavar="NAME",
                    help="run only the named pass (repeatable); "
                         "names: " + ", ".join(p.NAME for p in ALL_PASSES))
    ap.add_argument("--list-passes", action="store_true",
                    help="print the pass catalog as JSON to stdout and "
                         "exit (CI/pre-commit discover fast subsets "
                         "from this instead of hardcoding names)")
    ap.add_argument("--json", dest="json_out", metavar="PATH",
                    help="write the SARIF-ish JSON report "
                         "(bench_gate --lint-report asserts it clean)")
    ap.add_argument("--repo", default=None,
                    help="repo root override (tests)")
    args = ap.parse_args(argv)

    if args.list_passes:
        print(json.dumps({
            "schema": "cplint-passes/v1",
            "passes": [{"name": p.NAME, "description": p.DESCRIPTION}
                       for p in ALL_PASSES],
        }, indent=2))
        return 0

    known = {p.NAME for p in ALL_PASSES}
    only = set(args.passes or ())
    unknown = only - known
    if unknown:
        ap.error(f"unknown pass(es): {', '.join(sorted(unknown))}")

    ctx = PassContext(repo=args.repo)
    findings = run_passes(ALL_PASSES, ctx, only=only or None)
    report = report_dict(findings, ALL_PASSES)
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(report, f, indent=2)
    for finding in findings:
        print(finding.format(), file=sys.stderr)
    counts = report["counts"]
    print(
        f"cplint: {counts['errors']} finding(s), "
        f"{counts['suppressed']} suppressed",
        file=sys.stderr,
    )
    return 1 if counts["errors"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
