"""RBAC cross-check configuration: which sources run under which Role.

Each controller binary (cmd/*.py) runs one ServiceAccount whose
ClusterRole lives under manifests/controllers/<name>/rbac.yaml. The
static pass extracts every ``(group, resource, verb)`` a binary's
modules can issue and diffs against the parsed rules — in BOTH
directions. This map is the binary→sources join the AST can't see
(imports are conditional: culling/tpusched ride ENABLE_* flags but
still need their verbs granted for when the flag is on).

``ALLOWED_EXTRA`` lists grants that are intentionally broader than the
statically-visible call graph; every entry carries its justification
and is reported as covered, never as dead.
"""

from __future__ import annotations

CP = "service_account_auth_improvements_tpu/controlplane"

#: role name -> (manifest path, module paths whose client calls run
#: under that role's ServiceAccount)
ROLES = {
    "notebook-controller": {
        "manifest": "manifests/controllers/notebook/rbac.yaml",
        "sources": (
            f"{CP}/controllers/notebook.py",
            f"{CP}/controllers/culling.py",       # ENABLE_CULLING
            f"{CP}/scheduler",                    # ENABLE_SCHEDULER
            f"{CP}/obs/events.py",                # EventRecorder verbs
            f"{CP}/engine/leaderelection.py",     # --leader-elect
            f"{CP}/engine/shard.py",               # --shard (cpshard HA)
        ),
    },
    "profile-controller": {
        "manifest": "manifests/controllers/profile/rbac.yaml",
        "sources": (
            f"{CP}/controllers/profile.py",
            f"{CP}/obs/events.py",                # EventRecorder verbs
            f"{CP}/engine/leaderelection.py",
            f"{CP}/engine/shard.py",               # --shard (cpshard HA)
        ),
    },
    "tensorboard-controller": {
        "manifest": "manifests/controllers/tensorboard/rbac.yaml",
        "sources": (
            f"{CP}/controllers/tensorboard.py",
            f"{CP}/obs/events.py",
            f"{CP}/engine/leaderelection.py",
            f"{CP}/engine/shard.py",               # --shard (cpshard HA)
        ),
    },
    "pvcviewer-controller": {
        "manifest": "manifests/controllers/pvcviewer/rbac.yaml",
        "sources": (
            f"{CP}/controllers/pvcviewer.py",
            f"{CP}/obs/events.py",
            f"{CP}/engine/leaderelection.py",
            f"{CP}/engine/shard.py",               # --shard (cpshard HA)
        ),
    },
}

#: (role, group, resource, verb) -> justification. These grants exceed
#: what the AST can prove is used; each one says why it stays.
ALLOWED_EXTRA = {
    # Finalizer mutation rides kube.update("profiles") in this
    # implementation, but a real apiserver checks the /finalizers
    # subresource whenever ownerReferences carry
    # blockOwnerDeletion=true on children the controller creates —
    # dropping it would break owner-cascade setup on a conformant
    # cluster even though no call site names it.
    ("profile-controller", "tpukf.dev", "profiles/finalizers", "update"):
        "blockOwnerDeletion on owned children needs /finalizers update "
        "on a real apiserver (OwnerReferencesPermissionEnforcement)",
}
