"""cplint: control-plane invariant analyzer.

Repo-specific static analysis over the controlplane package — the
invariants PR 5 (cached reads), PR 3 (tracing) and PR 6 (chaos) rely on
are enforced by machine, not by whichever test happens to exercise the
path. See docs/cplint.md for the pass catalog and suppression policy.

Entry points:

- ``python -m tools.cplint`` — run every pass, print findings, exit
  nonzero on any unsuppressed error (``--json report.json`` writes the
  SARIF-ish record CI uploads and ``bench_gate --lint-report`` asserts
  against).
- :mod:`tools.cplint.lockwatch` — the dynamic half: instrumented locks
  recording the per-thread acquisition graph during tier-1 tests
  (``CPLINT_LOCKWATCH=1``), failing on lock-order cycles and held-lock
  apiserver writes.
"""

from tools.cplint.core import (  # noqa: F401
    Finding,
    PassContext,
    load_suppressions,
    run_passes,
    report_dict,
)
from tools.cplint.passes import ALL_PASSES  # noqa: F401
