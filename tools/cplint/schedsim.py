"""schedsim: deterministic-interleaving model checker for the plane's
consensus protocols.

The static passes prove shape; lockwatch catches whatever interleaving
the OS scheduler happens to produce. This module closes the remaining
gap: it runs *small-scope models* of the consensus-critical code — the
cpshard handoff ack-barrier (engine/shard.py), leader-election expiry
under skew (engine/leaderelection.py), FakeKube's MVCC optimistic
commits (kube/fake.py), the workqueue get→done contract
(engine/queue.py), the park→release→resume→re-admit protocol
(controlplane/parking + controllers/culling.py, driven against the
real CullingReconciler), and the autoscaler's scale-down
drain-then-leave ordering racing a shard handoff
(engine/autoscale.py, driven through the real ReplicaAutoscaler) —
under a **cooperative scheduler** that serializes
the model's threads at instrumented sync points and *enumerates* their
interleavings:

- **sync points** come from three instrumented layers, all zero-cost in
  production: explicit ``controlplane/syncpoint.py`` calls at protocol
  transitions (the optimistic-commit window, queue transitions, shard
  handoff phases, lease acquire), the lockwatch lock wrappers (so a
  lock held by a *suspended* model thread parks the acquirer instead of
  wedging the harness — and a real A→B/B→A inversion surfaces as a
  detected deadlock), and the FakeKube ``_count`` choke point (every
  apiserver verb is a potential preemption).
- **exploration** is replay-based DFS with sleep-set partial-order
  reduction (alternatives whose next operation commutes with the chosen
  one are pruned — DPOR-style: one representative per Mazurkiewicz
  trace) and CHESS-style preemption bounding, under a schedule budget
  and wall deadline. Model threads otherwise run to their next block
  point, so the default schedule is the cheap one and every preemption
  is an explicit, replayable choice.
- **violations** — a dual reconcile recorded by the model's ledger, a
  lost update, an illegal lease takeover, a dropped level-triggered
  re-add, a deadlock, a wedged barrier — dump a replayable schedule
  (the exact choice list) as JSON; ``--replay`` re-runs that exact
  interleaving, and tests/test_schedsim.py replays dumps as failing
  tests.
- **mutation validation** (``--mutations``): ~14 hand-seeded protocol
  bugs (drop the ack barrier, ack before drain, skip self-fence,
  activate through a stale post-fence map, ignore lease skew bounds,
  steal held leases, drop the MVCC commit identity check, emit DELETED
  at the stale RV, drop the dirty re-add, skip processing
  registration, stop a parking notebook before its checkpoint commits,
  stamp a never-committed checkpoint ref, drop the resume-wins park
  cancellation, leave the membership before the scale-down drain)
  each applied as a runtime patch; every one must be
  caught by the explorer within the CI budget, and clean HEAD must
  explore violation-free. A checker that cannot catch a seeded
  regression of a bug this repo already fixed once guards nothing.

CLI::

    python -m tools.cplint.schedsim                  # clean-HEAD gate
    python -m tools.cplint.schedsim --mutations      # mutant suite
    python -m tools.cplint.schedsim --model mvcc_update --budget 500
    python -m tools.cplint.schedsim --replay schedsim_out/fail_0.json
    python -m tools.cplint.schedsim --list-models --list-sync-points

docs/cplint.md "Schedule exploration" is the operator's guide.
"""

from __future__ import annotations

import argparse
import contextlib
import datetime
import heapq
import json
import pathlib
import random
import sys
import tempfile
import threading
import time

REPO = pathlib.Path(__file__).resolve().parent.parent.parent
if str(REPO) not in sys.path:  # pragma: no cover - direct invocation
    sys.path.insert(0, str(REPO))

from service_account_auth_improvements_tpu.controlplane import (  # noqa: E402,E501
    parking,
    syncpoint,
    tpu as tpu_mod,
)
from service_account_auth_improvements_tpu.controlplane.controllers.culling import (  # noqa: E402,E501
    CullingReconciler,
)
from service_account_auth_improvements_tpu.controlplane.controllers.notebook import (  # noqa: E402,E501
    STOP_ANNOTATION,
)
from service_account_auth_improvements_tpu.controlplane.engine import (  # noqa: E402,E501
    Request,
    Result,
)
from service_account_auth_improvements_tpu.controlplane.engine import (  # noqa: E402,E501
    autoscale as autoscale_mod,
)
from service_account_auth_improvements_tpu.controlplane.engine.autoscale import (  # noqa: E402,E501
    AutoscaleConfig,
    ReplicaAutoscaler,
)
from service_account_auth_improvements_tpu.controlplane.engine import (  # noqa: E402,E501
    leaderelection,
)
from service_account_auth_improvements_tpu.controlplane.engine.leaderelection import (  # noqa: E402,E501
    LEASE_GROUP,
    LeaderElector,
    renew_stale as _pristine_renew_stale,
)
from service_account_auth_improvements_tpu.controlplane.engine.queue import (  # noqa: E402,E501
    RateLimitingQueue,
)
from service_account_auth_improvements_tpu.controlplane.engine.shard import (  # noqa: E402,E501
    ANN_ACKED,
    ANN_EPOCH,
    ANN_MAP,
    ANN_MEMBERS,
    ANN_SHARDS,
    FOREIGN,
    HOLD,
    OWN,
    ShardMember,
    shard_of,
)
from service_account_auth_improvements_tpu.controlplane.kube import (  # noqa: E402,E501
    errors,
)
from service_account_auth_improvements_tpu.controlplane.kube.fake import (  # noqa: E402,E501
    FakeKube,
)
from service_account_auth_improvements_tpu.controlplane.obs.journal import (  # noqa: E402,E501
    Journal,
)
from tools.cplint import lockwatch  # noqa: E402

GROUP = "tpukf.dev"

#: the sync-point inventory the explorer serializes on — kept in ONE
#: place so docs, --list-sync-points, and the instrumented modules can
#: be diffed (tests assert each label resolves to a real syncpoint.sync
#: call in its module). The three new static passes analyze exactly the
#: regions between these points: blocking-under-lock walks the lock
#: sites lockwatch instruments, mvcc-escape the commit points, and
#: check-then-act the read→write windows the "fake.commit" point lets
#: this explorer preempt inside.
SYNC_POINTS = {
    "fake.commit": "kube/fake.py — the optimistic-commit window "
                   "(successor built lock-free from the current object; "
                   "a racing commit must force a recompute)",
    "queue.add": "engine/queue.py — key becomes pending (or dirty)",
    "queue.get": "engine/queue.py — worker pickup, key → _processing",
    "queue.done": "engine/queue.py — key released; dirty re-adds "
                  "re-level here",
    "queue.discard": "engine/queue.py — shard handoff backlog prune",
    "shard.heartbeat": "engine/shard.py — member Lease renew carrying "
                       "the acked epoch",
    "shard.read_map": "engine/shard.py — map Lease poll / epoch apply",
    "shard.barrier": "engine/shard.py — gained-shard activation "
                     "barrier (every live fellow member acked)",
    "shard.ack": "engine/shard.py — drain-then-ack of a lost epoch",
    "lease.try_acquire": "engine/leaderelection.py — one acquire/renew "
                         "attempt against the Lease",
}


class Violation(AssertionError):
    """A model invariant failed under the explored interleaving."""


class _Abort(BaseException):
    """Internal: unwind a suspended model thread during teardown."""


# =====================================================================
# virtual clock + ledger
# =====================================================================

_EPOCH0 = datetime.datetime(2026, 1, 1, tzinfo=datetime.timezone.utc)


class VClock:
    """Deterministic wall+mono clock pair for the protocol models —
    time only moves when a scripted step advances it."""

    def __init__(self):
        self.t = 0.0

    def now(self) -> datetime.datetime:
        return _EPOCH0 + datetime.timedelta(seconds=self.t)

    def mono(self) -> float:
        return self.t

    def advance(self, seconds: float) -> None:
        self.t += seconds


class Ledger:
    """The dual-reconcile detector (the PR 12 ha bench ledger, reduced
    to model scale): enter/exit around each model reconcile; two actors
    inside the same unit concurrently is the violation the shard
    protocol exists to prevent. Single-threaded by construction — only
    one model thread runs at a time."""

    def __init__(self):
        self._inflight: dict = {}     # unit -> set of actors
        self.violations: list[str] = []

    def enter(self, actor: str, unit) -> None:
        cur = self._inflight.setdefault(unit, set())
        if cur:
            self.violations.append(
                f"dual reconcile of {unit!r}: {actor} overlaps "
                f"{sorted(cur)}"
            )
        cur.add(actor)

    def exit(self, actor: str, unit) -> None:
        self._inflight.get(unit, set()).discard(actor)

    def busy(self, actor: str, units=None) -> bool:
        for unit, actors in self._inflight.items():
            if actor in actors and (units is None or unit in units):
                return True
        return False


# =====================================================================
# the cooperative scheduler
# =====================================================================

class _Member:
    __slots__ = ("name", "fn", "thread", "gate", "state", "op", "pred",
                 "blocked", "error")

    def __init__(self, name, fn):
        self.name = name
        self.fn = fn
        self.thread = None
        self.gate = threading.Event()
        self.state = "new"   # new|ready|running|lockwait|condwait|done
        self.op = None       # (label, resource, kind)
        self.pred = None
        self.blocked = None
        self.error = None


_ACTIVE: "SchedSim | None" = None


def step(label: str, detail=None) -> None:
    """Model-script yield point (``sync:model.<label>``). No-op outside
    a schedsim run, so model bodies are plain callable code."""
    syncpoint.sync("model." + label, detail)


def wait_until(pred, label: str = "cond", timeout: float = 5.0) -> None:
    """Park the calling model thread until ``pred()`` is true (the
    scheduler re-evaluates at every decision). Off a model thread this
    degrades to a real-time spin so model setup code can reuse it."""
    sim = _ACTIVE
    if sim is not None:
        me = sim._me()
        if me is not None:
            # resource None = conflicts with everything: the predicate
            # reads state written by plain model code between other
            # threads' ops, which the resource relation cannot see —
            # never prune around a wait
            sim._park(me, "condwait",
                      op=("wait:" + label, None, "read"),
                      pred=pred)
            return
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            raise Violation(f"wait_until({label}) timed out off-sim")
        time.sleep(0.001)


class SchedSim:
    """One deterministic run: model threads execute one at a time; the
    scheduler picks who advances at each instrumented sync point, from
    a replayable ``choices`` prefix and a default policy after it
    (``block``: run-to-block DFS default; ``rr``: fair round-robin for
    progress checks)."""

    #: real-time ceiling for one model thread to reach its next sync
    #: point — model code is in-memory work; anything longer is a hung
    #: harness, not a slow model
    HANG_TIMEOUT_S = 20.0

    def __init__(self, threads, yield_on=None, choices=(),
                 max_decisions: int = 2000, policy: str = "block",
                 priorities: dict | None = None,
                 change_points: set | None = None):
        self._members = [_Member(name, fn) for name, fn in threads]
        self._cv = threading.Condition()
        self._tls = threading.local()
        self._filter = yield_on
        self._choices = list(choices)
        self._max_decisions = max_decisions
        self._policy = policy
        #: PCT mode: rank per thread name (higher runs first) + the
        #: decision indices where the current top enabled thread is
        #: demoted below everyone — one demotion per change point is
        #: exactly the PCT "d preemption points" schedule family
        self._prio = dict(priorities or {})
        self._changes = set(change_points or ())
        self._last: _Member | None = None
        self._aborting = False
        self.decisions: list[dict] = []
        self.violation: dict | None = None

    # ----------------------------------------------- model-thread side

    def _me(self) -> _Member | None:
        return getattr(self._tls, "member", None)

    def _park(self, member: _Member, state: str, op=None, pred=None,
              blocked=None) -> None:
        with self._cv:
            member.state = state
            if op is not None:
                member.op = op
            member.pred = pred
            member.blocked = blocked
            self._cv.notify_all()
        member.gate.wait()
        member.gate.clear()
        if self._aborting:
            raise _Abort()

    def _op(self, label: str, resource, kind: str) -> None:
        m = self._me()
        if m is None or self._aborting:
            return
        if self._filter is not None and not self._filter(label):
            return
        self._park(m, "ready", op=(label, resource, kind))

    # --- hook surface (syncpoint / lockwatch / FakeKube._count) ---

    def sync_hook(self, label: str, detail=None) -> None:
        # resource is the LABEL alone: two members' "lease.try_acquire"
        # points must conflict (both touch the lease) even though their
        # details differ — conflict resources may over-approximate,
        # never under-approximate, or the reduction prunes real
        # interleavings and the explorer goes blind
        self._op("sync:" + label, ("sync", label), "write")

    def api_call(self, verb: str, plural) -> None:
        kind = "read" if verb in ("get", "list", "watch") else "write"
        self._op(f"kube:{verb}:{plural}", ("kube", plural), kind)

    def lock_acquire(self, site: str, inner):
        """lockwatch wrapper entry for blocking acquires: None off
        model threads (caller does the real acquire); True once the
        scheduler let this model thread take the lock. A lock held by a
        suspended model thread parks the acquirer (``lockwait``) until
        its release — the harness can never wedge on a real lock, and
        an inversion becomes a detected deadlock instead of a hang."""
        m = self._me()
        if m is None or self._aborting:
            return None
        label = "lock:" + site
        if self._filter is None or self._filter(label):
            self._park(m, "ready", op=(label, ("lock", site), "lock"))
        while True:
            if inner.acquire(False):
                return True
            self._park(m, "lockwait",
                       op=("lockwait:" + site, ("lock", site), "lock"),
                       blocked=id(inner))

    def lock_release(self, site: str, inner) -> None:
        m = self._me()
        if m is None:
            return
        with self._cv:
            for o in self._members:
                if o.state == "lockwait" and o.blocked == id(inner):
                    o.state = "ready"
                    o.blocked = None

    # ------------------------------------------------- scheduler side

    def _bootstrap(self, member: _Member) -> None:
        self._tls.member = member
        try:
            # initial park: the explorer controls start order too
            self._park(member, "ready",
                       op=("start:" + member.name,
                           ("start", member.name), "read"))
            member.fn()
        except _Abort:
            pass
        except BaseException as e:  # noqa: BLE001 — recorded as evidence
            member.error = e
        finally:
            with self._cv:
                member.state = "done"
                self._cv.notify_all()

    def _pick_default(self, ready: list) -> _Member:
        if self._policy == "rr":
            order = self._members
            start = (order.index(self._last) + 1
                     if self._last in order else 0)
            for i in range(len(order)):
                cand = order[(start + i) % len(order)]
                if cand in ready:
                    return cand
        if self._policy == "pct":
            idx = len(self.decisions)
            top = max(ready, key=lambda m: self._prio.get(m.name, 0))
            if idx in self._changes:
                floor = min(self._prio.values(), default=0) - 1
                self._prio[top.name] = floor
                top = max(ready,
                          key=lambda m: self._prio.get(m.name, 0))
            return top
        if self._last is not None and self._last in ready:
            return self._last
        return ready[0]

    def run(self) -> "SchedSim":
        for m in self._members:
            m.thread = threading.Thread(
                target=self._bootstrap, args=(m,),
                name=f"schedsim-{m.name}", daemon=True,
            )
            m.thread.start()
        try:
            while True:
                chosen = None
                with self._cv:
                    deadline = time.monotonic() + self.HANG_TIMEOUT_S
                    while any(m.state in ("new", "running")
                              for m in self._members):
                        self._cv.wait(timeout=0.5)
                        if time.monotonic() > deadline:
                            self.violation = {
                                "kind": "hung-thread",
                                "threads": [m.name for m in self._members
                                            if m.state in ("new",
                                                           "running")],
                            }
                            break
                    if self.violation is not None:
                        break
                    for m in self._members:
                        if m.state == "condwait":
                            try:
                                if m.pred():
                                    m.state = "ready"
                                    m.pred = None
                            except Exception as e:  # noqa: BLE001
                                # record the broken predicate but leave
                                # the member PARKED (not "done"): its
                                # thread is still in gate.wait, and only
                                # _abort_all's gate.set can unwind it —
                                # marking it done here would leak the
                                # thread past teardown
                                m.error = e
                    if any(m.error is not None for m in self._members):
                        break   # recorded below; abort the rest
                    ready = [m for m in self._members
                             if m.state == "ready"]
                    if not ready:
                        parked = [m for m in self._members
                                  if m.state in ("lockwait", "condwait")]
                        if parked:
                            self.violation = {
                                "kind": "deadlock",
                                "threads": {
                                    m.name: (m.op[0] if m.op else "?")
                                    for m in parked
                                },
                            }
                        break
                    if len(self.decisions) >= self._max_decisions:
                        self.violation = {
                            "kind": "hang",
                            "detail": f"decision budget "
                                      f"{self._max_decisions} exhausted "
                                      "— the model never quiesced",
                        }
                        break
                    idx = len(self.decisions)
                    if idx < len(self._choices):
                        want = self._choices[idx]
                        chosen = next((m for m in ready
                                       if m.name == want), None)
                        if chosen is None:
                            self.violation = {
                                "kind": "replay-divergence",
                                "want": want,
                                "enabled": [m.name for m in ready],
                            }
                            break
                    else:
                        chosen = self._pick_default(ready)
                    prev = self._last
                    self.decisions.append({
                        "enabled": [m.name for m in ready],
                        "ops": {m.name: m.op for m in ready},
                        "chosen": chosen.name,
                        "prev": prev.name if prev else None,
                        "prev_enabled": bool(prev in ready),
                    })
                    self._last = chosen
                    chosen.state = "running"
                chosen.gate.set()
        finally:
            self._abort_all()
        if self.violation is None:
            for m in self._members:
                if m.error is not None:
                    assertion = isinstance(m.error,
                                           (Violation, AssertionError))
                    self.violation = {
                        "kind": "assertion" if assertion else "exception",
                        "thread": m.name,
                        "message": f"{type(m.error).__name__}: "
                                   f"{m.error}",
                    }
                    break
        return self

    def choices_taken(self) -> list[str]:
        return [d["chosen"] for d in self.decisions]

    def _abort_all(self) -> None:
        with self._cv:
            self._aborting = True
            for m in self._members:
                if m.state != "done":
                    m.gate.set()
        for m in self._members:
            if m.thread is not None:
                m.thread.join(timeout=2.0)


# =====================================================================
# running a model under the hooks
# =====================================================================

def _run_model(model, choices=(), policy: str = "block",
               priorities=None, change_points=None) -> SchedSim:
    """One scheduled run of a freshly-built model. Hooks are installed
    for the duration only; the scheduler runs on the calling thread."""
    global _ACTIVE
    lockwatch.hook_fake_count()
    sim = SchedSim(model.threads(), yield_on=model.yield_on,
                   choices=choices, max_decisions=model.max_decisions,
                   policy=policy, priorities=priorities,
                   change_points=change_points)
    syncpoint.install(sim.sync_hook)
    lockwatch.set_sched(sim)
    _ACTIVE = sim
    try:
        sim.run()
    finally:
        _ACTIVE = None
        lockwatch.set_sched(None)
        syncpoint.uninstall()
    if sim.violation is None:
        try:
            model.check()
        except (Violation, AssertionError) as e:
            sim.violation = {"kind": "check", "message": str(e)}
    return sim


def _conflicts(op_a, op_b) -> bool:
    """Dependence relation for the sleep-set reduction: two operations
    commute unless they touch the same resource with at least one
    writer (lock ops always conflict on their site)."""
    if op_a is None or op_b is None:
        return True   # unknown op: be conservative, never prune
    _, ra, ka = op_a
    _, rb, kb = op_b
    if ra is None or rb is None:
        return True
    if ra != rb:
        return False
    return not (ka == "read" and kb == "read")


def explore(model_factory, max_schedules: int = 400,
            preemption_bound: int = 2, deadline_s: float | None = None,
            stop_on_first: bool = True, seed: int = 0,
            dfs_share: float = 0.5) -> dict:
    """Two-phase schedule search. Phase 1: replay-based DFS with
    sleep-set partial-order reduction and preemption bounding — for the
    small models this is *exhaustive* within the bounds (the stack
    drains and the result is a proof over that space). Phase 2 (only
    when phase 1 exhausts its share of the budget without draining):
    seeded PCT-style sampling — random thread priorities with
    ``preemption_bound`` demotion points per run (Burckhardt et al.'s
    probabilistic concurrency testing), which reaches the
    few-specific-preemptions interleavings deep models hide far faster
    than systematic order. Deterministic for a given seed, and every
    violation carries the exact replayable choice list either way.

    Returns ``{"runs", "violations", "interrupted", "exhaustive"}`` —
    ``interrupted`` means the wall DEADLINE cut the search short (the
    operator should raise it); plain budget exhaustion is the normal
    bounded-search outcome and is reported as neither interrupted nor
    exhaustive."""
    t0 = time.monotonic()
    stack: list[tuple[tuple, frozenset]] = [((), frozenset())]
    runs = 0
    violations: list[dict] = []
    interrupted = False
    exhaustive = False
    dfs_budget = max(1, int(max_schedules * dfs_share))
    est_len = 20   # decision-count estimate for PCT change points
    while stack:
        if deadline_s is not None and \
                time.monotonic() - t0 > deadline_s:
            interrupted = True
            break
        if runs >= dfs_budget:
            break
        choices, sleep = stack.pop()
        model = model_factory()
        sim = _run_model(model, choices=choices)
        runs += 1
        est_len = max(est_len, len(sim.decisions))
        if sim.violation is not None:
            violations.append({
                "model": model.name,
                "choices": sim.choices_taken(),
                "violation": sim.violation,
            })
            if stop_on_first:
                break
            continue
        # ---- push unexplored alternatives (sleep sets + preemption
        # bound), walking the run from the first free decision on
        all_choices = sim.choices_taken()
        # cumulative preemption count per decision index
        pre = 0
        preempt_before = []
        for d in sim.decisions:
            preempt_before.append(pre)
            if d["prev_enabled"] and d["chosen"] != d["prev"]:
                pre += 1
        sleep_now = set(sleep)
        for i in range(len(choices), len(sim.decisions)):
            d = sim.decisions[i]
            ops = d["ops"]
            chosen = d["chosen"]
            sleep_now &= set(d["enabled"])
            pushed: list[str] = []
            for t in d["enabled"]:
                if t == chosen or t in sleep_now:
                    continue
                p = preempt_before[i] + (
                    1 if d["prev_enabled"] and t != d["prev"] else 0)
                if p > preemption_bound:
                    continue
                done_siblings = {chosen, *pushed}
                child_sleep = frozenset(
                    u for u in (sleep_now | done_siblings) - {t}
                    if u in ops and not _conflicts(ops[u], ops[t])
                )
                stack.append((tuple(all_choices[:i]) + (t,),
                              child_sleep))
                pushed.append(t)
            sleep_now = {u for u in sleep_now
                         if u in ops
                         and not _conflicts(ops[u], ops[chosen])}
    else:
        exhaustive = not violations or not stop_on_first
    # ---- phase 2: PCT sampling over the remaining budget
    if not exhaustive and not interrupted \
            and not (violations and stop_on_first):
        rng = random.Random(seed)
        names = [n for n, _ in model_factory().threads()]
        while runs < max_schedules:
            if deadline_s is not None and \
                    time.monotonic() - t0 > deadline_s:
                interrupted = True
                break
            prio = {n: i for i, n in enumerate(
                rng.sample(names, len(names)))}
            changes = {rng.randrange(max(est_len, 1))
                       for _ in range(preemption_bound)}
            model = model_factory()
            sim = _run_model(model, policy="pct", priorities=prio,
                             change_points=changes)
            runs += 1
            est_len = max(est_len, len(sim.decisions))
            if sim.violation is not None:
                violations.append({
                    "model": model.name,
                    "choices": sim.choices_taken(),
                    "violation": sim.violation,
                })
                if stop_on_first:
                    break
    return {"runs": runs, "violations": violations,
            "interrupted": interrupted, "exhaustive": exhaustive}


def fair_run(model_factory) -> SchedSim:
    """One round-robin-fair schedule — the progress/liveness check (a
    wedged barrier shows up here as a hang or a failed progress
    assertion, where the safety explorer cannot assert liveness
    per-interleaving)."""
    model = model_factory()
    sim = _run_model(model, policy="rr")
    if sim.violation is None:
        progress = getattr(model, "progress", None)
        if progress is not None:
            try:
                progress()
            except (Violation, AssertionError) as e:
                sim.violation = {"kind": "progress", "message": str(e)}
    return sim


# =====================================================================
# model helpers
# =====================================================================

def _key_in_shard(shard: int, num_shards: int,
                  ns: str = "ns") -> tuple[str, str]:
    i = 0
    while True:
        name = f"k{i}"
        if shard_of(ns, name, num_shards) == shard:
            return ns, name
        i += 1


def _write_map(kube, group: str, epoch: int, mapping: dict,
               members: list, num_shards: int,
               namespace: str = "kubeflow") -> None:
    """Publish a shard map Lease directly (the models script epochs —
    deterministic movement beats rendezvous for a small-scope model)."""
    ann = {
        ANN_EPOCH: str(epoch),
        ANN_MAP: json.dumps({str(s): o for s, o in mapping.items()},
                            sort_keys=True),
        ANN_MEMBERS: json.dumps(sorted(members)),
        ANN_SHARDS: str(num_shards),
    }
    name = f"{group}-map"
    body = {
        "apiVersion": f"{LEASE_GROUP}/v1",
        "kind": "Lease",
        "metadata": {"name": name, "namespace": namespace,
                     "annotations": ann},
        "spec": {"holderIdentity": "sim-coordinator"},
    }
    try:
        cur = kube.get("leases", name, namespace=namespace,
                       group=LEASE_GROUP)
    except errors.NotFound:
        kube.create("leases", body, namespace=namespace,
                    group=LEASE_GROUP)
        return
    body["metadata"]["resourceVersion"] = \
        cur["metadata"]["resourceVersion"]
    kube.update("leases", body, namespace=namespace, group=LEASE_GROUP)


def _yield_on_sync(label: str) -> bool:
    return label.startswith("sync:")


class _FlakyKube:
    """Per-member partition wrapper: fail this member's apiserver verbs
    while the scripted flags say it is cut off (heartbeat writes can
    heal separately from map reads — the partial-heal window the
    post-fence re-entry fix closed)."""

    def __init__(self, inner, flags: dict, map_name: str):
        self._inner = inner
        self._flags = flags
        self._map_name = map_name

    def _down(self, write: bool, name: str | None = None) -> bool:
        if not self._flags.get("partitioned"):
            return False
        if self._flags.get("heal_writes"):
            # heartbeats land again, but map READS still fail — the
            # stale-map window _map_confirmed guards
            return name == self._map_name
        return True

    def get(self, plural, name, **kw):
        if self._down(False, name):
            raise errors.ApiError("sim: partitioned")
        return self._inner.get(plural, name, **kw)

    def list(self, plural, **kw):
        if self._down(False):
            raise errors.ApiError("sim: partitioned")
        return self._inner.list(plural, **kw)

    def create(self, plural, obj, **kw):
        if self._down(True):
            raise errors.ApiError("sim: partitioned")
        return self._inner.create(plural, obj, **kw)

    def update(self, plural, obj, **kw):
        if self._down(True):
            raise errors.ApiError("sim: partitioned")
        return self._inner.update(plural, obj, **kw)

    def delete(self, plural, name, **kw):
        if self._down(True):
            raise errors.ApiError("sim: partitioned")
        return self._inner.delete(plural, name, **kw)


# =====================================================================
# the models
# =====================================================================

class ShardHandoffModel:
    """Two live members, a scripted coordinator moving one shard A→B,
    and a reconciler loop per member gated by ``admit()`` — the
    never-dual-reconcile core: B may not run a key until A drained and
    acked (or expired). The ledger records any overlap."""

    name = "shard_handoff"
    max_decisions = 2000
    preemption_bound = 2
    budget = 400

    NUM_SHARDS = 2

    def __init__(self):
        self.kube = FakeKube()
        self.clock = VClock()
        self.ledger = Ledger()
        self.group = "sim"
        jnl = Journal()

        def mk(ident):
            return ShardMember(
                self.kube, ident, group=self.group,
                num_shards=self.NUM_SHARDS, lease_duration=600.0,
                tick_period=0.01, journal=jnl,
                now_fn=self.clock.now, mono_fn=self.clock.mono,
            )

        self.a = mk("A")
        self.b = mk("B")
        self.a.drain_fn = \
            lambda shards: not self.ledger.busy("A", set(shards))
        self.b.drain_fn = \
            lambda shards: not self.ledger.busy("B", set(shards))
        self.key = _key_in_shard(0, self.NUM_SHARDS)
        # setup (unscheduled, deterministic): epoch 1 gives A everything
        _write_map(self.kube, self.group, 1, {0: "A", 1: "A"}, ["A"],
                   self.NUM_SHARDS)
        self.a._heartbeat()
        self.a._read_map()
        self.a._check_barrier()
        self.a._check_ack()
        assert self.a.admit(*self.key) == OWN
        self.b._heartbeat()
        self.b._read_map()
        self.b._check_ack()

    yield_on = staticmethod(_yield_on_sync)

    def _reconcile(self, member: ShardMember, actor: str) -> None:
        for _ in range(2):
            if member.admit(*self.key) == OWN:
                self.ledger.enter(actor, 0)
                step("reconcile", self.key)
                self.ledger.exit(actor, 0)
            else:
                step("reconcile.skip", actor)

    def _ticks(self, member: ShardMember, n: int) -> None:
        for _ in range(n):
            member._heartbeat()
            member._read_map()
            member._check_barrier()
            member._check_ack()

    def _publish_epoch2(self) -> None:
        step("publish", 2)
        _write_map(self.kube, self.group, 2, {0: "B", 1: "A"},
                   ["A", "B"], self.NUM_SHARDS)

    def threads(self):
        return [
            ("A.rec", lambda: self._reconcile(self.a, "A")),
            ("coord", self._publish_epoch2),
            ("B.tick", lambda: self._ticks(self.b, 3)),
            ("A.tick", lambda: self._ticks(self.a, 3)),
            ("B.rec", lambda: self._reconcile(self.b, "B")),
        ]

    def check(self):
        if self.ledger.violations:
            raise Violation("; ".join(self.ledger.violations))

    def progress(self):
        if self.b.admit(*self.key) != OWN:
            raise Violation(
                "handoff wedged: B never activated shard 0 under a "
                "fair schedule (barrier stuck?)"
            )
        if self.a.admit(*self.key) != FOREIGN:
            raise Violation("A still admits the moved key")


class ShardFenceModel:
    """A partitioned member must self-fence before the rest of the
    plane may presume it dead — and after the partition half-heals
    (heartbeats land, map reads still fail), nothing may re-activate
    off the stale pre-fence map (``_map_confirmed``). The clock only
    advances while no A-reconcile is in flight, encoding the protocol's
    fairness assumption (reconciles are short against lease windows;
    the residual wedged-past-expiry gap is documented in docs/ha.md and
    deliberately NOT modeled)."""

    name = "shard_fence"
    max_decisions = 2000
    preemption_bound = 2
    budget = 400

    NUM_SHARDS = 2
    DUR = 600.0

    def __init__(self):
        self.kube = FakeKube()
        self.clock = VClock()
        self.ledger = Ledger()
        self.group = "simf"
        self.flags = {"partitioned": False, "heal_writes": False}
        self.ticks = {"A": 0}
        jnl = Journal()
        self.a = ShardMember(
            _FlakyKube(self.kube, self.flags, f"{self.group}-map"),
            "A", group=self.group, num_shards=self.NUM_SHARDS,
            lease_duration=self.DUR, tick_period=0.01, journal=jnl,
            now_fn=self.clock.now, mono_fn=self.clock.mono,
        )
        self.b = ShardMember(
            self.kube, "B", group=self.group,
            num_shards=self.NUM_SHARDS, lease_duration=self.DUR,
            tick_period=0.01, journal=jnl,
            now_fn=self.clock.now, mono_fn=self.clock.mono,
        )
        self.b.drain_fn = \
            lambda shards: not self.ledger.busy("B", set(shards))
        self.key = _key_in_shard(0, self.NUM_SHARDS)
        _write_map(self.kube, self.group, 1, {0: "A", 1: "A"}, ["A"],
                   self.NUM_SHARDS)
        self.a._heartbeat()
        self.a._read_map()
        self.a._check_barrier()
        self.a._check_ack()
        assert self.a.admit(*self.key) == OWN
        self.b._heartbeat()
        self.b._read_map()
        self.b._check_ack()

    yield_on = staticmethod(_yield_on_sync)

    def _partition_script(self):
        step("partition")
        self.flags["partitioned"] = True
        # past A's own renew deadline (DUR) but inside the liveness
        # window others grant it (1.25 × DUR): A gets its fencing chance
        self.clock.advance(self.DUR + 1)
        wait_until(lambda: self.ticks["A"] >= 1
                   and not self.ledger.busy("A"), label="a-ticked")
        step("expire")
        self.clock.advance(0.5 * self.DUR)   # now stale to everyone
        wait_until(lambda: not self.ledger.busy("A"), label="a-idle")
        step("heal-writes")
        self.flags["heal_writes"] = True

    def _a_ticks(self):
        # the member's tick loop never stops in production; the phase
        # gates keep the model's finite iterations from being burned
        # before the window they exist to explore (a run-to-block
        # scheduler would otherwise spend all four pre-heal)
        wait_until(lambda: self.flags["partitioned"], label="part")
        for _ in range(2):
            self.a._tick()
            self.ticks["A"] += 1
        wait_until(lambda: self.flags["heal_writes"], label="healed")
        for _ in range(2):
            self.a._tick()
            self.ticks["A"] += 1

    def _a_reconcile(self):
        # gated on the heal: the stale-map re-entry window IS the
        # post-heal tick, so the reconciler must not burn its
        # iterations while A is unambiguously partitioned
        wait_until(lambda: self.flags["heal_writes"], label="healed")
        for _ in range(2):
            if self.a.admit(*self.key) == OWN:
                self.ledger.enter("A", 0)
                step("reconcile", self.key)
                self.ledger.exit("A", 0)
            else:
                step("reconcile.skip", "A")

    def _b_script(self):
        wait_until(lambda: self.flags["partitioned"], label="part")
        for _ in range(2):
            self.b._heartbeat()
            self.b._read_map()
            self.b._check_barrier()
            self.b._check_ack()
        wait_until(lambda: self.flags["heal_writes"], label="healed")
        for _ in range(2):
            self.b._heartbeat()
            self.b._read_map()
            self.b._check_barrier()
            self.b._check_ack()

    def _b_reconcile(self):
        wait_until(lambda: self.flags["heal_writes"], label="healed")
        for _ in range(2):
            if self.b.admit(*self.key) == OWN:
                self.ledger.enter("B", 0)
                step("reconcile", self.key)
                self.ledger.exit("B", 0)
            else:
                step("reconcile.skip", "B")

    def _coord(self):
        wait_until(lambda: self.flags["partitioned"], label="part")
        step("publish", 2)
        _write_map(self.kube, self.group, 2, {0: "B", 1: "B"}, ["B"],
                   self.NUM_SHARDS)

    def threads(self):
        return [
            ("part", self._partition_script),
            ("coord", self._coord),
            ("A.tick", self._a_ticks),
            ("A.rec", self._a_reconcile),
            ("B.tick", self._b_script),
            ("B.rec", self._b_reconcile),
        ]

    def check(self):
        if self.ledger.violations:
            raise Violation("; ".join(self.ledger.violations))


class AutoscaleMembershipModel:
    """Scale-down membership decision racing a shard handoff: the REAL
    ReplicaAutoscaler observes a sustained-idle fleet and fires
    scale_down, whose ordering contract is drain_then_leave — the
    victim's in-flight reconciles drain BEFORE the member leave that
    re-maps its shards. B owns everything under epoch 1 while the
    survivor A idles as a fresh member; the leave stops B (admit goes
    FOREIGN), deletes its member Lease, and publishes epoch 2 giving A
    the world; A's tick loop activates the gained shards (a departed
    member owes no barrier ack). The ledger catches the window a
    leave-without-drain opens: B suspended mid-reconcile while A
    activates and reconciles the same key."""

    name = "autoscale_membership"
    max_decisions = 1500
    preemption_bound = 2
    budget = 300

    NUM_SHARDS = 2

    def __init__(self):
        self.kube = FakeKube()
        self.clock = VClock()
        self.ledger = Ledger()
        self.group = "sims"
        self.left = False
        self.published = False
        jnl = Journal()

        def mk(ident):
            return ShardMember(
                self.kube, ident, group=self.group,
                num_shards=self.NUM_SHARDS, lease_duration=600.0,
                tick_period=0.01, journal=jnl,
                now_fn=self.clock.now, mono_fn=self.clock.mono,
            )

        self.a = mk("A")
        self.b = mk("B")
        self.key = _key_in_shard(0, self.NUM_SHARDS)
        # setup (unscheduled, deterministic): epoch 1 gives B
        # everything; A is a live member holding nothing — the replica
        # the scale-down leaves behind
        _write_map(self.kube, self.group, 1, {0: "B", 1: "B"}, ["B"],
                   self.NUM_SHARDS)
        self.b._heartbeat()
        self.b._read_map()
        self.b._check_barrier()
        self.b._check_ack()
        assert self.b.admit(*self.key) == OWN
        self.a._heartbeat()
        self.a._read_map()
        self.a._check_ack()
        self._drained = lambda: not self.ledger.busy("B")
        self.asc = ReplicaAutoscaler(
            lambda: 1 if self.left else 2,
            lambda: None,   # the idle feed can never scale up
            self._scale_down,
            AutoscaleConfig(min_replicas=1, max_replicas=2,
                            up_consecutive=2, down_consecutive=2,
                            cooldown_s=0.0),
            journal=jnl, mono_fn=self.clock.mono,
        )

    yield_on = staticmethod(_yield_on_sync)

    def _scale_down(self):
        # the production ordering contract under test — the mutant
        # patches the MODULE function to leave without draining, so the
        # call must go through the module attribute
        step("scaledown")
        autoscale_mod.drain_then_leave(
            self._drained, self._leave, timeout_s=600.0,
            sleep_fn=lambda _s: wait_until(self._drained,
                                           label="drained"),
            mono_fn=self.clock.mono,
        )

    def _leave(self):
        step("leave")
        self.left = True
        # the production leave: stop() clears B's active set and
        # deletes the member Lease, so A's barrier owes the departed
        # member no ack
        self.b.stop()
        _write_map(self.kube, self.group, 2, {0: "A", 1: "A"}, ["A"],
                   self.NUM_SHARDS)
        self.published = True

    def _autoscaler(self):
        idle = {"queue_depth_per_worker": 0.0, "busy_ratio": 0.0}
        for _ in range(3):
            step("observe")
            if self.asc.observe(idle) == "scale_down":
                return

    def _b_reconcile(self):
        for _ in range(2):
            if self.left:
                step("reconcile.stopped", "B")
                return
            if self.b.admit(*self.key) == OWN:
                self.ledger.enter("B", 0)
                step("reconcile", self.key)
                self.ledger.exit("B", 0)
            else:
                step("reconcile.skip", "B")

    def _a_ticks(self):
        # gated on the epoch-2 publish (the ShardFenceModel phase-gate
        # idiom): the survivor's finite ticks must not be burned before
        # the window they exist to explore
        wait_until(lambda: self.published, label="epoch2")
        for _ in range(3):
            self.a._heartbeat()
            self.a._read_map()
            self.a._check_barrier()
            self.a._check_ack()

    def _a_reconcile(self):
        wait_until(lambda: self.published, label="epoch2")
        for _ in range(2):
            if self.a.admit(*self.key) == OWN:
                self.ledger.enter("A", 0)
                step("reconcile", self.key)
                self.ledger.exit("A", 0)
            else:
                step("reconcile.skip", "A")

    def threads(self):
        return [
            ("B.rec", self._b_reconcile),
            ("AS", self._autoscaler),
            ("A.tick", self._a_ticks),
            ("A.rec", self._a_reconcile),
        ]

    def check(self):
        if self.ledger.violations:
            raise Violation("; ".join(self.ledger.violations))

    def progress(self):
        if not self.left:
            raise Violation(
                "the sustained-idle fleet never scaled down under a "
                "fair schedule"
            )
        if self.a.admit(*self.key) != OWN:
            raise Violation(
                "scale-down handoff wedged: the survivor never "
                "activated the departed replica's shard"
            )


class LeaseExpiryModel:
    """Two candidates with skewed clocks racing acquire/renew around an
    expiry: every successful takeover must be *legal* under the
    pristine staleness rule (captured before any mutant patches it) —
    deposing a holder whose renew is within duration + tolerance is the
    split-brain the hardened expiry exists to prevent."""

    name = "lease_expiry"
    max_decisions = 800
    preemption_bound = 2
    budget = 300

    DUR = 10.0
    SKEW = 11.0     # > DUR, < DUR + 0.25*DUR: only the tolerance saves
                    # the holder from this candidate's clock

    def __init__(self):
        self.kube = FakeKube()
        self.clock = VClock()
        jnl = Journal()
        self.illegal: list[str] = []
        self.acquires: list[str] = []
        self.c1 = LeaderElector(
            self.kube, "sim-el", identity="c1",
            lease_duration=self.DUR, on_lost=lambda: None,
            now_fn=self.clock.now, mono_fn=self.clock.mono,
            journal=jnl,
        )
        skew = self.SKEW

        def ahead():
            return self.clock.now() + datetime.timedelta(seconds=skew)

        self.c2 = LeaderElector(
            self.kube, "sim-el", identity="c2",
            lease_duration=self.DUR, on_lost=lambda: None,
            now_fn=ahead, mono_fn=self.clock.mono, journal=jnl,
        )

    def yield_on(self, label):
        return label.startswith("sync:")

    def _snapshot(self):
        try:
            return self.kube.get("leases", "sim-el",
                                 namespace="kubeflow", group=LEASE_GROUP)
        except errors.NotFound:
            return None

    def _attempt(self, c: LeaderElector, ident: str) -> None:
        prev = self._snapshot()
        try:
            ok = c._try_acquire()
        except errors.ApiError:
            return
        if not ok:
            return
        self.acquires.append(ident)
        if prev is None:
            return
        spec = prev.get("spec") or {}
        holder = spec.get("holderIdentity")
        if not holder or holder == ident:
            return
        renew = leaderelection._parse(spec.get("renewTime")) or \
            leaderelection._parse(spec.get("acquireTime"))
        dur = float(spec.get("leaseDurationSeconds") or self.DUR)
        if renew is not None and not _pristine_renew_stale(
                renew, dur, 0.25 * dur, c._now()):
            self.illegal.append(
                f"{ident} deposed {holder} whose lease was still "
                f"within duration+tolerance (renew {renew})"
            )

    def _t1(self):
        self._attempt(self.c1, "c1")
        step("held")
        self._attempt(self.c1, "c1")   # renew

    def _t2(self):
        for _ in range(2):
            self._attempt(self.c2, "c2")
            step("candidate")

    def _crash(self):
        step("crash")
        # c1 stops renewing; push its hold past duration + tolerance
        # even on its own clock
        self.clock.advance(self.DUR * 1.4)

    def threads(self):
        return [("T1", self._t1), ("T2", self._t2),
                ("TC", self._crash)]

    def check(self):
        if self.illegal:
            raise Violation("; ".join(self.illegal))

    def progress(self):
        if not self.acquires:
            raise Violation("nobody ever acquired the lease")


class LeaseRaceModel:
    """Two candidates racing an optimistic update of a holderless
    Lease: the MVCC commit identity check must let exactly one win —
    both winning is two active reconcilers."""

    name = "lease_race"
    max_decisions = 400
    preemption_bound = 2
    budget = 200

    def __init__(self):
        self.kube = FakeKube()
        self.clock = VClock()
        jnl = Journal()
        now = leaderelection._fmt(self.clock.now())
        self.kube.create("leases", {
            "apiVersion": f"{LEASE_GROUP}/v1",
            "kind": "Lease",
            "metadata": {"name": "sim-race", "namespace": "kubeflow"},
            "spec": {"holderIdentity": None,
                     "leaseDurationSeconds": 10,
                     "acquireTime": now, "renewTime": now},
        }, namespace="kubeflow", group=LEASE_GROUP)
        self.wins: list[str] = []

        def mk(ident):
            return LeaderElector(
                self.kube, "sim-race", identity=ident,
                lease_duration=10.0, on_lost=lambda: None,
                now_fn=self.clock.now, mono_fn=self.clock.mono,
                journal=jnl,
            )

        self.c1, self.c2 = mk("c1"), mk("c2")

    def yield_on(self, label):
        return label.startswith("sync:")

    def _race(self, c, ident):
        try:
            if c._try_acquire():
                self.wins.append(ident)
        except errors.ApiError:
            pass

    def threads(self):
        return [("T1", lambda: self._race(self.c1, "c1")),
                ("T2", lambda: self._race(self.c2, "c2"))]

    def check(self):
        if len(self.wins) != 1:
            raise Violation(
                f"expected exactly one winner of the holderless lease, "
                f"got {self.wins} — "
                + ("a lost update let both commit"
                   if len(self.wins) > 1 else "nobody won")
            )


class MvccUpdateModel:
    """Two writers incrementing one CR through optimistic updates, then
    a delete; the watch history must show every successful commit
    (no lost update) in strictly increasing RV order with the DELETED
    event RV-bumped past the last write."""

    name = "mvcc_update"
    max_decisions = 600
    preemption_bound = 2
    budget = 300

    def __init__(self):
        self.kube = FakeKube()
        self.kube.create("notebooks", {
            "metadata": {"name": "x", "namespace": "ns"},
            "spec": {"n": 0},
        }, namespace="ns", group=GROUP)
        self.successes = 0
        self.done = {"T1": False, "T2": False}

    def yield_on(self, label):
        return label.startswith("sync:")

    def _incr(self, tid):
        for _ in range(2):
            while True:
                try:
                    cur = self.kube.get("notebooks", "x",
                                        namespace="ns", group=GROUP)
                except errors.NotFound:
                    break
                cur["spec"]["n"] = int(cur["spec"]["n"]) + 1
                try:
                    self.kube.update("notebooks", cur, namespace="ns",
                                     group=GROUP)
                except errors.Conflict:
                    continue
                except errors.NotFound:
                    break
                self.successes += 1
                break
        self.done[tid] = True

    def _delete(self):
        wait_until(lambda: all(self.done.values()), label="writers")
        step("delete")
        try:
            self.kube.delete("notebooks", "x", namespace="ns",
                             group=GROUP)
        except errors.NotFound:
            pass

    def threads(self):
        return [("T1", lambda: self._incr("T1")),
                ("T2", lambda: self._incr("T2")),
                ("T3", self._delete)]

    def check(self):
        events = []
        for ev in self.kube.watch("notebooks", namespace="ns",
                                  group=GROUP, resource_version=0,
                                  timeout=0.01):
            events.append(ev)
        rvs = [int(ev["object"]["metadata"]["resourceVersion"])
               for ev in events]
        if rvs != sorted(rvs) or len(set(rvs)) != len(rvs):
            raise Violation(
                f"watch RVs not strictly increasing: {rvs} — history "
                "order no longer matches RV order"
            )
        if not events or events[-1]["type"] != "DELETED":
            raise Violation("DELETED event missing or not terminal")
        mods = [ev for ev in events if ev["type"] == "MODIFIED"]
        if len(mods) != self.successes:
            raise Violation(
                f"{self.successes} updates succeeded but only "
                f"{len(mods)} MODIFIED events exist"
            )
        final_n = int(mods[-1]["object"]["spec"]["n"]) if mods else 0
        if final_n != self.successes:
            raise Violation(
                f"lost update: {self.successes} commits succeeded but "
                f"the final object shows n={final_n}"
            )
        if mods and rvs[-1] <= int(
                mods[-1]["object"]["metadata"]["resourceVersion"]):
            raise Violation(
                "DELETED event rode a stale resourceVersion — a "
                "resume-from-last-RV watcher would drop the delete"
            )


class QueueGetDoneModel:
    """Workers and a producer over one RateLimitingQueue: a key is
    never processed by two workers at once (per-key serialization) and
    a re-add while processing is never lost (level triggering) — the
    final drain must leave no key whose last event is its add."""

    name = "queue_getdone"
    max_decisions = 600
    preemption_bound = 2
    budget = 300

    def __init__(self):
        self.q = RateLimitingQueue()
        self.q.add("K1")           # setup: pre-hook, unscheduled
        self.ledger = Ledger()
        self.events: list[tuple] = []

    def yield_on(self, label):
        return (label.startswith("sync:queue.")
                or label.startswith("sync:model."))

    def _worker(self, wid, iters):
        for _ in range(iters):
            k = self.q.get(timeout=0.005)
            if k is None:
                continue
            self.events.append(("get", k))
            self.ledger.enter(wid, k)
            step("proc", k)
            self.ledger.exit(wid, k)
            self.q.done(k)

    def _producer(self):
        for k in ("K1", "K2"):
            self.events.append(("add", k))
            self.q.add(k)

    def threads(self):
        return [("W1", lambda: self._worker("W1", 2)),
                ("P", self._producer),
                ("W2", lambda: self._worker("W2", 1))]

    def check(self):
        if self.ledger.violations:
            raise Violation("; ".join(self.ledger.violations))
        # final drain: anything still pending is observed now; a key
        # whose LAST event remains its add was dropped on the floor
        while True:
            k = self.q.get(timeout=0.005)
            if k is None:
                break
            self.events.append(("get", k))
            self.q.done(k)
        last: dict = {}
        for kind, k in self.events:
            last[k] = kind
        dropped = sorted(k for k, kind in last.items() if kind == "add")
        if dropped:
            raise Violation(
                f"level-trigger lost: key(s) {dropped} were added but "
                "never surfaced again (dirty re-add dropped?)"
            )


_PARK_MODEL_ROOT: str | None = None


def _park_model_store() -> "parking.ParkStore":
    """One shared on-disk store root per process — the explorer builds
    a fresh model per schedule, and a per-run mkdtemp would leak
    thousands of directories; each model init wipes its notebook's
    subtree instead, so schedules stay independent."""
    global _PARK_MODEL_ROOT
    if _PARK_MODEL_ROOT is None:
        _PARK_MODEL_ROOT = tempfile.mkdtemp(prefix="schedsim-park-")
    return parking.ParkStore(_PARK_MODEL_ROOT)


class ParkResumeModel:
    """Park→release→resume→re-admit (controlplane/parking) over the
    REAL CullingReconciler — the single park executor and resume
    finisher — with a scripted tpusched mirror: the mirror stamps the
    oversubscription park request, waits for the stop, clears the pool
    annotation BEFORE freeing the booking, admits the waiter onto the
    freed pool, and then re-requests a park while the user's resume is
    still in flight (the next oversubscription round racing the resume
    finisher — the exact window the resume-wins rule exists for).
    Invariants over the FULL watch history plus the final state: no
    torn park (a Parked instant without its checkpoint ref), every
    Parked instant carries a restorable ref, no lost checkpoint on
    resume (a ResumeFailed event), at most one booking per pool per
    instant, and final convergence — the notebook ends running with
    every park annotation cleared (a leftover park request would
    re-park the notebook the user just resumed)."""

    name = "park_resume"
    max_decisions = 800
    preemption_bound = 2
    budget = 300

    NS = "team"
    NB = "victim"
    POOL_A = "pool-a"
    POOL_B = "pool-b"

    def __init__(self):
        self.kube = FakeKube()
        self.clock = VClock()
        self.store = _park_model_store()
        self.store.delete(self.NS, self.NB)   # fresh store per schedule
        self.parker = parking.Parker(self.store)
        self.culler = CullingReconciler(
            self.kube, fetch_kernels=lambda url: None,
            now=self.clock.now, parker=self.parker,
        )
        self.kube.create("notebooks", {
            "metadata": {"name": self.NB, "namespace": self.NS,
                         "annotations": {
                             tpu_mod.ANNOTATION_NODEPOOL: self.POOL_A,
                         }},
            "spec": {"tpu": {"accelerator": "v5litepod-16"}},
            "status": {"readyReplicas": 1},
        }, namespace=self.NS, group=GROUP)
        #: booking mirror: pool -> holders; two holders at any instant
        #: is the double booking the release ordering prevents
        self.holders = {self.POOL_A: {self.NB}, self.POOL_B: set()}
        self.double: list[str] = []
        self.resume_patched = False

    def yield_on(self, label):
        return (label.startswith("sync:fake.")
                or label.startswith("sync:model."))

    # ---------------------------------------------------------- helpers

    def _annots(self) -> dict:
        try:
            nb = self.kube.get("notebooks", self.NB, namespace=self.NS,
                               group=GROUP)
        except errors.NotFound:
            return {}
        return nb["metadata"].get("annotations") or {}

    def _stopped(self) -> bool:
        return STOP_ANNOTATION in self._annots()

    def _parked(self) -> bool:
        return parking.PARKED_ANNOTATION in self._annots()

    def _requested(self) -> bool:
        return parking.PARK_REQUESTED_ANNOTATION in self._annots()

    def _resume_pending(self) -> bool:
        return parking.RESUME_REQUESTED_ANNOTATION in self._annots()

    def _book(self, pool: str, name: str) -> None:
        held = self.holders[pool]
        if held:
            self.double.append(
                f"{pool} booked for {name} while held by {sorted(held)}"
            )
        held.add(name)

    def _patch_nb(self, annotations: dict) -> None:
        try:
            self.kube.patch("notebooks", self.NB,
                            {"metadata": {"annotations": annotations}},
                            namespace=self.NS, group=GROUP)
        except errors.NotFound:
            pass

    # ---------------------------------------------------------- threads

    def _sched(self):
        # oversubscription: no pool feasible for the waiter — park the
        # coldest tenant (scheduler/reconciler.py _finish_park shape)
        step("sched.request")
        self._patch_nb({
            parking.PARK_REQUESTED_ANNOTATION:
                parking.PARK_OVERSUBSCRIBED,
            parking.PARKED_FOR_ANNOTATION: "waiter",
        })
        wait_until(self._stopped, label="park.stop")
        # release: clear the placement BEFORE freeing the chips (the
        # scheduler's stop-branch ordering — two live annotations on
        # one pool would read as a double booking), then admit the
        # waiter onto the freed pool
        step("sched.release")
        self._patch_nb({tpu_mod.ANNOTATION_NODEPOOL: None})
        step("sched.free")
        self.holders[self.POOL_A].discard(self.NB)
        self._book(self.POOL_A, "waiter")
        # the NEXT oversubscription round racing the resume finisher:
        # the request must land on a still-resuming notebook or not at
        # all, so it rides an optimistic update gated on the
        # resume-requested annotation
        wait_until(lambda: self.resume_patched, label="resume.seen")
        for _ in range(4):
            try:
                nb = self.kube.get("notebooks", self.NB,
                                   namespace=self.NS, group=GROUP)
            except errors.NotFound:
                return
            annots = nb["metadata"].setdefault("annotations", {})
            if parking.RESUME_REQUESTED_ANNOTATION not in annots:
                return   # resume already finished: nothing to race
            annots[parking.PARK_REQUESTED_ANNOTATION] = (
                parking.PARK_OVERSUBSCRIBED)
            try:
                self.kube.update("notebooks", nb, namespace=self.NS,
                                 group=GROUP)
                return
            except errors.Conflict:
                continue
            except errors.NotFound:
                return

    def _culler(self):
        req = Request(self.NS, self.NB)

        def settled():
            return (self.resume_patched and not self._resume_pending()
                    and not self._stopped())

        while not settled():
            wait_until(lambda: (settled() or self._requested()
                                or self._resume_pending()),
                       label="culler.wake")
            if settled():
                break
            step("culler.pass")
            self.culler.reconcile(req)

    def _user(self):
        wait_until(self._parked, label="parked")
        # the open hit: the webapp PATCH clears the stop annotation,
        # stamps resume-requested when a checkpoint exists, and cancels
        # any in-flight park request (webapps/jupyter/app.py mirror)
        step("user.open")
        annots = self._annots()
        patch = {STOP_ANNOTATION: None}
        if parking.CHECKPOINT_ANNOTATION in annots:
            patch[parking.RESUME_REQUESTED_ANNOTATION] = (
                self.clock.now().strftime("%Y-%m-%dT%H:%M:%SZ"))
        if parking.PARK_REQUESTED_ANNOTATION in annots:
            patch[parking.PARK_REQUESTED_ANNOTATION] = None
        self._patch_nb(patch)
        self.resume_patched = True
        wait_until(lambda: (not self._resume_pending()
                            and not self._stopped()),
                   label="resumed")
        # re-admission: the resumed notebook goes back through the
        # queue and books a (new) pool
        step("user.readmit")
        self._book(self.POOL_B, self.NB)

    def threads(self):
        return [("SCHED", self._sched), ("CULL", self._culler),
                ("USER", self._user)]

    # ------------------------------------------------------------ check

    def check(self):
        if self.double:
            raise Violation("double booking: " + "; ".join(self.double))
        parked_instants = 0
        for ev in self.kube.watch("notebooks", namespace=self.NS,
                                  group=GROUP, resource_version=0,
                                  timeout=0.01):
            if ev["type"] == "DELETED":
                continue
            annots = (ev["object"]["metadata"].get("annotations")
                      or {})
            if parking.PARKED_ANNOTATION not in annots:
                continue
            parked_instants += 1
            ref = annots.get(parking.CHECKPOINT_ANNOTATION)
            if not ref:
                raise Violation(
                    "torn park: a Parked state without its checkpoint "
                    "ref is in the history — a crash there strands a "
                    "stopped notebook with no restorable state"
                )
            if not self.parker.resumable(ref):
                raise Violation(
                    f"Parked state carries unrestorable ref {ref!r} — "
                    "the checkpoint never committed before the stop "
                    "landed"
                )
        if not parked_instants:
            raise Violation(
                "the park never executed: no Parked state in the "
                "watch history"
            )
        for ev in self.kube.list("events",
                                 namespace=self.NS)["items"]:
            if ev.get("reason") == parking.REASON_RESUME_FAILED:
                raise Violation(
                    "lost checkpoint: the resume finisher raised "
                    f"ResumeFailed — {ev.get('message')}"
                )
        final = self._annots()
        leftover = sorted(
            a for a in (STOP_ANNOTATION, parking.PARKED_ANNOTATION,
                        parking.CHECKPOINT_ANNOTATION,
                        parking.PARK_REASON_ANNOTATION,
                        parking.PARK_REQUESTED_ANNOTATION,
                        parking.RESUME_REQUESTED_ANNOTATION,
                        parking.PARKED_FOR_ANNOTATION)
            if a in final
        )
        if leftover:
            raise Violation(
                f"resume did not win: the notebook ended with "
                f"{leftover} still set — a pending park request here "
                "re-parks the notebook the user just resumed"
            )
        if self.holders != {self.POOL_A: {"waiter"},
                            self.POOL_B: {self.NB}}:
            raise Violation(
                f"re-admission bookkeeping diverged: {self.holders}"
            )


class LockInversionModel:
    """The test_cplint two-thread A→B/B→A fixture as a schedsim model:
    the explorer must FIND the deadlock interleaving within a bounded
    budget — lockwatch alone only catches it when the OS scheduler
    cooperates. Deliberately violating: not part of the clean gate."""

    name = "lock_inversion"
    max_decisions = 200
    preemption_bound = 2
    budget = 60

    def __init__(self):
        self.watch = lockwatch.LockWatch()
        self.a = self.watch.lock("/x/controlplane/sched.py:10")
        self.b = self.watch.lock("/x/controlplane/informer.py:20")

    def yield_on(self, label):
        return label.startswith("lock:")

    def _t1(self):
        with self.a:
            with self.b:
                pass

    def _t2(self):
        with self.b:
            with self.a:
                pass

    def threads(self):
        return [("T1", self._t1), ("T2", self._t2)]

    def check(self):
        pass


class LockOrderedModel(LockInversionModel):
    """Control for the inversion model: both threads take A→B — no
    interleaving deadlocks, the explorer must come back clean."""

    name = "lock_ordered"

    def _t2(self):
        with self.a:
            with self.b:
                pass


#: the clean-gate models: clean HEAD must explore every one of these
#: violation-free within the CI budget
MODELS: dict = {
    m.name: m for m in (
        ShardHandoffModel, ShardFenceModel, AutoscaleMembershipModel,
        LeaseExpiryModel, LeaseRaceModel, MvccUpdateModel,
        QueueGetDoneModel, ParkResumeModel,
    )
}

#: deliberately-violating demo models (lockwatch fixtures re-run
#: through the explorer); addressable via --model, excluded from the
#: default gate
DEMO_MODELS: dict = {
    m.name: m for m in (LockInversionModel, LockOrderedModel)
}


# =====================================================================
# the seeded mutants
# =====================================================================

def _patched(obj, attr, repl):
    @contextlib.contextmanager
    def cm():
        orig = getattr(obj, attr)
        setattr(obj, attr, repl)
        try:
            yield
        finally:
            setattr(obj, attr, orig)
    return cm


def _mut_drop_ack_barrier(self):
    # seeded bug: activate gained shards WITHOUT consulting fellow
    # members' acked epochs (the PR 12 barrier removed)
    syncpoint.sync("shard.barrier", self.identity)
    with self._lock:
        gained = set(self._pending)
        self._pending.clear()
        self._active = frozenset(set(self._active) | gained)
    if gained and self.on_gain is not None:
        self.on_gain(gained)


def _mut_ack_before_drain(self):
    # seeded bug: publish the epoch ack without waiting for in-flight
    # reconciles of the lost shards to drain
    syncpoint.sync("shard.ack", self.identity)
    with self._lock:
        wait = self._ack_wait
    if wait is None:
        return
    with self._lock:
        if self._ack_wait != wait:
            return
        self._acked = wait[0]
        self._ack_wait = None
    self._heartbeat()


def _mut_never_fence(self, renewed):
    # seeded bug: a member whose heartbeat went stale keeps admitting
    return None


def _mut_barrier_ignores_fence(self):
    # seeded bug: the _map_confirmed gate removed — a post-fence member
    # re-activates through the stale pre-partition map (the exact
    # re-entry hole the PR 12 review closed)
    syncpoint.sync("shard.barrier", self.identity)
    with self._lock:
        if not self._pending:
            return
        epoch = self._epoch
    try:
        listing = self.kube.list(
            "leases", namespace=self.namespace, group=LEASE_GROUP,
            label_selector=("cpshard.tpukf.dev/group="
                            f"{self.group},cpshard.tpukf.dev/role"
                            "=member"),
        )["items"]
    except errors.ApiError:
        return
    from service_account_auth_improvements_tpu.controlplane.engine import (  # noqa: E501
        shard as shard_mod,
    )
    now = self._now()
    for lease in listing:
        ident = (lease.get("spec") or {}).get("holderIdentity")
        if not ident or ident == self.identity:
            continue
        if not shard_mod._lease_live(lease, now, self.lease_duration):
            continue
        ann = (lease.get("metadata") or {}).get("annotations") or {}
        try:
            acked = int(ann.get(ANN_ACKED) or 0)
        except ValueError:
            acked = 0
        if acked < epoch:
            return
    gained = set()
    with self._lock:
        if self._epoch != epoch or not self._pending:
            return
        gained = {s for s, e in self._pending.items() if e <= epoch}
        if not gained:
            return
        for s in gained:
            del self._pending[s]
        self._active = frozenset(set(self._active) | gained)
    if self.on_gain is not None:
        self.on_gain(gained)


def _mut_renew_stale_no_tolerance(renew, duration, tolerance, now):
    # seeded bug: the skew tolerance and the broken-future-clock leg
    # dropped — a candidate's fast clock deposes a healthy holder
    return (now - renew).total_seconds() > float(duration)


def _mut_expired_always(self, lease):
    # seeded bug: every hold reads as expired — candidates steal live
    # leases
    return True


def _mut_commit_ok_always(self, stripe, key, cur):
    # seeded bug: the MVCC identity check removed — a racing commit is
    # silently overwritten (the lost update)
    return True


def _mut_remove_stale_rv(self, res, key, expect=None):
    # seeded bug: DELETED events carry the pre-delete resourceVersion
    # (the exact bug the striped-MVCC refactor fixed: a
    # resume-from-last-RV watcher drops the delete)
    fam = self._family(res)
    stripe = self._stripe(fam, key[2])
    if stripe is None:
        return None
    syncpoint.sync("fake.commit", res.plural)
    with fam.lock:
        with stripe.lock:
            obj = stripe.objects.get(key)
            if obj is None or (expect is not None and obj is not expect):
                return None
            self._next_rv()
            del stripe.objects[key]
        self._emit_locked(fam, "DELETED", obj)   # stale RV!
    uid = obj["metadata"].get("uid")
    with self._uids_lock:
        if uid:
            self._uids.discard(uid)
    if uid:
        self._defer("cascade", None, uid)
    return obj


def _mut_done_drops_dirty(self, key):
    # seeded bug: done() forgets the dirty re-add — a key re-added
    # while processing is lost (level triggering broken)
    syncpoint.sync("queue.done", key)
    with self._lock:
        self._processing.discard(key)
        self._dirty.discard(key)


def _mut_get_skips_processing(self, timeout):
    # seeded bug: dequeue does not register the key in _processing —
    # two workers can run the same key concurrently and a re-add while
    # processing re-queues immediately instead of going dirty
    deadline = time.monotonic() + timeout if timeout else None
    with self._lock:
        while True:
            now = time.monotonic()
            while self._delayed and self._delayed[0][0] <= now:
                _, _, key = heapq.heappop(self._delayed)
                if key not in self._pending:
                    self._pending.add(key)
                    self._order.append(key)
                    self._note_pending_locked(key)
            if self._order:
                key = self._order.popleft()
                self._pending.discard(key)
                enqueued = self._added_at.pop(key, None)
                self._observe_depth_locked()
                return key, enqueued, time.monotonic()
            if self._shutdown:
                return None
            wait = 0.2
            if self._delayed:
                wait = min(wait, max(self._delayed[0][0] - now, 0.001))
            if deadline is not None:
                if now >= deadline:
                    return None
                wait = min(wait, deadline - now)
            self._lock.wait(wait)


def _mut_park_stop_before_checkpoint(self, req, nb, annots, reason,
                                     period, kernels=None,
                                     idle_for=None, base_patch=None):
    # seeded bug: the park verb's crash invariant inverted — stop +
    # parked stamped BEFORE the checkpoint commits (the torn-park
    # window the real _execute_park exists to close)
    now = self.now()
    patch = base_patch or {"metadata": {"annotations": {}}}
    patch["metadata"]["annotations"].update({
        STOP_ANNOTATION: now.strftime("%Y-%m-%dT%H:%M:%SZ"),
        parking.PARKED_ANNOTATION: now.strftime("%Y-%m-%dT%H:%M:%SZ"),
        parking.PARK_REASON_ANNOTATION: reason,
        parking.PARK_REQUESTED_ANNOTATION: None,
    })
    try:
        self.kube.patch("notebooks", req.name, patch,
                        namespace=req.namespace, group=GROUP)
    except errors.NotFound:
        return Result()
    ref = self.parker.park(nb, kernels)
    try:
        self.kube.patch("notebooks", req.name,
                        {"metadata": {"annotations": {
                            parking.CHECKPOINT_ANNOTATION: ref,
                        }}}, namespace=req.namespace, group=GROUP)
    except errors.NotFound:
        pass
    return Result(requeue_after=period.total_seconds())


def _mut_park_uncommitted_ref(self, nb, kernels=None):
    # seeded bug: park hands back a ref whose save never committed —
    # the checkpoint the resume will need does not exist
    meta = nb.get("metadata") or {}
    return f"{meta.get('namespace') or ''}/{meta['name']}@1"


def _mut_resume_keeps_park_request(self, req, nb, annots, period):
    # seeded bug: the resume finisher no longer cancels an in-flight
    # park request ("resume wins" dropped) — the next culler pass
    # re-parks the notebook the user just resumed
    ref = annots.get(parking.CHECKPOINT_ANNOTATION)
    if ref:
        try:
            self.parker.restore(ref)
        except Exception:  # noqa: BLE001 — mutant keeps the happy path
            pass
    try:
        self.kube.patch("notebooks", req.name,
                        {"metadata": {"annotations": {
                            parking.RESUME_REQUESTED_ANNOTATION: None,
                            parking.PARKED_ANNOTATION: None,
                            parking.PARK_REASON_ANNOTATION: None,
                            parking.PARKED_FOR_ANNOTATION: None,
                            parking.CHECKPOINT_ANNOTATION: None,
                        }}}, namespace=req.namespace, group=GROUP)
    except errors.NotFound:
        return Result()
    return Result(requeue_after=period.total_seconds())


def _mut_leave_without_drain(drained_fn, leave_fn, **kw):
    # seeded bug: the scale-down ordering contract inverted — the
    # member leaves (re-mapping its shards) while its reconciles are
    # still in flight
    leave_fn()
    return True


class Mutant:
    def __init__(self, name: str, models: tuple, apply_cm,
                 description: str):
        self.name = name
        self.models = models
        self.apply = apply_cm
        self.description = description


MUTANTS: dict = {
    m.name: m for m in (
        Mutant("shard-drop-ack-barrier", ("shard_handoff",),
               _patched(ShardMember, "_check_barrier",
                        _mut_drop_ack_barrier),
               "gained shards activate without the fellow-member ack "
               "barrier"),
        Mutant("shard-ack-before-drain", ("shard_handoff",),
               _patched(ShardMember, "_check_ack",
                        _mut_ack_before_drain),
               "a lost epoch is acked while its reconciles are still "
               "in flight"),
        Mutant("shard-skip-self-fence", ("shard_fence",),
               _patched(ShardMember, "_update_fence", _mut_never_fence),
               "a member whose heartbeat staled keeps admitting its "
               "shards"),
        Mutant("shard-stale-map-reactivation", ("shard_fence",),
               _patched(ShardMember, "_check_barrier",
                        _mut_barrier_ignores_fence),
               "a post-fence member re-activates through the stale "
               "pre-partition map (no _map_confirmed gate)"),
        Mutant("lease-skew-ignored", ("lease_expiry",),
               _patched(leaderelection, "renew_stale",
                        _mut_renew_stale_no_tolerance),
               "lease expiry drops the skew tolerance — a fast clock "
               "deposes a healthy holder"),
        Mutant("lease-steal-held", ("lease_expiry",),
               _patched(LeaderElector, "_expired", _mut_expired_always),
               "every hold reads as expired — candidates steal live "
               "leases"),
        Mutant("fake-commit-identity-dropped",
               ("lease_race", "mvcc_update"),
               _patched(FakeKube, "_commit_ok", _mut_commit_ok_always),
               "the MVCC optimistic-commit identity check removed — "
               "racing writers silently overwrite each other"),
        Mutant("fake-delete-stale-rv", ("mvcc_update",),
               _patched(FakeKube, "_remove", _mut_remove_stale_rv),
               "DELETED watch events carry the pre-delete RV"),
        Mutant("queue-dirty-dropped", ("queue_getdone",),
               _patched(RateLimitingQueue, "done",
                        _mut_done_drops_dirty),
               "done() forgets the dirty re-add — level triggering "
               "lost"),
        Mutant("queue-processing-unregistered", ("queue_getdone",),
               _patched(RateLimitingQueue, "_get",
                        _mut_get_skips_processing),
               "dequeue skips _processing registration — per-key "
               "serialization lost"),
        Mutant("park-stop-before-checkpoint", ("park_resume",),
               _patched(CullingReconciler, "_execute_park",
                        _mut_park_stop_before_checkpoint),
               "the park verb stops the notebook BEFORE the checkpoint "
               "commits — a crash in the window strands a stopped "
               "notebook with no restorable state"),
        Mutant("park-ref-never-committed", ("park_resume",),
               _patched(parking.Parker, "park",
                        _mut_park_uncommitted_ref),
               "park stamps a checkpoint ref whose save never "
               "committed — the resume finds nothing restorable"),
        Mutant("park-resume-keeps-request", ("park_resume",),
               _patched(CullingReconciler, "_finish_resume",
                        _mut_resume_keeps_park_request),
               "the resume finisher no longer cancels an in-flight "
               "park request — the next culler pass re-parks a "
               "just-resumed notebook"),
        Mutant("autoscale-leave-without-drain", ("autoscale_membership",),
               _patched(autoscale_mod, "drain_then_leave",
                        _mut_leave_without_drain),
               "scale-down leaves the membership before the victim's "
               "reconciles drain — the dual-reconcile window "
               "drain_then_leave exists to close"),
    )
}


def run_mutations(names=None, budget: int | None = None,
                  deadline_s: float | None = None) -> dict:
    """Run each seeded mutant's target models under the explorer; a
    mutant is CAUGHT when any target model yields a violation within
    budget. ``deadline_s`` bounds the WHOLE suite (shared across
    mutants — the knob an operator sets is the step's wall time, not a
    per-exploration slice); a mutant whose exploration was cut short
    by it records ``interrupted`` so a deadline-starved run reads as
    "raise the deadline", not as a protocol regression. Returns the
    machine record (ok = every mutant caught)."""
    t0 = time.monotonic()
    results = {}
    for name in sorted(names or MUTANTS):
        mut = MUTANTS[name]
        caught_by = None
        runs_total = 0
        interrupted = False
        with mut.apply():
            for model_name in mut.models:
                cls = MODELS[model_name]
                # mutants hide deeper than the clean gate's budget: the
                # PCT phase needs room (the deepest seeded bug lands
                # around run ~1600 at seed 0 — 2500 leaves headroom)
                per_model = (budget if budget is not None
                             else max(cls.budget, 2500))
                remaining = None
                if deadline_s is not None:
                    remaining = deadline_s - (time.monotonic() - t0)
                res = explore(
                    cls,
                    max_schedules=per_model,
                    preemption_bound=cls.preemption_bound,
                    deadline_s=remaining,
                )
                runs_total += res["runs"]
                interrupted = interrupted or res["interrupted"]
                if res["violations"]:
                    caught_by = {
                        "model": model_name,
                        "runs": res["runs"],
                        "violation": res["violations"][0]["violation"],
                        "choices": res["violations"][0]["choices"],
                    }
                    break
        results[name] = {
            "description": mut.description,
            "caught": caught_by is not None,
            "caught_by": caught_by,
            "runs": runs_total,
            "interrupted": interrupted,
        }
    return {
        "schema": "schedsim/v1",
        "mode": "mutations",
        "ok": all(r["caught"] for r in results.values()),
        "mutants": results,
    }


# =====================================================================
# dumps + replay
# =====================================================================

def dump_violation(vio: dict, out_dir: pathlib.Path,
                   index: int) -> pathlib.Path:
    out_dir.mkdir(parents=True, exist_ok=True)
    path = out_dir / f"schedsim_{vio['model']}_{index}.json"
    with open(path, "w") as f:
        json.dump({"schema": "schedsim/v1", "mode": "schedule",
                   **vio}, f, indent=2)
    return path


def replay(dump: dict) -> dict | None:
    """Re-run the exact dumped interleaving; returns the reproduced
    violation (None when the schedule now runs clean — the bug was
    fixed)."""
    name = dump["model"]
    cls = MODELS.get(name) or DEMO_MODELS.get(name)
    if cls is None:
        raise KeyError(f"unknown model {name!r}")
    sim = _run_model(cls(), choices=dump["choices"])
    return sim.violation


# =====================================================================
# CLI
# =====================================================================

def main(argv=None) -> int:
    import logging

    # the models drive members through scripted partitions; their
    # warning logs are expected noise here, not signal
    logging.getLogger(
        "service_account_auth_improvements_tpu"
    ).setLevel(logging.CRITICAL)
    ap = argparse.ArgumentParser(
        prog="python -m tools.cplint.schedsim",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("--model", action="append", dest="models",
                    metavar="NAME",
                    help="explore only the named model (repeatable); "
                         "default: every clean-gate model")
    ap.add_argument("--budget", type=int, default=None,
                    help="max schedules per model (default: each "
                         "model's own)")
    ap.add_argument("--preemptions", type=int, default=None,
                    help="preemption bound (default: each model's own)")
    ap.add_argument("--deadline", type=float, default=None,
                    metavar="SECONDS",
                    help="wall-clock ceiling for the WHOLE invocation "
                         "(shared across every model/mutant explored — "
                         "what a CI step's wall budget means)")
    ap.add_argument("--mutations", action="store_true",
                    help="run the seeded-mutant catch suite instead of "
                         "the clean gate")
    ap.add_argument("--mutant", action="append", dest="mutants",
                    metavar="NAME",
                    help="with --mutations: only the named mutant(s)")
    ap.add_argument("--replay", metavar="PATH",
                    help="re-run a dumped schedule; exits 1 when the "
                         "violation reproduces")
    ap.add_argument("--dump-dir", default="schedsim_out",
                    help="where failing schedules are dumped "
                         "(default: schedsim_out)")
    ap.add_argument("--json", dest="json_out", metavar="PATH",
                    help="write the machine-readable run record")
    ap.add_argument("--fair", action="store_true",
                    help="additionally run each model's round-robin "
                         "progress check")
    ap.add_argument("--list-models", action="store_true")
    ap.add_argument("--list-mutants", action="store_true")
    ap.add_argument("--list-sync-points", action="store_true")
    args = ap.parse_args(argv)

    if args.list_models:
        print(json.dumps({
            "models": {n: (MODELS | DEMO_MODELS)[n].__doc__.split("\n")[0]
                       for n in sorted(MODELS | DEMO_MODELS)},
        }, indent=2))
        return 0
    if args.list_mutants:
        print(json.dumps({
            "mutants": {n: {"models": list(m.models),
                            "description": m.description}
                        for n, m in sorted(MUTANTS.items())},
        }, indent=2))
        return 0
    if args.list_sync_points:
        print(json.dumps({"sync_points": SYNC_POINTS}, indent=2))
        return 0

    if args.replay:
        with open(args.replay) as f:
            dump = json.load(f)
        vio = replay(dump)
        if vio is not None:
            print(f"schedsim: replay of {dump['model']} reproduces: "
                  f"{vio}", file=sys.stderr)
            return 1
        print(f"schedsim: replay of {dump['model']} runs clean",
              file=sys.stderr)
        return 0

    if args.mutations:
        unknown = set(args.mutants or ()) - set(MUTANTS)
        if unknown:
            ap.error(f"unknown mutant(s): {', '.join(sorted(unknown))}")
        record = run_mutations(args.mutants, budget=args.budget,
                               deadline_s=args.deadline)
        if args.json_out:
            with open(args.json_out, "w") as f:
                json.dump(record, f, indent=2)
        for name, r in sorted(record["mutants"].items()):
            if r["caught"]:
                cb = r["caught_by"]
                print(f"schedsim: mutant {name} CAUGHT by {cb['model']} "
                      f"after {cb['runs']} schedule(s): "
                      f"{cb['violation']['kind']}", file=sys.stderr)
            elif r.get("interrupted"):
                # a deadline-starved exploration is NOT evidence the
                # mutant is uncatchable — say so (and still fail: a
                # suite that couldn't finish proves nothing)
                print(f"schedsim: mutant {name} NOT CAUGHT within the "
                      f"deadline ({r['runs']} schedules, interrupted) "
                      "— raise --deadline/--budget",
                      file=sys.stderr)
            else:
                print(f"schedsim: mutant {name} SURVIVED "
                      f"({r['runs']} schedules) — {r['description']}",
                      file=sys.stderr)
        return 0 if record["ok"] else 1

    # ------------------------------------------------- clean-HEAD gate
    names = args.models or sorted(MODELS)
    unknown = set(names) - set(MODELS) - set(DEMO_MODELS)
    if unknown:
        ap.error(f"unknown model(s): {', '.join(sorted(unknown))}")
    record: dict = {"schema": "schedsim/v1", "mode": "explore",
                    "models": {}, "ok": True}
    dumped = 0
    t0 = time.monotonic()
    for name in names:
        cls = MODELS.get(name) or DEMO_MODELS[name]
        remaining = None
        if args.deadline is not None:
            remaining = args.deadline - (time.monotonic() - t0)
        res = explore(
            cls,
            max_schedules=args.budget or cls.budget,
            preemption_bound=(args.preemptions
                              if args.preemptions is not None
                              else cls.preemption_bound),
            deadline_s=remaining,
        )
        entry = {"runs": res["runs"],
                 "violations": len(res["violations"]),
                 "interrupted": res["interrupted"],
                 "exhaustive": res["exhaustive"]}
        if res["runs"] == 0:
            # a model the deadline starved to ZERO schedules proved
            # nothing — the gate must not read absence of exploration
            # as cleanliness (the bench_gate lint-leg asymmetry)
            record["ok"] = False
            record["models"][name] = entry
            print(f"schedsim: {name}: 0 schedules explored (deadline "
                  "starved) — no evidence either way; raise --deadline",
                  file=sys.stderr)
            continue
        if args.fair and not res["violations"]:
            fr = fair_run(cls)
            entry["fair_ok"] = fr.violation is None
            if fr.violation is not None:
                res["violations"].append({
                    "model": name, "choices": fr.choices_taken(),
                    "violation": fr.violation,
                })
                entry["violations"] += 1
        record["models"][name] = entry
        for vio in res["violations"]:
            record["ok"] = False
            path = dump_violation(vio, pathlib.Path(args.dump_dir),
                                  dumped)
            dumped += 1
            print(f"schedsim: {name}: {vio['violation']} — schedule "
                  f"dumped to {path} (re-run: python -m "
                  f"tools.cplint.schedsim --replay {path})",
                  file=sys.stderr)
        if not res["violations"]:
            print(f"schedsim: {name}: {res['runs']} schedule(s) "
                  "explored, no violation"
                  + (" (exhaustive within bounds)"
                     if res.get("exhaustive") else " (budget spent)"),
                  file=sys.stderr)
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(record, f, indent=2)
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
