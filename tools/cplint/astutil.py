"""Small AST helpers shared by the cplint passes."""

from __future__ import annotations

import ast

#: method names that mutate their receiver in place (dict/list/set/deque
#: surface) — the mutation half of lock-discipline and cache-mutation
MUTATING_METHODS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "discard",
    "remove", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse",
})

#: constructors whose instances are internally synchronized (or
#: thread-confined by design) — mutating method calls on them don't need
#: the class lock
THREADSAFE_CTORS = frozenset({
    "Event", "local", "Lock", "RLock", "Condition", "Semaphore",
    "BoundedSemaphore", "Barrier", "Timer", "Queue", "SimpleQueue",
    "LifoQueue", "PriorityQueue",
})


def attr_chain(node: ast.AST) -> list[str] | None:
    """``a.b.c`` -> ["a", "b", "c"]; None for non-name-rooted chains."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def dotted(node: ast.AST) -> str | None:
    chain = attr_chain(node)
    return ".".join(chain) if chain else None


def self_attr(node: ast.AST) -> str | None:
    """'x' when node is exactly ``self.x``."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def call_name(node: ast.Call) -> str | None:
    """Trailing name of the called expression: ``threading.Lock`` ->
    'Lock', ``Lock`` -> 'Lock'."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def base_name(node: ast.AST) -> str | None:
    """Root Name of a subscript/attribute chain: ``x["a"]["b"]`` / ``x.a``
    -> 'x'."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def str_arg(node: ast.Call, index: int = 0) -> str | None:
    """The call's positional arg at ``index`` when it is a string
    literal."""
    if len(node.args) > index:
        a = node.args[index]
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            return a.value
    return None


def iter_functions(tree: ast.AST):
    """Yield every (Function/AsyncFunction) node in the module."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def self_mutations(stmt: ast.AST):
    """Yield (attr_name, node) for every in-place mutation of a
    ``self.X`` attribute inside ``stmt`` (without descending into nested
    function defs): assignment, augmented assignment, subscript
    write/delete, and mutating method calls (incl. ``heapq.heappush``
    style helpers whose first arg is the container)."""
    for node in walk_no_nested_functions(stmt):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                yield from _mutation_targets(tgt)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            yield from _mutation_targets(node.target)
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                yield from _mutation_targets(tgt)
        elif isinstance(node, ast.Call):
            yield from call_mutations(node)


def call_mutations(node: ast.Call):
    """(attr_name, node) when the call mutates a ``self.X`` container
    in place: ``self.X.append(...)``, ``self.X[k].update(...)``,
    ``heapq.heappush(self.X, ...)`` — the ONE definition of the
    mutating-call surface, shared by self_mutations and the
    lock-discipline expression scan."""
    name = call_name(node)
    if name in MUTATING_METHODS and isinstance(node.func, ast.Attribute):
        # receiver is self.X or self.X[...] / self.X.Y chains:
        # attribute the mutation to the outermost self attr
        attr = _rooted_self_attr(node.func.value)
        if attr:
            yield attr, node
    elif name in ("heappush", "heappop", "heapify") and node.args:
        attr = _rooted_self_attr(node.args[0])
        if attr:
            yield attr, node


def _mutation_targets(tgt: ast.AST):
    attr = self_attr(tgt)
    if attr:
        yield attr, tgt
        return
    if isinstance(tgt, (ast.Subscript, ast.Attribute)):
        rooted = _rooted_self_attr(tgt)
        if rooted:
            yield rooted, tgt
    elif isinstance(tgt, (ast.Tuple, ast.List)):
        for elt in tgt.elts:
            yield from _mutation_targets(elt)


def _rooted_self_attr(node: ast.AST) -> str | None:
    """'x' when node is ``self.x`` possibly wrapped in further
    subscripts/attributes (``self.x[k]``, ``self.x.y[k]``)."""
    prev = None
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        prev = node
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and \
            isinstance(prev, ast.Attribute):
        return prev.attr
    return None


def walk_no_nested_functions(root: ast.AST):
    """ast.walk that does not descend into nested function/class defs
    (their bodies run in a different dynamic context)."""
    stack = [root]
    first = True
    while stack:
        node = stack.pop()
        if not first and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.Lambda, ast.ClassDef)):
            yield node  # the def itself, not its body
            continue
        first = False
        yield node
        stack.extend(ast.iter_child_nodes(node))
