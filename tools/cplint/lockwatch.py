"""lockwatch: dynamic lock-order race detection for the control plane.

The static lock-discipline pass proves mutations happen under *a* lock;
only the running system shows whether two locks are ever taken in
conflicting orders — the deadlock that strikes once a month in
production and never in a quick test. This module instruments every
``threading.Lock``/``RLock``/``Condition`` **created by controlplane
code** (creation-site filtered, so jax/logging/stdlib locks stay raw)
and maintains:

- the per-thread *held* stack, and
- a global acquisition-order graph over lock **creation sites**
  (file:line) — instances churn per Manager/queue, sites are stable.

Besides the failure classes below, every watched lock records
**contention telemetry** per creation site — acquisition count, waited
time (the gap between calling ``acquire`` and getting the lock) and
held time (acquire→release), each with totals/maxima and a log-scale
histogram. Lint mode (``CPLINT_LOCKWATCH=1``) and the cpprof contention
view (``CPPROF_LOCKS=1`` / cpbench ``--profile``) share this ONE
wrapper — there is deliberately no second instrumentation layer that
could drift from the one the lint trusts. ``contention_snapshot()`` is
the read surface; obs/prof.py turns it into /debug/profilez rows and
``cpprof_lock_*`` gauges.

Two failure classes are recorded:

- **lock-order cycle**: acquiring B while holding A inserts edge A→B;
  if the graph already proves B→…→A, the inversion is recorded with
  both stacks. Same-site self-edges (two instances of the same class
  nested) are reported separately as ``self_edges`` — they are a design
  smell, not proof of inversion, and must not fail a run.
- **held-lock apiserver write**: a FakeKube WRITE verb (create/update/
  patch/delete — reads are legitimately cache-served under locks)
  issued while the calling thread holds any watched lock created
  outside ``kube/``. A write can block on chaos latency or retry
  through a blackout; doing that under a lock starves every sibling
  worker (the scheduler's write-after-lock-drop rule, machine-checked).

Enable for a test run with ``CPLINT_LOCKWATCH=1`` (tests/conftest.py
calls :func:`install` before any controlplane import and fails the
session on recorded violations). ``install()`` is idempotent;
:class:`LockWatch` is also directly constructible for the unit tests
that build deliberate inversions.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

#: module-path fragment that opts a creation site into instrumentation
WATCH_PATH_FRAGMENT = os.sep + "controlplane" + os.sep
#: locks created inside the fake apiserver itself — held while it runs
#: its own synchronous machinery, exempt from the held-lock write check
KUBE_PATH_FRAGMENT = os.sep + "kube" + os.sep

#: FakeKube verbs gated by the held-lock check (reads are cache-served
#: under locks by design; see module docstring)
WRITE_VERBS = frozenset({"create", "update", "patch", "delete"})

#: wait/hold histogram bucket upper bounds (seconds, log scale); one
#: implicit overflow bucket rides at the end
CONTENTION_BUCKETS = (1e-5, 1e-4, 1e-3, 1e-2, 0.1, 1.0)
#: a wait below this is the uncontended fast path (two clock reads of
#: measurement overhead), not contention
CONTENDED_WAIT_S = 1e-4

#: the schedule explorer's hook (tools/cplint/schedsim.py), or None in
#: every production/test run that isn't actively exploring. When set, a
#: MODEL thread's blocking lock acquire routes through the cooperative
#: scheduler (optional yield point + try-acquire/park-until-released
#: protocol, so a lock held by a *suspended* model thread can never
#: wedge the harness) and every FakeKube verb becomes a potential
#: preemption point. Non-model threads pass straight through — the hook
#: returns None for them. One module-global load on the fast path.
SCHED = None


def set_sched(hook) -> None:
    """Install/clear the schedule-explorer hook (schedsim only)."""
    global SCHED
    SCHED = hook


def _new_site_stats() -> dict:
    # "_lock" is the per-site raw stat lock (stripped from snapshots):
    # updating the totals under the GLOBAL _g would make every watched
    # lock's acquire/release rendezvous on one process-wide lock —
    # serializing unrelated locks and distorting the very contention
    # being measured. Per-site locks only contend when the watched lock
    # itself is contended.
    return {
        "_lock": _REAL_LOCK(),
        "acquires": 0, "contended": 0,
        "wait_s": 0.0, "hold_s": 0.0,
        "wait_max_s": 0.0, "hold_max_s": 0.0,
        "wait_hist": [0] * (len(CONTENTION_BUCKETS) + 1),
        "hold_hist": [0] * (len(CONTENTION_BUCKETS) + 1),
    }


def _bucket_index(seconds: float) -> int:
    for i, bound in enumerate(CONTENTION_BUCKETS):
        if seconds <= bound:
            return i
    return len(CONTENTION_BUCKETS)


class LockWatch:
    """Acquisition-graph recorder. One global instance per process when
    installed; tests construct their own."""

    def __init__(self, mono_fn=None):
        self._g = _REAL_LOCK()           # guards the graph (a raw lock)
        self._mono = mono_fn or time.monotonic
        self._tls = threading.local()
        #: site -> set of sites acquired while holding it
        self.order: dict = {}
        #: (a, b) edges already seen (dedup for the cycle check)
        self._edges: set = set()
        self.violations: list = []       # lock-order cycles
        self.api_violations: list = []   # held-lock apiserver writes
        self.self_edges: set = set()     # same-site nesting (smell)
        #: site -> wait/hold contention stats (see _new_site_stats);
        #: guarded by _g — plain floats/ints, nanoseconds per update
        self.contention: dict = {}

    # ------------------------------------------------------------ state

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = []
            self._tls.held = held
        return held

    def held_sites(self) -> list:
        return [entry[0] for entry in self._held()]

    def lock(self, site: str):
        """A watched non-reentrant lock for ``site`` (test surface)."""
        return _WatchedLock(self, site, _REAL_LOCK())

    def rlock(self, site: str):
        return _WatchedLock(self, site, _REAL_RLOCK())

    def reset(self) -> None:
        with self._g:
            self.order.clear()
            self._edges.clear()
            self.violations.clear()
            self.api_violations.clear()
            self.self_edges.clear()
            self.contention.clear()

    def contention_snapshot(self) -> dict:
        """Copy of the per-site wait/hold stats (histogram bucket
        bounds are the module-level ``CONTENTION_BUCKETS``); obs/prof.py
        and /debug/profilez consume this. The per-site stat lock is
        stripped — readers get plain data."""
        with self._g:
            sites = list(self.contention.items())
        out = {}
        for site, st in sites:
            with st["_lock"]:
                out[site] = {
                    k: (list(v) if isinstance(v, list) else v)
                    for k, v in st.items() if k != "_lock"
                }
        return out

    # ------------------------------------------------------------ hooks

    def _site_stats(self, site: str) -> dict:
        st = self.contention.get(site)    # GIL-safe read
        if st is None:
            with self._g:
                st = self.contention.setdefault(site, _new_site_stats())
        return st

    def _note_wait(self, site: str, waited: float) -> None:
        st = self._site_stats(site)
        with st["_lock"]:
            st["acquires"] += 1
            if waited >= CONTENDED_WAIT_S:
                st["contended"] += 1
            st["wait_s"] += waited
            if waited > st["wait_max_s"]:
                st["wait_max_s"] = waited
            st["wait_hist"][_bucket_index(waited)] += 1

    def _note_hold(self, site: str, held_for: float) -> None:
        st = self._site_stats(site)
        with st["_lock"]:
            st["hold_s"] += held_for
            if held_for > st["hold_max_s"]:
                st["hold_max_s"] = held_for
            st["hold_hist"][_bucket_index(held_for)] += 1

    def note_acquire(self, site: str, lock, waited: float = 0.0) -> None:
        held = self._held()
        for entry in held:
            if entry[1] is lock:
                entry[2] += 1            # reentrant re-acquire
                return
        for entry in held:
            self._edge(entry[0], site)
        held.append([site, lock, 1, self._mono()])
        self._note_wait(site, waited)

    def note_release(self, site: str, lock) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] is lock:
                held[i][2] -= 1
                if held[i][2] <= 0:
                    held_for = self._mono() - held[i][3]
                    del held[i]
                    self._note_hold(site, held_for)
                return

    def note_api_call(self, verb: str) -> None:
        """FakeKube write entry: no non-kube watched lock may be held."""
        if verb not in WRITE_VERBS:
            return
        offending = [entry[0] for entry in self._held()
                     if KUBE_PATH_FRAGMENT not in entry[0]]
        if offending:
            with self._g:
                self.api_violations.append({
                    "kind": "held-lock-apiserver-write",
                    "verb": verb,
                    "held": offending,
                    "thread": threading.current_thread().name,
                    "stack": "".join(traceback.format_stack(limit=12)),
                })

    # ------------------------------------------------------------ graph

    def _edge(self, a: str, b: str) -> None:
        if a == b:
            with self._g:
                self.self_edges.add(a)
            return
        with self._g:
            if (a, b) in self._edges:
                return
            self._edges.add((a, b))
            self.order.setdefault(a, set()).add(b)
            path = self._path(b, a)
            if path is not None:
                self.violations.append({
                    "kind": "lock-order-cycle",
                    "edge": (a, b),
                    "cycle": [b] + path,
                    "thread": threading.current_thread().name,
                    "stack": "".join(traceback.format_stack(limit=12)),
                })

    def _path(self, src: str, dst: str) -> list | None:
        """DFS path src → dst in the order graph (caller holds _g)."""
        seen = {src}
        stack = [(src, [])]
        while stack:
            node, path = stack.pop()
            for nxt in self.order.get(node, ()):
                if nxt == dst:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # ----------------------------------------------------------- report

    def report(self) -> str:
        lines = []
        for v in self.violations:
            lines.append(
                f"lockwatch: lock-order cycle via edge "
                f"{v['edge'][0]} -> {v['edge'][1]} "
                f"(cycle {' -> '.join(v['cycle'])}) "
                f"on thread {v['thread']}\n{v['stack']}"
            )
        for v in self.api_violations:
            lines.append(
                f"lockwatch: apiserver {v['verb']} while holding "
                f"{', '.join(v['held'])} on thread {v['thread']}\n"
                f"{v['stack']}"
            )
        return "\n".join(lines)


class _WatchedLock:
    """Lock/RLock wrapper that reports to a LockWatch. Also speaks the
    private RLock protocol Condition relies on, so watched Conditions
    keep held-state correct across wait()."""

    __slots__ = ("_watch", "_site", "_inner")

    def __init__(self, watch: LockWatch, site: str, inner):
        self._watch = watch
        self._site = site
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1):
        sched = SCHED
        if sched is not None and blocking:
            # schedsim protocol: returns None off model threads (fall
            # through to the real acquire), True once the scheduler let
            # this model thread take the lock
            ok = sched.lock_acquire(self._site, self._inner)
            if ok is not None:
                self._watch.note_acquire(self._site, self, waited=0.0)
                return ok
        t0 = self._watch._mono()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._watch.note_acquire(self._site, self,
                                     waited=self._watch._mono() - t0)
        return ok

    def release(self):
        self._watch.note_release(self._site, self)
        self._inner.release()
        sched = SCHED
        if sched is not None:
            sched.lock_release(self._site, self._inner)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    # --- private RLock protocol (Condition.wait/_is_owned) ---
    # Delegates when the inner lock is an RLock; falls back to the
    # plain-Lock semantics Condition itself would use otherwise, so a
    # watched Lock handed to Condition(lock) still behaves.

    def _is_owned(self):
        fn = getattr(self._inner, "_is_owned", None)
        if fn is not None:
            return fn()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _acquire_restore(self, state):
        t0 = self._watch._mono()
        fn = getattr(self._inner, "_acquire_restore", None)
        if fn is not None:
            fn(state)
        else:
            self._inner.acquire()
        self._watch.note_acquire(self._site, self,
                                 waited=self._watch._mono() - t0)

    def _release_save(self):
        self._watch.note_release(self._site, self)
        fn = getattr(self._inner, "_release_save", None)
        if fn is not None:
            return fn()
        self._inner.release()
        return None

    def __repr__(self):  # pragma: no cover - debugging nicety
        return f"<watched {self._inner!r} from {self._site}>"


# --------------------------------------------------------- installation

_GLOBAL: LockWatch | None = None


def active() -> LockWatch | None:
    return _GLOBAL


def _creation_site(depth: int = 2) -> str | None:
    """file:line of the caller when it lives under controlplane/."""
    try:
        frame = sys._getframe(depth)
    except ValueError:  # pragma: no cover - shallow stacks
        return None
    fname = frame.f_code.co_filename
    if WATCH_PATH_FRAGMENT not in fname:
        return None
    return f"{fname}:{frame.f_lineno}"


def hook_fake_count() -> None:
    """Wrap FakeKube._count — the choke point every external request
    passes through before any lock is taken — so the active LockWatch
    sees held-lock writes and the schedule explorer (SCHED) gets a
    preemption point per apiserver verb. Idempotent; installed by
    :func:`install` and by schedsim runs that skip the threading patch."""
    from service_account_auth_improvements_tpu.controlplane.kube import (
        fake,
    )

    if getattr(fake.FakeKube._count, "_lockwatch", False):
        return
    orig_count = fake.FakeKube._count

    def counted(self, verb, *args, **kwargs):
        # *args/**kwargs: _count grew a plural parameter (APF flow
        # classification) — the hook only cares about the verb
        w = active()   # current watch, surviving uninstall/reinstall
        if w is not None:
            w.note_api_call(verb)
        sched = SCHED
        if sched is not None:
            sched.api_call(verb, args[0] if args
                           else kwargs.get("plural"))
        return orig_count(self, verb, *args, **kwargs)

    counted._lockwatch = True  # marker so double-install can't stack
    fake.FakeKube._count = counted


def install() -> LockWatch:
    """Patch threading.Lock/RLock/Condition with creation-site-filtered
    watched variants and hook FakeKube's request choke point. Idempotent;
    returns the process-global LockWatch."""
    global _GLOBAL
    if _GLOBAL is not None:
        return _GLOBAL
    watch = LockWatch()
    _GLOBAL = watch

    def make_lock():
        site = _creation_site()
        inner = _REAL_LOCK()
        if site is None:
            return inner
        return _WatchedLock(watch, site, inner)

    def make_rlock():
        site = _creation_site()
        inner = _REAL_RLOCK()
        if site is None:
            return inner
        return _WatchedLock(watch, site, inner)

    def make_condition(lock=None):
        if lock is None:
            site = _creation_site()
            inner = _REAL_RLOCK()
            lock = (inner if site is None
                    else _WatchedLock(watch, site, inner))
        return _REAL_CONDITION(lock)

    threading.Lock = make_lock
    threading.RLock = make_rlock
    threading.Condition = make_condition

    # the apiserver choke point: FakeKube._count(verb) runs first in
    # every external request (before FakeKube's own lock is taken)
    hook_fake_count()
    return watch


def uninstall() -> None:
    """Restore the raw primitives (tests of lockwatch itself)."""
    global _GLOBAL
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION
    _GLOBAL = None
