"""mvcc-escape: stored/emitted FakeKube objects are immutable, by
machine check instead of convention.

The PR 11 copy-on-write contract (docs/fakekube.md): once an object is
committed to a stripe (``stripe.objects[key] = x``) or emitted as a
watch event (``_emit_locked`` / watch queue), it is SHARED — GET
snapshots it by reference, watch fanout is zero-copy, informer caches
hold the apiserver's own snapshots. One in-place mutation after that
point tears state for every reader. Until now a single dynamic pass
(cache-mutation, consumer side) plus convention enforced it; this pass
checks the *producer* side statically, inside ``kube/``.

Per function (kube/ scope):

- **frozen sources**: reads from stripe storage (any ``.objects``
  subscript/``.get``/``.values``/``.items`` access, including
  iteration), objects passed to ``_emit_locked`` or a watch queue
  ``put``, objects assigned INTO storage (frozen from the commit line
  on — flow order matters: stamping the RV *before* the store insert
  is the contract, after it is the bug), and ``event``/``ev``
  function parameters (watch events are shared by contract);
- **violations**: any in-place mutation of a frozen object — subscript
  or attribute write, ``del``, augmented assignment, mutating method
  calls — directly or through an alias (``meta = obj["metadata"]``);
- **sanctioned shapes**: build a successor instead. ``copy.deepcopy``
  / ``json_merge_patch`` / ``_apply_json_patch`` results are fully
  fresh (mutate freely); ``dict(x)`` / ``{**x}`` are SHALLOW — the
  top level is yours, every nested subtree is still shared, so only
  top-level writes (and writes under a slot you re-assigned to a
  fresh value first, the ``new["metadata"] = {**cur["metadata"],...}``
  idiom) are allowed.
"""

from __future__ import annotations

import ast

from tools.cplint import astutil

NAME = "mvcc-escape"
DESCRIPTION = (
    "mutation of a FakeKube object after it was committed to a stripe "
    "or emitted as a watch event"
)

SCOPE = (
    "service_account_auth_improvements_tpu/controlplane/kube",
)

#: fully-fresh constructors: the result shares nothing with its source
DEEP_FRESH = frozenset({"deepcopy", "json_merge_patch",
                        "_apply_json_patch"})
#: shallow constructors: top level fresh, subtrees shared
SHALLOW_FRESH = frozenset({"dict"})

#: parameters that carry shared watch events by contract
EVENT_PARAMS = frozenset({"ev", "event"})

_STATE_FRESH = "fresh"      # owns everything
_STATE_SHALLOW = "shallow"  # owns the top level only
_STATE_FROZEN = "frozen"    # owns nothing


def run(ctx) -> list:
    findings = []
    for path in ctx.files(*SCOPE):
        parsed = ctx.parse(path)
        if parsed is None:
            continue
        tree, _ = parsed
        for fn in astutil.iter_functions(tree):
            findings.extend(_check_function(ctx, path, fn))
    return findings


def _reads_storage(expr: ast.AST) -> bool:
    """``stripe.objects.get(k)`` / ``stripe.objects[k]`` /
    ``s.objects.values()`` — any read out of stripe storage."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr == "objects":
            return True
    return False


def _is_storage_target(tgt: ast.AST) -> bool:
    """``stripe.objects[key] = ...`` — the commit itself."""
    return (isinstance(tgt, ast.Subscript)
            and isinstance(tgt.value, ast.Attribute)
            and tgt.value.attr == "objects")


def _sub_depth(node: ast.AST) -> tuple[str | None, int, str | None]:
    """(root var, subscript/attr depth, first-level constant key) of a
    write target like ``x["metadata"]["labels"]``."""
    depth = 0
    first_key = None
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        depth += 1
        if isinstance(node, ast.Subscript):
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value,
                                                          str):
                first_key = sl.value
        else:
            first_key = node.attr
        node = node.value
    if isinstance(node, ast.Name):
        return node.id, depth, first_key
    return None, depth, first_key


class _Fn:
    def __init__(self, ctx, path, fn):
        self.ctx = ctx
        self.path = path
        self.fn = fn
        self.state: dict = {}       # var -> _STATE_*
        self.aliases: dict = {}     # var -> root var
        self.refreshed: dict = {}   # shallow var -> set of fresh slots
        self.findings: list = []

    def root(self, var: str | None) -> str | None:
        seen = set()
        while var in self.aliases and var not in seen:
            seen.add(var)
            var = self.aliases[var]
        return var

    def var_state(self, var: str | None) -> str | None:
        return self.state.get(self.root(var))

    def freeze(self, var: str | None) -> None:
        var = self.root(var)
        if var is not None:
            self.state[var] = _STATE_FROZEN

    def _value_state(self, expr: ast.AST):
        """(state, source_root) the assigned value confers."""
        if isinstance(expr, ast.Call):
            name = astutil.call_name(expr)
            if name in DEEP_FRESH:
                return _STATE_FRESH, None
            if name in SHALLOW_FRESH and expr.args:
                return _STATE_SHALLOW, None
            if name == "copy" and expr.func and \
                    isinstance(expr.func, ast.Attribute) and \
                    not expr.args:
                return _STATE_SHALLOW, None
            # x.get(...) / x.setdefault(...) off a tracked var: alias
            # into its subtree
            if isinstance(expr.func, ast.Attribute) and \
                    expr.func.attr in ("get", "setdefault"):
                base = astutil.base_name(expr.func.value)
                if self.var_state(base) is not None:
                    return "alias", base
            if _reads_storage(expr):
                return _STATE_FROZEN, None
            return None, None
        if isinstance(expr, ast.Dict):
            # {**x, ...}: shallow over whatever x shares
            if any(k is None for k in expr.keys):
                return _STATE_SHALLOW, None
            return _STATE_FRESH, None
        if isinstance(expr, (ast.Subscript, ast.Attribute)):
            if _reads_storage(expr):
                return _STATE_FROZEN, None
            base = astutil.base_name(expr)
            if self.var_state(base) is not None:
                return "alias", base
            return None, None
        if isinstance(expr, ast.Name):
            if expr.id in self.state or expr.id in self.aliases:
                return "alias", expr.id
            return None, None
        return None, None

    def flag(self, node, var, how: str) -> None:
        self.findings.append(self.ctx.finding(
            NAME, self.path, node.lineno,
            f"{how} — the object reachable through {var!r} is already "
            "committed to a stripe or emitted as a watch event and is "
            "SHARED with every reader; commit a successor instead "
            "(copy-on-write contract, docs/fakekube.md)",
        ))

    def check_write(self, tgt, node) -> None:
        var, depth, first_key = _sub_depth(tgt)
        if var is None or depth == 0:
            return
        st = self.var_state(var)
        if st is None or st == _STATE_FRESH:
            return
        rootv = self.root(var)
        if st == _STATE_FROZEN:
            self.flag(node, var,
                      f"in-place write {ast.unparse(tgt)!r}")
            return
        # shallow: depth-1 writes own the top level; deeper writes
        # escape into shared subtrees unless that slot was refreshed
        if depth == 1:
            return
        if first_key is not None and \
                first_key in self.refreshed.get(rootv, set()):
            return
        self.flag(node, var,
                  f"write through a SHALLOW copy "
                  f"{ast.unparse(tgt)!r} reaches a shared subtree")

    def note_shallow_refresh(self, tgt, value) -> None:
        """``y[K] = <fresh>`` on a shallow var makes slot K owned."""
        if not isinstance(tgt, ast.Subscript):
            return
        var, depth, first_key = _sub_depth(tgt)
        rootv = self.root(var)
        if depth != 1 or first_key is None or \
                self.var_state(var) != _STATE_SHALLOW:
            return
        vstate, _src = self._value_state(value)
        if vstate in (_STATE_FRESH, _STATE_SHALLOW):
            self.refreshed.setdefault(rootv, set()).add(first_key)

    def check_mutator_call(self, node: ast.Call) -> None:
        name = astutil.call_name(node)
        if name not in astutil.MUTATING_METHODS and name != "pop":
            return
        if not isinstance(node.func, ast.Attribute):
            return
        recv = node.func.value
        var, depth, first_key = _sub_depth(recv)
        if var is None:
            # direct Name receiver: x.update(...)
            if isinstance(recv, ast.Name):
                var, depth, first_key = recv.id, 0, None
            else:
                return
        st = self.var_state(var)
        if st is None or st == _STATE_FRESH:
            return
        rootv = self.root(var)
        if st == _STATE_FROZEN:
            self.flag(node, var, f"mutating call .{name}()")
            return
        if depth == 0:
            return   # top-level mutator on the shallow copy itself
        if first_key is not None and \
                first_key in self.refreshed.get(rootv, set()):
            return
        self.flag(node, var,
                  f"mutating call .{name}() through a SHALLOW copy "
                  "reaches a shared subtree")

    def scan(self) -> list:
        # event/ev parameters are shared watch events by contract
        args = self.fn.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            if a.arg in EVENT_PARAMS:
                self.state[a.arg] = _STATE_FROZEN
        nodes = [n for n in astutil.walk_no_nested_functions(self.fn)
                 if hasattr(n, "lineno")]
        nodes.sort(key=lambda n: (n.lineno, n.col_offset))
        for node in nodes:
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if _is_storage_target(tgt):
                        # the commit: the committed object is frozen
                        # from HERE on (stamping before the insert is
                        # the contract; after it is the bug)
                        vname = astutil.base_name(node.value)
                        self.freeze(vname)
                        continue
                    if isinstance(tgt, (ast.Subscript, ast.Attribute)):
                        self.check_write(tgt, node)
                        self.note_shallow_refresh(tgt, node.value)
                # (re)binding plain names
                vstate, src = self._value_state(node.value)
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.aliases.pop(tgt.id, None)
                        self.state.pop(tgt.id, None)
                        self.refreshed.pop(tgt.id, None)
                        if vstate == "alias":
                            self.aliases[tgt.id] = src
                        elif vstate is not None:
                            self.state[tgt.id] = vstate
                    elif isinstance(tgt, ast.Tuple):
                        # for key, obj in ...items(): handled by For
                        for elt in tgt.elts:
                            if isinstance(elt, ast.Name):
                                self.aliases.pop(elt.id, None)
                                self.state.pop(elt.id, None)
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, (ast.Subscript,
                                            ast.Attribute)):
                    self.check_write(node.target, node)
            elif isinstance(node, ast.Delete):
                for tgt in node.targets:
                    if _is_storage_target(tgt):
                        continue   # removing the key is the delete verb
                    if isinstance(tgt, (ast.Subscript, ast.Attribute)):
                        self.check_write(tgt, node)
            elif isinstance(node, ast.For):
                taints = _reads_storage(node.iter)
                names = []
                if isinstance(node.target, ast.Name):
                    names = [node.target.id]
                elif isinstance(node.target, ast.Tuple):
                    names = [e.id for e in node.target.elts
                             if isinstance(e, ast.Name)]
                for nm in names:
                    self.aliases.pop(nm, None)
                    if taints:
                        self.state[nm] = _STATE_FROZEN
                    else:
                        self.state.pop(nm, None)
            elif isinstance(node, ast.Call):
                name = astutil.call_name(node)
                if name == "_emit_locked" and len(node.args) >= 3:
                    self.check_mutator_call(node)
                    vname = astutil.base_name(node.args[2])
                    self.freeze(vname)
                elif name == "put" and node.args:
                    vname = astutil.base_name(node.args[0])
                    self.freeze(vname)
                else:
                    self.check_mutator_call(node)
        return self.findings


def _check_function(ctx, path, fn) -> list:
    return _Fn(ctx, path, fn).scan()
