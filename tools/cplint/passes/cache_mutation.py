"""cache-mutation: objects read from the informer/delegating cache are
never mutated in place.

The PR 5 contract (docs/engine.md "Read semantics"): ``CachedClient``
reads return deep copies, but *direct informer reads*
(``Informer.get/list/by_index``) hand out the live cache objects — one
in-place mutation corrupts the shared cache for every reader and every
index built over it. And even for deep-copied reads, the repo's
convention for read-modify-write is explicit: mutate a ``deepcopy`` (or
a fresh patch dict), or go through ``.live`` when the write needs the
apiserver's current state — mutating the read result in place is how
stale-write bugs start.

Taint model (per function, name-based):

- sources: ``<informer>.get/list/by_index(...)`` where the receiver
  names an informer (``*inf*`` identifier or a ``.informer(...)``
  result), and ``<kube>.get/list(...)`` whose first argument is a known
  resource plural (the delegating/cached client surface);
- propagation: direct assignment, ``["items"]`` extraction, iteration
  (``for o in <tainted>...``);
- cleansers: ``copy.deepcopy``, or re-assignment from an untainted
  expression;
- sinks: subscript writes, ``del``, mutating method calls on a tainted
  root, and ``helpers.set_condition(<tainted>, ...)`` (which mutates its
  argument).
"""

from __future__ import annotations

import ast
import re

from tools.cplint import astutil
from tools.cplint.core import CONTROLPLANE

NAME = "cache-mutation"
DESCRIPTION = (
    "in-place mutation of objects obtained from informer caches or "
    "cached-client reads"
)

SCOPE = CONTROLPLANE

#: read methods on informers that return live cache objects
INFORMER_READS = ("get", "list", "by_index")
#: read methods on clients (deep-copied, but in-place mutation of the
#: result is still the stale-write pattern the docs ban)
CLIENT_READS = ("get", "list", "by_owner")

_INFORMER_NAME = re.compile(r"(^|_)inf(ormer)?($|_)|informer")

#: mutators that take the object as first argument
ARG_MUTATORS = {"set_condition"}


def _known_plurals():
    from service_account_auth_improvements_tpu.controlplane.kube.registry import (  # noqa: E501
        DEFAULT_REGISTRY,
    )

    return {r.plural for r in DEFAULT_REGISTRY.all()}


def run(ctx) -> list:
    plurals = _known_plurals()
    findings = []
    for path in ctx.files(*SCOPE):
        parsed = ctx.parse(path)
        if parsed is None:
            continue
        tree, _ = parsed
        for fn in astutil.iter_functions(tree):
            findings.extend(_check_function(ctx, path, fn, plurals))
    return findings


def _is_informer_recv(node: ast.AST) -> bool:
    """Receiver expression that names an informer: ``inf``,
    ``self._pod_inf``, ``manager.informer("pods")``."""
    if isinstance(node, ast.Call):
        return astutil.call_name(node) == "informer"
    name = astutil.dotted(node)
    if not name:
        return False
    last = name.split(".")[-1]
    return bool(_INFORMER_NAME.search(last))


def _source_kind(node: ast.Call, plurals: set) -> str | None:
    """'informer' / 'client' when the call reads from a cache."""
    if not isinstance(node.func, ast.Attribute):
        return None
    method = node.func.attr
    recv = node.func.value
    if method in INFORMER_READS and _is_informer_recv(recv):
        return "informer"
    if method in CLIENT_READS:
        plural = astutil.str_arg(node)
        if method == "by_owner" and plural in plurals:
            return "client"
        if plural in plurals and not _through_live(recv):
            return "client"
    return None


def _through_live(recv: ast.AST) -> bool:
    """True for ``kube.live.get(...)`` / ``live_client(kube).get(...)``
    — the documented live-read escape hatch; those reads are fresh
    apiserver objects the caller owns outright."""
    name = astutil.dotted(recv)
    if name and (name.endswith(".live") or name == "live"):
        return True
    if isinstance(recv, ast.Call) and \
            astutil.call_name(recv) == "live_client":
        return True
    return False


def _check_function(ctx, path, fn, plurals) -> list:
    findings = []
    tainted: dict = {}   # var name -> (kind, source line)

    def value_taint(expr: ast.AST):
        """Taint of an assigned expression, following ["items"] /
        .get("items") extraction; deepcopy cleanses."""
        if isinstance(expr, ast.Call):
            name = astutil.call_name(expr)
            # ONLY deepcopy cleanses: a shallow .copy()/copy.copy()
            # shares every nested dict with the live cache, so mutating
            # through it corrupts the cache exactly as the bare object
            # would — the contract says "mutate a deepcopy"
            if name == "deepcopy":
                return None
            if name == "copy":
                # method form x.copy() carries x's taint; module form
                # copy.copy(x) carries x's
                if isinstance(expr.func, ast.Attribute) and \
                        not expr.args:
                    return value_taint(expr.func.value)
                if expr.args:
                    return value_taint(expr.args[0])
                return None
            kind = _source_kind(expr, plurals)
            if kind:
                return (kind, expr.lineno)
            # x = tainted.get("items", []) — dict-read off a taint
            if isinstance(expr.func, ast.Attribute) and \
                    expr.func.attr == "get":
                base = astutil.base_name(expr.func.value)
                if base in tainted:
                    return tainted[base]
            return None
        if isinstance(expr, ast.Subscript):
            base = astutil.base_name(expr)
            if base in tainted:
                return tainted[base]
            inner = expr.value
            if isinstance(inner, ast.Call):
                return value_taint(inner)
            return None
        if isinstance(expr, ast.Name):
            return tainted.get(expr.id)
        return None

    def handle_assign(targets, value):
        taint = value_taint(value)
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                if taint:
                    tainted[tgt.id] = taint
                else:
                    tainted.pop(tgt.id, None)
            elif isinstance(tgt, ast.Tuple):
                for elt in tgt.elts:
                    if isinstance(elt, ast.Name):
                        tainted.pop(elt.id, None)

    def flag(node, var, kind):
        what = ("the live informer cache" if kind == "informer"
                else "a cached-client read")
        findings.append(ctx.finding(
            NAME, path, node.lineno,
            f"{var!r} was obtained from {what} and is mutated in "
            "place — deepcopy it (or read through .live) before "
            "writing",
        ))

    # approximate flow order: AST walk sorted by source position (the
    # taint map is flow-sensitive-ish — a deepcopy re-assignment must be
    # seen before the mutations that follow it). Nested defs are
    # excluded: iter_functions analyzes each with its OWN taint map, so
    # a shadowing parameter can't inherit the parent's taint
    nodes = [n for n in astutil.walk_no_nested_functions(fn)
             if hasattr(n, "lineno")]
    nodes.sort(key=lambda n: (n.lineno, n.col_offset))
    for node in nodes:
        if isinstance(node, ast.Assign):
            # mutation sink first: tainted["k"] = v
            for tgt in node.targets:
                if isinstance(tgt, (ast.Subscript, ast.Attribute)):
                    base = astutil.base_name(tgt)
                    if base in tainted:
                        flag(tgt, base, tainted[base][0])
            handle_assign(node.targets, node.value)
        elif isinstance(node, ast.AugAssign):
            if isinstance(node.target, (ast.Subscript, ast.Attribute)):
                base = astutil.base_name(node.target)
                if base in tainted:
                    flag(node.target, base, tainted[base][0])
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                base = astutil.base_name(tgt)
                if base in tainted:
                    flag(tgt, base, tainted[base][0])
        elif isinstance(node, ast.For):
            # for o in <tainted> / <tainted>["items"] / .get("items")
            taint = value_taint(node.iter)
            if taint and isinstance(node.target, ast.Name):
                tainted[node.target.id] = taint
        elif isinstance(node, ast.Call):
            name = astutil.call_name(node)
            if name in astutil.MUTATING_METHODS and \
                    isinstance(node.func, ast.Attribute):
                base = astutil.base_name(node.func.value)
                if base in tainted:
                    flag(node, base, tainted[base][0])
            elif name in ARG_MUTATORS and node.args:
                base = astutil.base_name(node.args[0])
                if base in tainted:
                    flag(node, base, tainted[base][0])
    return findings
