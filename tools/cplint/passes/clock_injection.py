"""clock-injection: modules with an injectable clock never read the
wall/monotonic clock directly.

A module that exposes a clock parameter (``now_fn`` / ``now`` /
``mono_fn`` / ``clock``) has declared that time is an INPUT — that is
what lets chaos's ``skewed_clock`` and the lease-skew tests run
deterministically. A bare ``time.time()`` / ``time.monotonic()`` /
``datetime.now()`` in the same module is a second, uninjectable clock:
under ``skewed_clock`` the two disagree and the scenario's determinism
quietly dies (the exact failure mode PR 6's lease-skew work had to
hunt).

Exemptions: the module-level ``_now``-style default helper, default
expressions (``x or time.monotonic``) that *reference* without calling,
and calls inside ``lambda`` defaults — those ARE the injection default.
"""

from __future__ import annotations

import ast

from tools.cplint import astutil
from tools.cplint.core import CONTROLPLANE

NAME = "clock-injection"
DESCRIPTION = (
    "bare time.time()/time.monotonic()/datetime.now() in modules that "
    "expose an injectable clock"
)

SCOPE = CONTROLPLANE

CLOCK_PARAMS = {"now_fn", "now", "mono_fn", "clock", "time_fn"}
#: (receiver suffix, method) pairs that read a clock
CLOCK_CALLS = (
    ("time", "time"),
    ("time", "monotonic"),
    ("datetime", "now"),
    ("datetime", "utcnow"),
)


def run(ctx) -> list:
    findings = []
    for path in ctx.files(*SCOPE):
        parsed = ctx.parse(path)
        if parsed is None:
            continue
        tree, _ = parsed
        if not _exposes_clock(tree):
            continue
        findings.extend(_check_module(ctx, path, tree))
    return findings


def _exposes_clock(tree: ast.AST) -> bool:
    for fn in astutil.iter_functions(tree):
        args = fn.args
        names = [a.arg for a in (args.posonlyargs + args.args
                                 + args.kwonlyargs)]
        if any(n in CLOCK_PARAMS for n in names):
            return True
    return False


def _default_helper_names(tree: ast.AST) -> set:
    """Module-level ``_now``/``_utcnow``-style helpers: THE designated
    defaults a clock param falls back to."""
    return {
        node.name for node in tree.body
        if isinstance(node, ast.FunctionDef)
        and node.name.lstrip("_").startswith(("now", "utcnow", "mono"))
    }


def _is_clock_call(node: ast.Call) -> bool:
    if not isinstance(node.func, ast.Attribute):
        return False
    chain = astutil.attr_chain(node.func)
    if not chain or len(chain) < 2:
        return False
    recv, method = chain[-2], chain[-1]
    return (recv, method) in CLOCK_CALLS


def _check_module(ctx, path, tree) -> list:
    findings = []
    helpers = _default_helper_names(tree)
    exempt_nodes: set = set()
    # calls inside the designated default helpers are the injection
    # default itself
    for fn in astutil.iter_functions(tree):
        if fn.name in helpers:
            for sub in ast.walk(fn):
                exempt_nodes.add(id(sub))
    # lambdas are exempt ONLY as clock-injection defaults: a lambda
    # assigned to a clock-ish attribute (``self.now = now or (lambda:
    # datetime.now(tz))``) or used as a clock param's default value.
    # A lambda in ordinary logic (a Timer callback reading time.time())
    # is a second, uninjectable clock and must still be flagged.
    def clock_attr(name):
        return bool(name and ("now" in name or "clock" in name
                              or "mono" in name))

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = []
            for tgt in node.targets:
                attr = astutil.self_attr(tgt)
                targets.append(attr or (tgt.id if isinstance(
                    tgt, ast.Name) else None))
            if any(clock_attr(t) for t in targets):
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Lambda):
                        for inner in ast.walk(sub):
                            exempt_nodes.add(id(inner))
    for fn in astutil.iter_functions(tree):
        args = fn.args
        # align trailing defaults to trailing params (positional) plus
        # kw-only defaults; exempt lambdas defaulting a clock param
        pos = args.posonlyargs + args.args
        pos_defaults = list(zip(pos[len(pos) - len(args.defaults):],
                                args.defaults))
        kw_defaults = [(p, d) for p, d in zip(args.kwonlyargs,
                                              args.kw_defaults or [])
                       if d is not None]
        for param, default in pos_defaults + kw_defaults:
            if param.arg in CLOCK_PARAMS and default is not None:
                for sub in ast.walk(default):
                    if isinstance(sub, ast.Lambda):
                        for inner in ast.walk(sub):
                            exempt_nodes.add(id(inner))
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_clock_call(node) \
                and id(node) not in exempt_nodes:
            chain = astutil.attr_chain(node.func)
            findings.append(ctx.finding(
                NAME, path, node.lineno,
                f"bare {'.'.join(chain[-2:])}() in a module that "
                "exposes an injectable clock — route it through the "
                "injected fn or chaos skewed_clock scenarios lose "
                "determinism",
            ))
    return findings
