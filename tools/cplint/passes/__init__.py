"""Pass registry: one module per pass, each exposing NAME / DESCRIPTION
/ run(ctx)."""

from tools.cplint.passes import (
    cache_mutation,
    clock_injection,
    event_reason,
    lock_discipline,
    metrics,
    queue_span,
    rbac,
)

ALL_PASSES = (
    lock_discipline,
    cache_mutation,
    queue_span,
    rbac,
    clock_injection,
    metrics,
    event_reason,
)
