"""Pass registry: one module per pass, each exposing NAME / DESCRIPTION
/ run(ctx)."""

from tools.cplint.passes import (
    autoscale_journal,
    blocking_under_lock,
    cache_mutation,
    check_then_act,
    clock_injection,
    event_reason,
    lock_discipline,
    metrics,
    mvcc_escape,
    queue_span,
    rbac,
)

ALL_PASSES = (
    lock_discipline,
    cache_mutation,
    queue_span,
    rbac,
    clock_injection,
    metrics,
    event_reason,
    blocking_under_lock,
    check_then_act,
    mvcc_escape,
    autoscale_journal,
)
