"""lock-discipline: shared state in threaded classes is touched only
under its lock.

A *threaded class* is one that creates a ``threading.Lock``/``RLock``/
``Condition`` on ``self``. For each such class the pass classifies every
in-place mutation of a ``self.X`` attribute as *locked* (inside a
``with self.<lock>:`` block, or in a method only ever called from locked
contexts, or in a method named ``*_locked`` — the repo's call-with-lock-
held convention) or *unlocked*, and flags attributes mutated **both
ways**: one racy writer is enough to corrupt every careful one.

Exemptions — each is a happens-before argument, not a loophole:

- ``__init__`` writes (construction precedes publication);
- attributes initialized to internally-synchronized types (Event,
  local, Queue, the locks themselves);
- private methods whose every call site inside the class holds the lock
  (computed to a fixpoint); a method whose NAME is referenced without a
  call (thread targets, callbacks) stays an unlocked entry point.
"""

from __future__ import annotations

import ast

from tools.cplint import astutil
from tools.cplint.core import CONTROLPLANE

NAME = "lock-discipline"
DESCRIPTION = (
    "attributes of threaded classes mutated both inside and outside "
    "their lock"
)

#: directories whose classes are analyzed
SCOPE = CONTROLPLANE


def run(ctx) -> list:
    findings = []
    for path in ctx.files(*SCOPE):
        parsed = ctx.parse(path)
        if parsed is None:
            continue
        tree, _ = parsed
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(_check_class(ctx, path, node))
    return findings


def _lock_attrs(cls: ast.ClassDef) -> set:
    """Attributes assigned a Lock/RLock/Condition anywhere in the
    class."""
    locks = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            name = astutil.call_name(node.value)
            if name in ("Lock", "RLock", "Condition"):
                for tgt in node.targets:
                    attr = astutil.self_attr(tgt)
                    if attr:
                        locks.add(attr)
    return locks


def _exempt_attrs(cls: ast.ClassDef) -> set:
    """Attributes initialized to internally-synchronized types."""
    out = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            name = astutil.call_name(node.value)
            if name in astutil.THREADSAFE_CTORS:
                for tgt in node.targets:
                    attr = astutil.self_attr(tgt)
                    if attr:
                        out.add(attr)
    return out


def _is_with_lock(item: ast.withitem, locks: set) -> bool:
    expr = item.context_expr
    # ``with self._lock:`` and ``with self._lock.something():`` both
    # count (Condition use sometimes wraps)
    attr = astutil.self_attr(expr)
    if attr in locks:
        return True
    if isinstance(expr, ast.Call):
        attr = astutil.self_attr(expr.func)
        if attr in locks:
            return True
        if isinstance(expr.func, ast.Attribute):
            inner = astutil.self_attr(expr.func.value)
            if inner in locks:
                return True
    return False


class _MethodScan:
    """Per-method classification of mutations and intra-class calls by
    lock context."""

    def __init__(self, locks: set):
        self.locks = locks
        #: attr -> list of (locked: bool, node)
        self.mutations: list = []
        #: method name -> set of contexts it is called from
        self.calls: dict = {}
        #: methods referenced without a call (thread targets, hooks)
        self.referenced: set = set()

    def scan(self, fn: ast.FunctionDef, base_locked: bool) -> None:
        self._scan_body(fn.body, base_locked)

    def _scan_body(self, stmts, locked: bool) -> None:
        for stmt in stmts:
            self._scan_stmt(stmt, locked)

    def _scan_stmt(self, stmt: ast.stmt, locked: bool) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = locked or any(
                _is_with_lock(item, self.locks) for item in stmt.items
            )
            for item in stmt.items:
                self._scan_expr(item.context_expr, locked)
            self._scan_body(stmt.body, inner)
            return
        # compound statements: recurse into bodies with the same context
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                self._scan_body(sub, locked)
        for handler in getattr(stmt, "handlers", []) or []:
            self._scan_body(handler.body, locked)
        # expressions hanging off this statement (test/targets/value)
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, ast.expr):
                self._scan_expr(node, locked)
        # mutations within this single statement (no recursion into
        # nested defs)
        if not isinstance(stmt, (ast.With, ast.AsyncWith, ast.Try,
                                 ast.If, ast.For, ast.While)):
            for attr, node in astutil.self_mutations(stmt):
                self.mutations.append((attr, locked, node))
        else:
            # compound statement headers can still mutate (for-targets);
            # scan only the header expressions already handled above
            if isinstance(stmt, ast.For):
                for attr, node in astutil.self_mutations(stmt.target):
                    self.mutations.append((attr, locked, node))

    def _scan_expr(self, expr: ast.expr, locked: bool) -> None:
        call_funcs = set()
        nodes = list(astutil.walk_no_nested_functions(expr))
        for node in nodes:
            if isinstance(node, ast.Call):
                call_funcs.add(id(node.func))
        for node in nodes:
            if isinstance(node, ast.Call):
                # intra-class call: self._helper(...)
                attr = astutil.self_attr(node.func) if isinstance(
                    node.func, ast.Attribute) else None
                if attr:
                    self.calls.setdefault(attr, set()).add(locked)
                for a, n in astutil.call_mutations(node):
                    self.mutations.append((a, locked, n))
            elif isinstance(node, ast.Attribute) and \
                    id(node) not in call_funcs:
                attr = astutil.self_attr(node)
                if attr:
                    # bare method reference (thread target / callback)
                    self.referenced.add(attr)


def _check_class(ctx, path, cls: ast.ClassDef) -> list:
    locks = _lock_attrs(cls)
    if not locks:
        return []
    exempt = _exempt_attrs(cls) | locks
    methods = [n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    scans: dict = {}
    for fn in methods:
        scan = _MethodScan(locks)
        # *_locked naming convention: the body runs with the lock held
        scan.scan(fn, base_locked=fn.name.endswith("_locked"))
        scans[fn.name] = scan

    # fixpoint: a private method whose every intra-class call site is
    # locked (and which is never referenced as a bare attribute) runs
    # with the lock held
    locked_methods = {name for name in scans if name.endswith("_locked")}
    referenced = set()
    for scan in scans.values():
        referenced |= scan.referenced
    changed = True
    while changed:
        changed = False
        for name, fn_scan in scans.items():
            if name in locked_methods or not name.startswith("_") \
                    or name.startswith("__") or name in referenced:
                continue
            contexts = set()
            called = False
            for caller, scan in scans.items():
                ctxs = scan.calls.get(name)
                if ctxs:
                    called = True
                    base_locked = caller in locked_methods
                    contexts |= {c or base_locked for c in ctxs}
            if called and contexts == {True}:
                locked_methods.add(name)
                changed = True

    # classify every mutation with method-level lock context folded in
    by_attr: dict = {}
    for fn in methods:
        scan = scans[fn.name]
        method_locked = fn.name in locked_methods
        for attr, locked, node in scan.mutations:
            if attr in exempt:
                continue
            if fn.name == "__init__":
                continue
            by_attr.setdefault(attr, []).append(
                (locked or method_locked, fn.name, node)
            )

    findings = []
    for attr, sites in sorted(by_attr.items()):
        locked_sites = [s for s in sites if s[0]]
        unlocked_sites = [s for s in sites if not s[0]]
        if locked_sites and unlocked_sites:
            _, fn_name, node = unlocked_sites[0]
            lock_names = ", ".join(sorted("self." + x for x in locks))
            findings.append(ctx.finding(
                NAME, path, node.lineno,
                f"{cls.name}.{attr} is mutated under {lock_names} "
                f"elsewhere but without it in {fn_name}() — one racy "
                "writer corrupts every locked one",
            ))
    return findings
