"""rbac-check: client call-sites vs the Role rules in manifests/.

For each controller Role (tools/cplint/rbacmap.py maps role → manifest
→ source modules) the pass extracts every ``(group, resource, verb)``
the code can issue:

- client verbs: ``X.get/list/watch/create/update/update_status/patch/
  delete/pod_logs("<plural>", ...)`` with a literal plural known to the
  resource registry (group resolved from the registry — unambiguous by
  construction);
- ``helpers.ensure(kube, "<plural>", ...)`` → get + create + update;
- informer registrations (``manager.informer``, ``watch_owned``,
  ``watch_mapped``, and each Reconciler's ``resource`` class attr) →
  list + watch.

It then diffs against the ClusterRole parsed from the manifest, in both
directions: a **missing grant** is a runtime Forbidden waiting for the
flag that enables the code path; a **dead grant** is standing privilege
nothing uses — exactly the drift RBAC reviews exist to catch.
Intentional extras carry a justification in ``ALLOWED_EXTRA``.
"""

from __future__ import annotations

import ast

from tools.cplint import astutil, rbacmap

NAME = "rbac-check"
DESCRIPTION = (
    "controller client verbs vs manifest Role rules — missing grants "
    "and dead grants"
)

#: client method -> RBAC verb (resource transformed for subresources)
VERB_METHODS = {
    "get": "get",
    "list": "list",
    "watch": "watch",
    "create": "create",
    "update": "update",
    "update_status": "update",
    "patch": "patch",
    "delete": "delete",
    "pod_logs": "get",
}

INFORMER_METHODS = {"informer": 0, "watch_owned": 1, "watch_mapped": 1}


def _registry():
    from service_account_auth_improvements_tpu.controlplane.kube.registry import (  # noqa: E501
        DEFAULT_REGISTRY,
    )

    return DEFAULT_REGISTRY


def run(ctx) -> list:
    try:
        import yaml  # noqa: F401
    except ImportError:
        # degrade loudly but don't invent findings the environment
        # can't verify
        return [ctx.finding(
            NAME, ctx.repo / "manifests", 1,
            "pyyaml unavailable — rbac-check skipped (install pyyaml "
            "to run the manifest diff)",
        )]
    registry = _registry()
    plurals = {r.plural: r for r in registry.all()}
    findings = []
    for role, cfg in rbacmap.ROLES.items():
        findings.extend(
            _check_role(ctx, role, cfg, plurals)
        )
    return findings


# ----------------------------------------------------------- extraction

def extract_uses(tree: ast.AST, plurals: dict) -> dict:
    """{(group, resource, verb): first lineno} for one module."""
    uses: dict = {}

    def note(plural: str, verb: str, lineno: int) -> None:
        res = plurals.get(plural)
        if res is None:
            return
        resource = plural
        if verb == "update" and plural.endswith("/status"):
            resource = plural
        uses.setdefault((res.group, resource, verb), lineno)

    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            # Reconciler primary resource: the manager lists+watches it
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and \
                        isinstance(stmt.value, ast.Constant) and \
                        isinstance(stmt.value.value, str):
                    names = [t.id for t in stmt.targets
                             if isinstance(t, ast.Name)]
                    if "resource" in names:
                        note(stmt.value.value, "list", stmt.lineno)
                        note(stmt.value.value, "watch", stmt.lineno)
        if not isinstance(node, ast.Call):
            continue
        name = astutil.call_name(node)
        if name in INFORMER_METHODS:
            plural = astutil.str_arg(node, INFORMER_METHODS[name])
            if plural and plural in plurals:
                note(plural, "list", node.lineno)
                note(plural, "watch", node.lineno)
            continue
        if name == "ensure":
            plural = astutil.str_arg(node, 1)
            if plural and plural in plurals:
                for verb in ("get", "create", "update"):
                    note(plural, verb, node.lineno)
            continue
        if name in VERB_METHODS and isinstance(node.func, ast.Attribute):
            plural = astutil.str_arg(node, 0)
            if not plural or plural not in plurals:
                continue
            res = plurals[plural]
            verb = VERB_METHODS[name]
            if name == "update_status":
                uses.setdefault(
                    (res.group, plural + "/status", "update"),
                    node.lineno,
                )
            elif name == "pod_logs":
                uses.setdefault((res.group, plural, "get"), node.lineno)
            else:
                note(plural, verb, node.lineno)
    return uses


def role_uses(ctx, cfg, plurals: dict) -> dict:
    uses: dict = {}
    for src in cfg["sources"]:
        for path in ctx.files(src):
            parsed = ctx.parse(path)
            if parsed is None:
                continue
            tree, _ = parsed
            for triple, lineno in extract_uses(tree, plurals).items():
                uses.setdefault(triple, (ctx.rel(path), lineno))
    return uses


# ------------------------------------------------------------ manifests

def parse_role_rules(text: str, role: str) -> tuple[set, dict]:
    """(granted triples, resource → manifest line) for the named
    ClusterRole/Role in a multi-doc YAML."""
    import yaml

    granted: set = set()
    for doc in yaml.safe_load_all(text):
        if not isinstance(doc, dict):
            continue
        if doc.get("kind") not in ("ClusterRole", "Role"):
            continue
        if (doc.get("metadata") or {}).get("name") != role:
            continue
        for rule in doc.get("rules") or []:
            groups = rule.get("apiGroups") or [""]
            for group in groups:
                for resource in rule.get("resources") or []:
                    for verb in rule.get("verbs") or []:
                        granted.add((group, resource, verb))
    # resource token -> first line mentioning it (anchor for findings
    # and for # cplint: disable= comments in the yaml)
    lines: dict = {}
    for i, raw in enumerate(text.splitlines(), 1):
        if "resources:" in raw:
            for _, resource, _ in granted:
                base = resource.split("/")[0]
                if base in raw:
                    lines.setdefault(resource, i)
    return granted, lines


# ------------------------------------------------------------ the diff

def _check_role(ctx, role: str, cfg: dict, plurals: dict) -> list:
    findings = []
    manifest = ctx.repo / cfg["manifest"]
    try:
        text = manifest.read_text()
    except OSError:
        return [ctx.finding(
            NAME, manifest, 1,
            f"manifest for role {role!r} not found",
        )]
    # manifest suppressions ride the shared comment syntax
    from tools.cplint.core import load_suppressions

    suppr = load_suppressions(text)
    granted, lines = parse_role_rules(text, role)
    if not granted:
        return [ctx.finding(
            NAME, manifest, 1,
            f"no ClusterRole/Role named {role!r} in {cfg['manifest']}",
        )]
    uses = role_uses(ctx, cfg, plurals)

    for triple in sorted(set(uses) - granted):
        group, resource, verb = triple
        src, lineno = uses[triple]
        findings.append(ctx.finding(
            NAME, ctx.repo / src, lineno,
            f"{role}: code issues {verb} on "
            f"{group or 'core'}/{resource} (first at {src}:{lineno}) "
            "but the Role does not grant it — a runtime Forbidden "
            "waiting to happen",
        ))

    for triple in sorted(granted - set(uses)):
        group, resource, verb = triple
        if (role, group, resource, verb) in rbacmap.ALLOWED_EXTRA:
            continue
        line = lines.get(resource, 1)
        f = ctx.finding(
            NAME, manifest, line,
            f"{role}: Role grants {verb} on "
            f"{group or 'core'}/{resource} but no call site uses it — "
            "dead grant (trim it, or justify in "
            "tools/cplint/rbacmap.py ALLOWED_EXTRA)",
        )
        if suppr.covers(NAME, line):
            f.suppressed = True
        findings.append(f)
    return findings
