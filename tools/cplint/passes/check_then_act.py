"""check-then-act: a decision read from the cache must not drive an
unguarded write.

The tpusched booking-stamp family, generalized: a reconciler reads
state from the informer cache (or through ``CachedClient``), decides,
and then performs a dependent apiserver write. Between the read and
the write the world moves — the cache is a *level*, not a lock. The
repo's three sanctioned shapes (docs/engine.md "When to force a live
read") are:

- **RV guard**: ``update`` of the (deep-copied) read object carries its
  ``resourceVersion`` — a stale decision dies as a ``Conflict`` and the
  level-triggered requeue re-decides. Updates are therefore exempt.
- **live confirm**: re-read through ``.live`` before committing (what
  the tpusched legacy-adoption fix did).
- **requeue path**: the function visibly re-enters on failure —
  ``add_rate_limited`` / ``add_after`` / a ``Result(requeue...)`` —
  so a raced write converges instead of silently winning.

Flagged: a ``create``/``delete``/``patch`` (the RV-*unguarded* verbs)
inside a conditional whose test involves a cache-read value, in a
function with none of the three shapes. This is deliberately a
heuristic — it proves the *shape* is present, not that the guard
actually covers the race; suppressions carry the argument when the
analysis can't see it.
"""

from __future__ import annotations

import ast

from tools.cplint import astutil
from tools.cplint.core import CONTROLPLANE
from tools.cplint.passes.cache_mutation import (
    _source_kind,
    _known_plurals,
)

NAME = "check-then-act"
DESCRIPTION = (
    "cache-read decision followed by an RV-unguarded dependent write "
    "with no live confirm or requeue path"
)

SCOPE = CONTROLPLANE
#: kube/ is the apiserver + fault-injection layer itself: its reads
#: are live by construction (there is no cache between the fake and
#: itself), so the staleness this pass hunts cannot arise there
EXEMPT_PATH_FRAGMENT = "/kube/"

#: write verbs with NO optimistic-concurrency guard: a create races
#: a concurrent create/delete, a delete races a recreate, a merge
#: patch overwrites whatever landed since the read
UNGUARDED_WRITES = frozenset({"create", "delete", "patch"})

#: calls that prove a requeue path exists in this function
REQUEUE_CALLS = frozenset({"add_rate_limited", "add_after",
                           "enqueue_after"})


def run(ctx) -> list:
    plurals = _known_plurals()
    findings = []
    for path in ctx.files(*SCOPE):
        if EXEMPT_PATH_FRAGMENT in path.as_posix():
            continue
        parsed = ctx.parse(path)
        if parsed is None:
            continue
        tree, _ = parsed
        for fn in astutil.iter_functions(tree):
            findings.extend(_check_function(ctx, path, fn, plurals))
    return findings


def _has_absolution(fn: ast.AST) -> bool:
    """Live confirm or requeue path anywhere in the function."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and node.attr == "live":
            return True
        if isinstance(node, ast.Call):
            name = astutil.call_name(node)
            if name in REQUEUE_CALLS:
                return True
            if name == "Result":
                for kw in node.keywords:
                    if kw.arg in ("requeue", "requeue_after"):
                        return True
                if node.args:
                    return True
        if isinstance(node, ast.Assign):
            # the repo's helper idiom: a function computing a
            # ``requeue_after`` for its caller's Result IS the requeue
            # path, one frame removed
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and "requeue" in tgt.id:
                    return True
        if isinstance(node, ast.Raise):
            # a raising branch re-levels through the worker's backoff —
            # the engine's error path IS a requeue path
            return True
    return False


def _names_in(expr: ast.AST) -> set:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


def _check_function(ctx, path, fn, plurals) -> list:
    if _has_absolution(fn):
        return []
    # pass 1: find cache-read tainted names (flow order, same model as
    # cache-mutation: assignment from a cache read, ["items"] hops,
    # iteration)
    tainted: set = set()
    nodes = [n for n in astutil.walk_no_nested_functions(fn)
             if hasattr(n, "lineno")]
    nodes.sort(key=lambda n: (n.lineno, n.col_offset))

    def expr_tainted(expr) -> bool:
        if isinstance(expr, ast.Call):
            if _source_kind(expr, plurals):
                return True
            if isinstance(expr.func, ast.Attribute) and \
                    expr.func.attr == "get":
                base = astutil.base_name(expr.func.value)
                return base in tainted
            return False
        if isinstance(expr, ast.Subscript):
            base = astutil.base_name(expr)
            if base in tainted:
                return True
            return isinstance(expr.value, ast.Call) and \
                expr_tainted(expr.value)
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        if isinstance(expr, ast.Attribute):
            base = astutil.base_name(expr)
            return base in tainted
        return False

    for node in nodes:
        if isinstance(node, ast.Assign):
            hit = expr_tainted(node.value)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    if hit:
                        tainted.add(tgt.id)
                    else:
                        tainted.discard(tgt.id)
        elif isinstance(node, ast.For):
            if expr_tainted(node.iter) and \
                    isinstance(node.target, ast.Name):
                tainted.add(node.target.id)
    if not tainted:
        return []
    # pass 2: conditionals whose test reads a tainted name, guarding an
    # unguarded write
    findings = []

    def scan(node, guarded_by: set):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, ast.If):
            test_names = _names_in(node.test) & tainted
            for child in node.body:
                scan(child, guarded_by | test_names)
            for child in node.orelse:
                scan(child, guarded_by | test_names)
            return
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in UNGUARDED_WRITES and guarded_by:
                plural = astutil.str_arg(node)
                if plural in plurals:
                    findings.append(ctx.finding(
                        NAME, path, node.lineno,
                        f"{node.func.attr}({plural!r}, ...) is guarded "
                        f"by cache-read value(s) "
                        f"{sorted(guarded_by)} with no live confirm, "
                        "RV guard, or requeue path — the decision can "
                        "be stale by the time the write lands (the "
                        "tpusched booking-stamp family, "
                        "docs/cplint.md)",
                    ))
        for child in ast.iter_child_nodes(node):
            scan(child, guarded_by)

    for stmt in fn.body:
        scan(stmt, set())
    return findings
