"""queue-span: paired acquire/release protocols close on ALL paths.

Three protocols, one rule — the closer must sit in a ``finally`` so an
exception (or an early return threaded past it) cannot leak the opened
resource:

- ``queue.get()`` → ``queue.done(key)``: a key popped from a
  RateLimitingQueue and never marked done stays in ``_processing``
  forever — the object can never be reconciled again (the engine's
  level-triggering silently dies for that key);
- ``span.__enter__()`` (or an un-``with``-ed ``tracer.span(...)``) →
  ``span.__exit__``/``finish()``: an unclosed span wedges the trace's
  open-context and mis-books every duration after it;
- ``lock.acquire()`` → ``lock.release()``: the classic.

The analysis is per function: when both halves of a pair appear in one
function, every closer must be inside a ``Try.finalbody``. A ``get()``
on a receiver known to be a **RateLimitingQueue** (an attribute the
file assigns from the constructor) with NO ``done()`` in the same
function is flagged outright — forgetting ``done()`` entirely is the
worst leak, and a genuine get-here/done-elsewhere hand-off requires a
``# cplint: disable=queue-span`` with a justification. Plain
``queue.Queue`` consumers (no done protocol) are not flagged.
"""

from __future__ import annotations

import ast

from tools.cplint import astutil
from tools.cplint.core import CONTROLPLANE

NAME = "queue-span"
DESCRIPTION = (
    "queue.get/done, span enter/exit and lock acquire/release closed "
    "in a finally on all paths"
)

SCOPE = CONTROLPLANE


def run(ctx) -> list:
    findings = []
    for path in ctx.files(*SCOPE):
        parsed = ctx.parse(path)
        if parsed is None:
            continue
        tree, _ = parsed
        rlq = _rate_limiting_queue_attrs(tree)
        for fn in astutil.iter_functions(tree):
            findings.extend(_check_function(ctx, path, fn, rlq))
    return findings


def _rate_limiting_queue_attrs(tree) -> set:
    """Attribute/variable names the module assigns from a
    ``RateLimitingQueue(...)`` constructor — the receivers whose
    ``get()`` carries the done() obligation."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                astutil.call_name(node.value) == "RateLimitingQueue":
            for tgt in node.targets:
                attr = astutil.self_attr(tgt)
                if attr:
                    out.add(attr)
                elif isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def _finally_nodes(fn) -> set:
    """ids of all nodes inside any Try.finalbody of THIS function —
    nested defs are analyzed as their own functions, so their tries (and
    their bodies) don't count here."""
    out = set()
    for node in astutil.walk_no_nested_functions(fn):
        if isinstance(node, ast.Try) and node.finalbody:
            for stmt in node.finalbody:
                for sub in astutil.walk_no_nested_functions(stmt):
                    out.add(id(sub))
    return out


def _queue_like(recv: ast.AST) -> str | None:
    """Dotted receiver text when it names a queue ('queue' in the last
    component, or exactly 'q')."""
    name = astutil.dotted(recv)
    if not name:
        return None
    last = name.split(".")[-1]
    if "queue" in last.lower() or last == "q":
        return name
    return None


def _lock_like(recv: ast.AST) -> str | None:
    name = astutil.dotted(recv)
    if not name:
        return None
    last = name.split(".")[-1]
    if "lock" in last.lower() or "cond" in last.lower():
        return name
    return None


def _check_function(ctx, path, fn, rlq_attrs=frozenset()) -> list:
    findings = []
    in_finally = _finally_nodes(fn)
    gets: dict = {}       # recv -> first get node
    dones: dict = {}      # recv -> list of (node, in_finally)
    acquires: dict = {}
    releases: dict = {}
    enters: dict = {}     # var/recv -> node
    exits: dict = {}      # var/recv -> list of (node, in_finally)
    span_vars: dict = {}  # var -> assign node for un-with-ed spans
    with_ctx_calls = set()

    # a closure's get() must not be satisfied by the enclosing
    # function's done() (different dynamic scopes) — iter_functions
    # yields nested defs separately, so each is analyzed on its own
    nodes = list(astutil.walk_no_nested_functions(fn))
    for node in nodes:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    with_ctx_calls.add(id(sub))
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            # s = tracer.span(...) / s = obs.span(...)
            if astutil.call_name(node.value) == "span":
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        span_vars[tgt.id] = node

    for node in nodes:
        if not isinstance(node, ast.Call) or \
                not isinstance(node.func, ast.Attribute):
            continue
        method = node.func.attr
        recv = node.func.value
        if method == "get":
            q = _queue_like(recv)
            # queue.get() / queue.get(timeout=..) — zero positional
            # args, so dict.get("key") never matches
            if q and not node.args:
                gets.setdefault(q, node)
        elif method == "done":
            q = _queue_like(recv)
            if q:
                dones.setdefault(q, []).append(
                    (node, id(node) in in_finally)
                )
        elif method == "acquire":
            lk = _lock_like(recv)
            if lk:
                acquires.setdefault(lk, node)
        elif method == "release":
            lk = _lock_like(recv)
            if lk:
                releases.setdefault(lk, []).append(
                    (node, id(node) in in_finally)
                )
        elif method == "__enter__":
            name = astutil.dotted(recv)
            if name:
                enters.setdefault(name, node)
        elif method in ("__exit__", "finish"):
            name = astutil.dotted(recv)
            if name:
                exits.setdefault(name, []).append(
                    (node, id(node) in in_finally)
                )

    for q, get_node in gets.items():
        closers = dones.get(q)
        if closers is None:
            # no done() in this function at all: flag when the receiver
            # is a known RateLimitingQueue — forgetting done() wedges
            # the key in _processing forever, the worst leak class.
            # Other queue types (queue.Queue) carry no done obligation.
            if q.split(".")[-1] in rlq_attrs:
                findings.append(ctx.finding(
                    NAME, path, get_node.lineno,
                    f"{q}.get() with no .done() in this function — the "
                    "popped key stays in _processing forever; a "
                    "get-here/done-elsewhere hand-off needs an explicit "
                    "disable with its justification",
                ))
            continue
        if not any(ok for _, ok in closers):
            findings.append(ctx.finding(
                NAME, path, get_node.lineno,
                f"{q}.get() has a matching .done() but none inside a "
                "finally — an exception between them wedges the key in "
                "_processing forever",
            ))

    for lk, acq_node in acquires.items():
        if id(acq_node) in with_ctx_calls:
            continue
        closers = releases.get(lk)
        if closers is None:
            findings.append(ctx.finding(
                NAME, path, acq_node.lineno,
                f"{lk}.acquire() with no .release() in the same "
                "function — use `with`, or suppress with the hand-off "
                "justification",
            ))
        elif not any(ok for _, ok in closers):
            findings.append(ctx.finding(
                NAME, path, acq_node.lineno,
                f"{lk}.acquire() whose .release() is not in a finally",
            ))

    for name, enter_node in enters.items():
        closers = exits.get(name)
        if not closers or not any(ok for _, ok in closers):
            findings.append(ctx.finding(
                NAME, path, enter_node.lineno,
                f"{name}.__enter__() without __exit__/finish in a "
                "finally — a raise leaks the open span/context",
            ))

    for var, assign_node in span_vars.items():
        if id(assign_node.value) in with_ctx_calls:
            continue
        if var in enters:
            continue  # handled by the enter/exit rule above
        closers = exits.get(var)
        if not closers or not any(ok for _, ok in closers):
            findings.append(ctx.finding(
                NAME, path, assign_node.lineno,
                f"span assigned to {var!r} is neither used as a "
                "context manager nor finished in a finally",
            ))
    return findings
