"""event-reason: Event reasons are module-level CamelCase constants.

Event ``reason`` strings are a queryable API surface (``kubectl get
events --field-selector reason=Preempted``, dashboards group by them)
AND a cardinality control point: the cpscope recorder's correlation
groups key on (involvedObject, type, reason), so a reason built with an
f-string fans one logical event out into unbounded Event objects —
exactly the spam the aggregator exists to prevent, manufactured one
layer up.

The rule, checked at every ``*recorder*.event(...)`` / ``.emit(...)``
call site in the controlplane scope:

- the reason argument (positional 2, after the object and type) must be
  a **Name** or **Attribute** reference — never an inline string
  literal, f-string, concatenation, %-format, ``.format()`` call, or
  boolean fallback expression containing one;
- when the Name resolves to a module-level string constant, its value
  must be CamelCase (``^[A-Z][A-Za-z0-9]*$``) — the k8s Event reason
  convention;
- Names that do NOT resolve statically (locals, parameters — e.g. the
  notebook re-emission worker forwarding the CHILD event's own reason)
  are allowed: the pass is sound, not clairvoyant, and the constant
  hoisting it enforces makes the flows it can't follow start from
  checked constants anyway.
"""

from __future__ import annotations

import ast
import re

from tools.cplint import astutil
from tools.cplint.core import CONTROLPLANE

NAME = "event-reason"
DESCRIPTION = (
    "Event reasons must be module-level CamelCase constants — no inline "
    "literals, no f-strings (cardinality control)"
)

SCOPE = CONTROLPLANE

CAMEL_RE = re.compile(r"^[A-Z][A-Za-z0-9]*$")

#: recorder method names whose reason argument is checked
RECORDER_METHODS = ("event", "emit")


def run(ctx) -> list:
    findings = []
    for path in ctx.files(*SCOPE):
        parsed = ctx.parse(path)
        if parsed is None:
            continue
        tree, _ = parsed
        findings.extend(_check_module(ctx, path, tree))
    return findings


def _module_str_constants(tree: ast.AST) -> dict:
    """{name: value} for every module-level string assignment."""
    out: dict = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = node.value.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str) \
                and isinstance(node.target, ast.Name):
            out[node.target.id] = node.value.value
    return out


def _is_recorder_call(node: ast.Call) -> bool:
    """``<something>recorder<something>.event/emit(...)`` — the receiver
    chain must mention a recorder, so Tracker.record / queue.get style
    homonyms never false-positive."""
    fn = node.func
    if not isinstance(fn, ast.Attribute) or fn.attr not in RECORDER_METHODS:
        return False
    chain = astutil.attr_chain(fn.value)
    if chain is None:
        return False
    return any("recorder" in part.lower() for part in chain)


def _reason_arg(node: ast.Call):
    """The reason argument: positional index 2 (obj, type, reason, msg)
    or the ``reason=`` keyword."""
    for kw in node.keywords:
        if kw.arg == "reason":
            return kw.value
    if len(node.args) > 2:
        return node.args[2]
    return None


def _check_module(ctx, path, tree) -> list:
    findings = []
    constants = _module_str_constants(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not _is_recorder_call(node):
            continue
        reason = _reason_arg(node)
        if reason is None:
            continue
        if isinstance(reason, ast.Constant) and \
                isinstance(reason.value, str):
            findings.append(ctx.finding(
                NAME, path, node.lineno,
                f"inline Event reason {reason.value!r} — hoist it to a "
                "module-level CamelCase constant (reasons are a "
                "queryable API surface; the catalog lives in "
                "docs/observability.md)",
            ))
        elif isinstance(reason, (ast.JoinedStr, ast.BinOp, ast.BoolOp,
                                 ast.IfExp)) or (
                isinstance(reason, ast.Call)):
            findings.append(ctx.finding(
                NAME, path, node.lineno,
                "dynamic Event reason (f-string/concatenation/"
                "fallback expression) — reasons key the recorder's "
                "correlation groups, so unbounded values defeat "
                "aggregation; bind the value to a local first if it "
                "genuinely flows from data",
            ))
        elif isinstance(reason, ast.Name):
            value = constants.get(reason.id)
            if value is not None and not CAMEL_RE.match(value):
                findings.append(ctx.finding(
                    NAME, path, node.lineno,
                    f"Event reason constant {reason.id} = {value!r} is "
                    "not CamelCase (k8s Event reason convention)",
                ))
        # unresolvable Names / Attributes: allowed (see module docstring)
    return findings
