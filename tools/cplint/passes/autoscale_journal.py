"""autoscale-journal: autoscaler decisions journal a pinned schema row.

Every ``journal.decide("autoscale", ...)`` call in the controlplane
scope must carry ``schema="autoscale/v1"`` — as a keyword whose value
is the literal string or a Name resolving to a module-level constant
holding it (``AUTOSCALE_SCHEMA`` in engine/autoscale.py is the one
definition).

Why a lint rule and not a runtime check: the decision journal is a
TRAINING surface (the sched-journal/v1 precedent — schedpolicy trains
on placement rows). An autoscale row without a pinned schema is
unharvestable the day someone builds on it, and the writer is the only
place the pin can be enforced before rows exist. The bench's
``--storm`` gate proves rows are written; this pass proves every
writer pins them.
"""

from __future__ import annotations

import ast

from tools.cplint.core import CONTROLPLANE

NAME = "autoscale-journal"
DESCRIPTION = (
    "journal.decide(\"autoscale\", ...) must pin schema=\"autoscale/v1\""
    " — decision rows are a harvest surface, unversioned rows are "
    "unharvestable"
)

SCOPE = CONTROLPLANE

AUTOSCALE_KIND = "autoscale"
AUTOSCALE_SCHEMA = "autoscale/v1"


def run(ctx) -> list:
    findings = []
    for path in ctx.files(*SCOPE):
        parsed = ctx.parse(path)
        if parsed is None:
            continue
        tree, _ = parsed
        findings.extend(_check_module(ctx, path, tree))
    return findings


def _module_str_constants(tree: ast.AST) -> dict:
    """{name: value} for every module-level string assignment."""
    out: dict = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = node.value.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str) \
                and isinstance(node.target, ast.Name):
            out[node.target.id] = node.value.value
    return out


def _is_autoscale_decide(node: ast.Call) -> bool:
    """``<anything>.decide("autoscale", ...)`` — kind is the first
    positional argument by the Journal.decide contract; a dynamic kind
    that happens to equal "autoscale" at runtime is out of reach, but
    the constant-kind idiom is what the codebase writes."""
    fn = node.func
    if not isinstance(fn, ast.Attribute) or fn.attr != "decide":
        return False
    return bool(node.args) and isinstance(node.args[0], ast.Constant) \
        and node.args[0].value == AUTOSCALE_KIND


def _check_module(ctx, path, tree) -> list:
    findings = []
    constants = _module_str_constants(tree)
    # names imported from engine.autoscale resolve to the one pinned
    # value — `from ...autoscale import AUTOSCALE_SCHEMA` is the idiom
    for node in tree.body:
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.rsplit(".", 1)[-1] == "autoscale":
            for alias in node.names:
                if alias.name == "AUTOSCALE_SCHEMA":
                    constants[alias.asname
                              or alias.name] = AUTOSCALE_SCHEMA
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) \
                or not _is_autoscale_decide(node):
            continue
        schema = None
        for kw in node.keywords:
            if kw.arg == "schema":
                schema = kw.value
        if schema is None:
            findings.append(ctx.finding(
                NAME, path, node.lineno,
                "autoscale decision journaled without schema= — pin "
                f"schema={AUTOSCALE_SCHEMA!r} (engine/autoscale.py "
                "AUTOSCALE_SCHEMA) so the rows stay harvestable",
            ))
        elif isinstance(schema, ast.Constant):
            if schema.value != AUTOSCALE_SCHEMA:
                findings.append(ctx.finding(
                    NAME, path, node.lineno,
                    f"autoscale decision pins schema={schema.value!r}, "
                    f"want {AUTOSCALE_SCHEMA!r} — one schema, one "
                    "definition (engine/autoscale.py)",
                ))
        elif isinstance(schema, ast.Name):
            value = constants.get(schema.id)
            if value is not None and value != AUTOSCALE_SCHEMA:
                findings.append(ctx.finding(
                    NAME, path, node.lineno,
                    f"autoscale decision pins schema via {schema.id} = "
                    f"{value!r}, want {AUTOSCALE_SCHEMA!r}",
                ))
        else:
            findings.append(ctx.finding(
                NAME, path, node.lineno,
                "autoscale decision schema= is a dynamic expression — "
                f"use the literal {AUTOSCALE_SCHEMA!r} or the "
                "AUTOSCALE_SCHEMA constant",
            ))
    return findings
