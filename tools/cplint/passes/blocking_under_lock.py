"""blocking-under-lock: no blocking operation inside a held
controlplane lock.

The PR 8 leader-elector finding, generalized into a pass: the LOST
transition used to do a lease GET + Event write — each with a ~30 s
HTTP timeout — on its way to ``on_lost``, keeping a deposed leader
alive into the successor's term. The same shape under a *lock* is
worse: every sibling worker parks behind a thread that is waiting on
the network, a sleep, or another thread's lifetime. Lockwatch already
bans apiserver WRITES under held locks dynamically; this pass catches
the whole family statically, reads included, before any test runs.

Flagged inside a ``with self.<lock>:`` block (or between a bare
``.acquire()`` and its ``.release()``) in a class that creates the
lock:

- ``time.sleep(...)`` — scheduled delay under a lock serializes every
  waiter behind the clock;
- ``<thread>.join(...)`` — waiting on another thread's lifetime while
  holding a lock that thread may want is a deadlock-by-design;
- apiserver I/O — any verb (``get/list/watch/create/update/patch/
  delete``) on a receiver named like a kube client (``kube``,
  ``client``, ``api``); reads block exactly as long as writes when
  chaos latency or a blackout is in play;
- HTTP/socket calls (``urlopen``, ``request``, ``getresponse``,
  ``connect``, ``sendall``, ``recv``).

Out of scope: ``kube/`` itself (the fake IS the apiserver — its own
machinery runs under its own locks by design, the same exemption
lockwatch's held-write check applies), ``Condition.wait`` on the held
lock (that RELEASES it — the sanctioned blocking-under-lock shape),
and lock-free code (no lock in scope, no finding).
"""

from __future__ import annotations

import ast

from tools.cplint import astutil
from tools.cplint.core import CONTROLPLANE

NAME = "blocking-under-lock"
DESCRIPTION = (
    "apiserver I/O, sleep, join, or socket work while holding a "
    "controlplane lock"
)

SCOPE = CONTROLPLANE
#: the fake apiserver's own machinery legitimately runs under its own
#: locks (lockwatch carves out the same exemption for held-write checks)
EXEMPT_PATH_FRAGMENT = "/kube/"

#: apiserver verbs on a kube-client-shaped receiver
KUBE_VERBS = frozenset({
    "get", "list", "watch", "create", "update", "update_status",
    "patch", "delete",
})
KUBE_RECEIVERS = frozenset({"kube", "client", "api", "live"})

#: method names that block on the network regardless of receiver
NET_CALLS = frozenset({
    "urlopen", "getresponse", "connect", "sendall", "recv",
})


def run(ctx) -> list:
    findings = []
    for path in ctx.files(*SCOPE):
        if EXEMPT_PATH_FRAGMENT in path.as_posix():
            continue
        parsed = ctx.parse(path)
        if parsed is None:
            continue
        tree, _ = parsed
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(_check_class(ctx, path, node))
    return findings


def _lock_attrs(cls: ast.ClassDef) -> set:
    locks = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call):
            name = astutil.call_name(node.value)
            if name in ("Lock", "RLock", "Condition"):
                for tgt in node.targets:
                    attr = astutil.self_attr(tgt)
                    if attr:
                        locks.add(attr)
    return locks


def _is_lock_expr(expr: ast.AST, locks: set) -> str | None:
    attr = astutil.self_attr(expr)
    if attr in locks:
        return attr
    return None


def _kube_receiver(node: ast.Call) -> bool:
    """``self.kube.get(...)``, ``kube.update(...)``,
    ``self._client.api.patch(...)`` — the receiver chain ends in a
    kube-client-shaped name."""
    if not isinstance(node.func, ast.Attribute):
        return False
    if node.func.attr not in KUBE_VERBS:
        return False
    chain = astutil.attr_chain(node.func.value)
    if not chain:
        return False
    tail = chain[-1].lstrip("_")
    return any(tail == r or tail.endswith("_" + r)
               or tail.startswith(r + "_") or tail == r + "s"
               for r in KUBE_RECEIVERS) or "kube" in tail


def _blocking_reason(node: ast.Call, held_locks: set) -> str | None:
    """Why this call blocks, or None."""
    name = astutil.call_name(node)
    chain = astutil.attr_chain(node.func) or []
    if name == "sleep" and chain and chain[0] in ("time",):
        return "time.sleep under a held lock"
    if name == "join" and isinstance(node.func, ast.Attribute):
        # only thread-ish receivers count — str.join / os.path.join
        # share the method name, so the receiver NAME is the filter
        recv = astutil.dotted(node.func.value) or ""
        tail = recv.split(".")[-1]
        if ("thread" in tail or tail in ("t", "worker")
                or tail.startswith("_t")):
            return f"{recv}.join() under a held lock"
        return None
    if name == "wait" and isinstance(node.func, ast.Attribute):
        # Condition.wait on the HELD lock releases it (sanctioned);
        # waiting on a DIFFERENT event/condition under a lock blocks
        recv_attr = astutil.self_attr(node.func.value)
        if recv_attr is not None and recv_attr not in held_locks:
            # Event.wait with no/long timeout under a lock; a short
            # timeout poll is still a hold — flag uniformly, suppress
            # with justification where intended
            return (f"self.{recv_attr}.wait() under a held lock "
                    "(only waiting on the held lock's own Condition "
                    "releases it)")
        return None
    if name in NET_CALLS:
        return f"{name}() network call under a held lock"
    if _kube_receiver(node):
        return (f"apiserver {node.func.attr}() under a held lock — "
                "a chaos latency/blackout turns this into every "
                "sibling worker parked behind one request")
    return None


class _Scanner:
    def __init__(self, ctx, path, locks):
        self.ctx = ctx
        self.path = path
        self.locks = locks
        self.findings: list = []

    def scan_body(self, stmts, held: set) -> None:
        # held threads ACROSS sibling statements (a bare .acquire()
        # poisons everything until its .release()), copied at body
        # boundaries so an inner block's acquire doesn't leak out
        held = set(held)
        for stmt in stmts:
            self.scan_stmt(stmt, held)

    def scan_stmt(self, stmt, held: set) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in stmt.items:
                lock = _is_lock_expr(item.context_expr, self.locks)
                if lock:
                    inner.add(lock)
                else:
                    self.scan_expr(item.context_expr, held)
            self.scan_body(stmt.body, inner)
            return
        # bare acquire()/release() tracking within one body
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Call):
            call = stmt.value
            if isinstance(call.func, ast.Attribute):
                recv = astutil.self_attr(call.func.value)
                if recv in self.locks:
                    if call.func.attr == "acquire":
                        held.add(recv)
                        return
                    if call.func.attr == "release":
                        held.discard(recv)
                        return
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                self.scan_body(sub, held)
        for handler in getattr(stmt, "handlers", []) or []:
            self.scan_body(handler.body, held)
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, ast.expr):
                self.scan_expr(node, held)

    def scan_expr(self, expr, held: set) -> None:
        if not held:
            return
        for node in astutil.walk_no_nested_functions(expr):
            if isinstance(node, ast.Call):
                reason = _blocking_reason(node, held)
                if reason:
                    lock_names = ", ".join(
                        sorted("self." + x for x in held))
                    self.findings.append(self.ctx.finding(
                        NAME, self.path, node.lineno,
                        f"{reason} (holding {lock_names}) — release "
                        "the lock before blocking (the tpusched "
                        "write-after-lock-drop rule, docs/cplint.md)",
                    ))


def _check_class(ctx, path, cls: ast.ClassDef) -> list:
    locks = _lock_attrs(cls)
    if not locks:
        return []
    scanner = _Scanner(ctx, path, locks)
    for fn in cls.body:
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            base = {next(iter(locks))} if fn.name.endswith("_locked") \
                and len(locks) == 1 else set()
            if fn.name.endswith("_locked") and len(locks) > 1:
                base = set(locks)   # conservative: some lock is held
            scanner.scan_body(fn.body, base)
    return scanner.findings
