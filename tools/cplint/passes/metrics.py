"""metrics: Prometheus declaration conventions (folded from
tools/metrics_lint.py — same rules, shared AST infra; the old
``python -m tools.metrics_lint`` CLI remains as a compat shim).

- counters end ``_total`` (and nothing else does);
- histograms declare buckets explicitly;
- no duplicate metric family across modules;
- ``fleet_*`` families are the cross-replica aggregation namespace:
  declared only in obs/fleet.py, and every one carries a ``replica`` or
  ``objective`` label (a fleet metric without the dimension it was
  federated over is unreadable — which replica? which SLO?).
"""

from __future__ import annotations

import ast
import pathlib

NAME = "metrics"
DESCRIPTION = (
    "Prometheus declaration conventions: _total suffixes, explicit "
    "histogram buckets, no cross-module duplicates"
)

#: where metric declarations live; tests/ is excluded on purpose — tests
#: declare throwaway metrics (including intentional duplicates)
SCAN_ROOTS = ("service_account_auth_improvements_tpu",)
METRIC_KINDS = ("Counter", "Gauge", "Histogram")


def _call_kind(node: ast.Call) -> str | None:
    fn = node.func
    name = None
    if isinstance(fn, ast.Name):
        name = fn.id
    elif isinstance(fn, ast.Attribute):
        name = fn.attr
    return name if name in METRIC_KINDS else None


def metric_calls(tree: ast.AST):
    """Yield (kind, metric_name, node) for literal-name constructions."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        kind = _call_kind(node)
        if kind is None:
            continue
        if not node.args or not isinstance(node.args[0], ast.Constant) \
                or not isinstance(node.args[0].value, str):
            continue
        yield kind, node.args[0].value, node


def _has_buckets(node: ast.Call) -> bool:
    if any(kw.arg == "buckets" for kw in node.keywords):
        return True
    # Histogram(name, help_, labels, buckets, ...) — 4th positional
    return len(node.args) >= 4


#: the one module allowed to declare fleet_* families (path suffix,
#: compared with forward slashes)
FLEET_MODULE = "obs/fleet.py"
#: a fleet metric must carry at least one of these label dimensions
FLEET_LABELS = ("replica", "objective")


def _label_names(node: ast.Call) -> tuple | None:
    """Literal label tuple of a metric construction; None when the
    labels are non-literal (dynamic labels are someone else's problem —
    this rule only judges what it can read)."""
    arg = node.args[2] if len(node.args) >= 3 else next(
        (kw.value for kw in node.keywords if kw.arg == "labels"), None)
    if arg is None:
        return ()
    if isinstance(arg, (ast.Tuple, ast.List)) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in arg.elts):
        return tuple(e.value for e in arg.elts)
    return None


def lint_file(path: pathlib.Path, repo: pathlib.Path, tree=None):
    """(findings, declarations) for one file; declarations feed the
    cross-module duplicate check. Findings are (bare_message, lineno) —
    no location prefix; the compat shim and the pass each add their own
    (the pass via Finding.format, the shim via the historical
    ``rel:line:`` string). ``tree`` lets the cplint pass hand in the
    PassContext's cached AST instead of re-reading and re-parsing."""
    findings: list = []
    decls: list = []
    try:
        rel = path.relative_to(repo)
    except ValueError:
        rel = path
    if tree is None:
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except (OSError, SyntaxError) as e:
            return [(f"unparseable: {e}", 1)], []
    for kind, name, node in metric_calls(tree):
        decls.append((name, kind, str(rel), node.lineno))
        if kind == "Counter" and not name.endswith("_total"):
            findings.append(
                (f"counter {name!r} must end with '_total'",
                 node.lineno)
            )
        if kind != "Counter" and name.endswith("_total"):
            findings.append(
                (f"{kind.lower()} {name!r} must not end with "
                 "'_total' (counters only)", node.lineno)
            )
        if kind == "Histogram" and not _has_buckets(node):
            findings.append(
                (f"histogram {name!r} must declare buckets "
                 "explicitly", node.lineno)
            )
        if name.startswith("fleet_"):
            if not str(rel).replace("\\", "/").endswith(FLEET_MODULE):
                findings.append(
                    (f"fleet metric {name!r} declared outside "
                     f"{FLEET_MODULE} — the fleet_* namespace belongs "
                     "to the cross-replica aggregator", node.lineno)
                )
            labels = _label_names(node)
            if labels is not None and not any(
                    lbl in labels for lbl in FLEET_LABELS):
                findings.append(
                    (f"fleet metric {name!r} must carry a "
                     f"{' or '.join(repr(x) for x in FLEET_LABELS)} "
                     "label (the dimension it federates over)",
                     node.lineno)
                )
    return findings, decls


def run_lint(repo: pathlib.Path) -> list:
    """All findings as (bare_message, rel_path, lineno, located)
    tuples. ``located`` distinguishes per-site findings (the shim
    prefixes them ``rel:line:``) from the cross-module duplicate
    summaries (historically printed bare)."""
    findings: list = []
    by_name: dict = {}
    for root in SCAN_ROOTS:
        base = repo / root
        if not base.exists():
            continue
        for path in sorted(base.rglob("*.py")):
            if "__pycache__" in path.parts:
                continue
            file_findings, decls = lint_file(path, repo)
            rel = str(path.relative_to(repo))
            findings += [(msg, rel, lineno, True)
                         for msg, lineno in file_findings]
            for name, kind, drel, lineno in decls:
                by_name.setdefault(name, []).append((drel, lineno, kind))
    findings += [(msg, rel, lineno, False)
                 for msg, rel, lineno in _duplicate_findings(by_name)]
    return findings


def _duplicate_findings(by_name: dict) -> list:
    """(message, rel, lineno) for metric families declared in more than
    one module — shared by run_lint (shim) and run (pass)."""
    out = []
    for name, sites in sorted(by_name.items()):
        modules = {rel for rel, _, _ in sites}
        if len(modules) > 1:
            where = ", ".join(
                f"{rel}:{lineno}" for rel, lineno, _ in sorted(sites)
            )
            first = sorted(sites)[0]
            out.append((
                f"metric {name!r} declared in multiple modules: {where}",
                first[0], first[1],
            ))
    return out


def run(ctx) -> list:
    """The cplint pass: same rules through the PassContext, so the AST
    cache is shared (no second read/parse of the tree) and the
    ``# cplint: disable=metrics`` suppression index is populated for
    every scanned file — metrics scans the whole package, beyond the
    controlplane roots the other passes parse."""
    out = []
    by_name: dict = {}
    for root in SCAN_ROOTS:
        for path in ctx.files(root):
            parsed = ctx.parse(path)
            if parsed is None:
                out.append(ctx.finding(NAME, path, 1, "unparseable"))
                continue
            tree, _ = parsed
            file_findings, decls = lint_file(path, ctx.repo, tree=tree)
            for msg, lineno in file_findings:
                out.append(ctx.finding(NAME, path, lineno, msg))
            for name, kind, drel, lineno in decls:
                by_name.setdefault(name, []).append((drel, lineno, kind))
    for msg, rel, lineno in _duplicate_findings(by_name):
        out.append(ctx.finding(NAME, ctx.repo / rel, lineno, msg))
    return out
