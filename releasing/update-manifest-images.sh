#!/bin/bash
# Pin every image reference in manifests/ to a release tag (analog of the
# reference's releasing/update-manifests-images).
#
# Usage: releasing/update-manifest-images.sh v0.1.0
set -euo pipefail

TAG="${1:?usage: update-manifest-images.sh <tag>}"
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"

grep -rl 'ghcr.io/tpukf/' "${REPO_ROOT}/manifests" | while read -r f; do
  sed -i -E "s|(ghcr\.io/tpukf/[a-z0-9-]+):[A-Za-z0-9_.-]+|\1:${TAG}|g" "$f"
done
echo "pinned manifests to ${TAG}"
git -C "${REPO_ROOT}" diff --stat -- manifests
