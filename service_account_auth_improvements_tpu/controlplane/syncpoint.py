"""Injectable sync points for the deterministic schedule explorer.

The consensus-critical modules (engine/shard.py, engine/leaderelection.py,
kube/fake.py, engine/queue.py) call :func:`sync` at the protocol
transitions whose *ordering* their correctness arguments rest on: the
optimistic-commit window between reading the current object and taking
the commit locks, queue get→done transitions, heartbeat/map-read/
barrier/ack phases of the shard handoff, lease acquire attempts. With
no hook installed the call is one module-global load and a ``None``
check — the same zero-cost-when-disabled shape as the chaos hooks
(``self.chaos is not None``), safe on every hot path.

tools/cplint/schedsim.py installs a hook that *suspends the calling
thread* at each point and lets a cooperative scheduler enumerate
interleavings (docs/cplint.md "Schedule exploration"). Nothing else in
the repo should install one; production binaries never do.

The hook contract: ``hook(label, detail)`` where ``label`` is a stable
dotted identifier (``"fake.commit"``, ``"queue.done"``, ``"shard.ack"``)
and ``detail`` an optional discriminator (plural, key) the explorer
folds into its conflict relation. The hook is called on WHATEVER thread
hit the point — schedule explorers must filter to their own model
threads and no-op for everyone else. Hooks must never raise; a raising
hook is a broken harness, not a broken plane, so ``sync`` lets the
exception propagate loudly rather than swallowing evidence.
"""

from __future__ import annotations

#: the installed hook, or None (the production state). Read directly
#: (one global load) by sync(); tests swap it via install/uninstall.
_HOOK = None


def sync(label: str, detail=None) -> None:
    """Mark a schedule-relevant transition. No-op unless a hook is
    installed (schedsim test runs only)."""
    hook = _HOOK
    if hook is not None:
        hook(label, detail)


def install(hook) -> None:
    """Install the scheduler hook (schedsim). Not reentrant — a second
    explorer in the same process must uninstall the first."""
    global _HOOK
    if _HOOK is not None and hook is not None and hook is not _HOOK:
        raise RuntimeError("a syncpoint hook is already installed")
    _HOOK = hook


def uninstall() -> None:
    global _HOOK
    _HOOK = None


def active():
    return _HOOK
