"""park_resume: checkpoint-park / scale-to-zero, measured end to end.

The parking plane (controlplane/parking + the culler's park verb + the
scheduler's oversubscription mode) promises that an idle notebook costs
zero chips and comes back on open. This family holds the whole loop to
numbers, through the REAL reconcile stack — the park store commits to
actual disk, the culler executes every park, the notebook controller
tears the pods down, and resumes re-enter tpusched admission like any
other start:

====================  ==================================================
``park_resume_cycle``  N single-host notebooks: explicit park request →
                       Parked (checkpoint committed, pods gone) →
                       resume → running again with the park state
                       cleared. Reports park/resume latency p50/p95/p99
                       and the checkpoint round-trip count (every ref
                       resumable while parked, every resume restored).
``park_resume_storm``  thundering herd: the whole fleet parks, then
                       every resume lands in ONE burst. Reports herd
                       resume percentiles + the full herd-drain time —
                       the Monday-morning scenario where everyone opens
                       their notebook at once.
``park_during_gang``   multi-host gangs vs too few pools: parking a
                       Ready gang must release its WHOLE slice (a
                       queued gang places into it), and the parked gang
                       must resume through re-admission once capacity
                       frees. 0 double-booked pools at any tick.
``park_oversubscribe`` the headline A/B: the same over-capacity tenant
                       load with oversubscription OFF (waiters queue
                       forever) vs ON (tpusched parks the coldest
                       tenant per stuck waiter). Headline metric:
                       ``oversubscription_ratio`` — chips SERVED over
                       physical chips — with create→Ready SLO
                       attainment no worse than the baseline arm's and
                       0 double bookings. Gated by ``bench_gate
                       --park``.
====================  ==================================================

Scenario knobs ride :class:`BenchConfig` unchanged; the park store lives
in a per-scenario tempdir (real ``os.rename`` commits, removed at the
end like sched_policy's checkpoint scratch).
"""

from __future__ import annotations

import datetime as dt
import re
import shutil
import tempfile
import time

from service_account_auth_improvements_tpu.controlplane.controllers.notebook import (  # noqa: E501
    GROUP,
    STOP_ANNOTATION,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.loadgen import (  # noqa: E501
    LoadGenerator,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.scenarios import (  # noqa: E501
    SCENARIOS,
    BenchConfig,
    ScenarioResult,
    _NotebookWorld,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.tracker import (  # noqa: E501
    percentiles,
)
from service_account_auth_improvements_tpu.controlplane.kube import errors
from service_account_auth_improvements_tpu.controlplane.obs import (
    slo as slo_mod,
)
from service_account_auth_improvements_tpu.controlplane import parking
from service_account_auth_improvements_tpu.controlplane import tpu as tpu_mod

#: microsecond stamps for the bench's own resume requests: the culler
#: parses both time formats, and second-granularity stamps would
#: quantize every sub-second resume latency to 0
STAMP_FMT = "%Y-%m-%dT%H:%M:%S.%fZ"


def _utcnow() -> str:
    return dt.datetime.now(dt.timezone.utc).strftime(STAMP_FMT)


_KERNELS_URL = re.compile(r"/notebook/([^/]+)/([^/]+)/api/kernels")


def _mk_park_world(cfg: BenchConfig, scenario: str, store_dir: str,
                   scheduler: bool = False,
                   oversubscribe: bool = False) -> _NotebookWorld:
    parker = parking.Parker(parking.ParkStore(store_dir))
    cell: dict = {}

    def fetch_kernels(url: str):
        # churn's probe shape: unreachable while booting (a busy answer
        # would stamp last-activity on a HALF-STARTED notebook, and the
        # scheduler would then park a gang that never reached Ready),
        # busy once running — last-activity stays fresh, so the
        # idle-cull path never fires and every park in this family is
        # an explicit request or a tpusched oversubscription decision
        m = _KERNELS_URL.search(url)
        world = cell.get("world")
        if not m or world is None:
            return None
        if _ready_replicas(world, m.group(1), m.group(2)) == 0:
            return None
        return [{"execution_state": "busy"}]

    world = _NotebookWorld(cfg, scenario, fetch_kernels=fetch_kernels,
                           scheduler=scheduler, parker=parker,
                           oversubscribe=oversubscribe)
    cell["world"] = world
    world.culler.check_period_minutes = cfg.cull_period_minutes
    if scheduler and getattr(world, "sched", None) is not None:
        # bench-speed admission retry: prod's 5s cadence would dominate
        # a seconds-scale scenario window
        world.sched.park_retry_s = 0.2
    world.parker = parker
    return world


def _annots(world, ns: str, name: str) -> dict | None:
    try:
        nb = world.cached.get("notebooks", name, namespace=ns,
                              group=GROUP)
    except errors.NotFound:
        return None
    return nb["metadata"].get("annotations") or {}


def _ready_replicas(world, ns: str, name: str) -> int:
    try:
        nb = world.cached.get("notebooks", name, namespace=ns,
                              group=GROUP)
    except errors.NotFound:
        return 0
    return (nb.get("status") or {}).get("readyReplicas") or 0


def _request_park(world, ns: str, name: str,
                  reason: str = parking.PARK_IDLE) -> None:
    world.kube.patch(
        "notebooks", name,
        {"metadata": {"annotations": {
            parking.PARK_REQUESTED_ANNOTATION: reason,
        }}}, namespace=ns, group=GROUP,
    )


def _request_resume(world, ns: str, name: str) -> None:
    # the webapp's start-a-parked-notebook patch (jupyter app.py): stop
    # cleared + resume stamped in one write
    world.kube.patch(
        "notebooks", name,
        {"metadata": {"annotations": {
            STOP_ANNOTATION: None,
            parking.RESUME_REQUESTED_ANNOTATION: _utcnow(),
        }}}, namespace=ns, group=GROUP,
    )


def _is_parked(annots: dict | None) -> bool:
    return bool(annots) and parking.PARKED_ANNOTATION in annots \
        and parking.CHECKPOINT_ANNOTATION in annots \
        and STOP_ANNOTATION in annots


def _is_resumed(world, ns: str, name: str, want_ready: int) -> bool:
    annots = _annots(world, ns, name)
    if annots is None:
        return False
    if parking.CHECKPOINT_ANNOTATION in annots or \
            parking.RESUME_REQUESTED_ANNOTATION in annots or \
            STOP_ANNOTATION in annots:
        return False
    return _ready_replicas(world, ns, name) >= want_ready


def _wait_each(names: list[str], probe, timeout: float,
               out_ms: dict[str, float], t0: dict[str, float]) -> list[str]:
    """Poll until ``probe(name)`` turns true per name, recording each
    name's latency from its ``t0`` mark. Returns the names that never
    made it (empty = success)."""
    pending = list(names)
    deadline = time.monotonic() + timeout
    while pending and time.monotonic() < deadline:
        for name in list(pending):
            if probe(name):
                out_ms[name] = (time.monotonic() - t0[name]) * 1000.0
                pending.remove(name)
        if pending:
            time.sleep(0.01)
    return pending


def _lost_checkpoints(world, ns: str, names: list[str]) -> int:
    """Parked CRs whose checkpoint ref does NOT round-trip through the
    store — the invariant the checkpoint-before-stop ordering exists to
    hold at zero."""
    lost = 0
    for name in names:
        annots = _annots(world, ns, name)
        if not _is_parked(annots):
            continue
        ref = annots.get(parking.CHECKPOINT_ANNOTATION) or ""
        if not world.parker.resumable(ref):
            lost += 1
    return lost


def _park_finish(world, cfg: BenchConfig, started: float, ok: bool,
                 extra: dict, slo_samples: dict | None = None,
                 violating=()) -> ScenarioResult:
    world.stop()
    summary = world.tracker.summary()
    summary["stage_attribution"] = world.attribution()
    extra.setdefault("gate_violations", world.actuator.gate_violations)
    extra.update(world.apiserver_extra(summary["reconciles"]))
    world.cpscope_extra(extra)
    summary["extra"] = extra
    summary["slo"] = world.slo_record(slo_samples)
    return ScenarioResult(
        name=world.tracker.scenario,
        elapsed_s=time.monotonic() - started,
        records=world.tracker.records(),
        summary=summary,
        ok=ok and summary["failed"] == 0,
        blackbox=world.blackbox(violating=violating,
                                force=not ok),
        journal_jsonl=world.journal.to_jsonl(),
    )


# -------------------------------------------------------------- scenarios

def scenario_park_resume_cycle(cfg: BenchConfig) -> ScenarioResult:
    """One full park→resume cycle per notebook, latencies per leg."""
    started = time.monotonic()
    store_dir = tempfile.mkdtemp(prefix="parkbench-")
    try:
        return _run_cycle(cfg, started, store_dir, storm=False)
    finally:
        shutil.rmtree(store_dir, ignore_errors=True)


def scenario_park_resume_storm(cfg: BenchConfig) -> ScenarioResult:
    """The whole parked fleet resumes in one burst (thundering herd)."""
    started = time.monotonic()
    store_dir = tempfile.mkdtemp(prefix="parkbench-")
    try:
        return _run_cycle(cfg, started, store_dir, storm=True)
    finally:
        shutil.rmtree(store_dir, ignore_errors=True)


def _run_cycle(cfg: BenchConfig, started: float, store_dir: str,
               storm: bool) -> ScenarioResult:
    scenario = "park_resume_storm" if storm else "park_resume_cycle"
    world = _mk_park_world(cfg, scenario, store_dir)
    try:
        return _run_cycle_in(cfg, started, world, storm)
    finally:
        world.stop()   # idempotent; covers the exception path


def _run_cycle_in(cfg: BenchConfig, started: float, world,
                  storm: bool) -> ScenarioResult:
    world.start()
    ns = "bench"
    names = [f"prk-{i:03d}" for i in range(cfg.n)]
    tpu = {"generation": "v5e", "topology": "2x2"}
    gen = LoadGenerator(cfg.concurrency, cfg.pattern, cfg.rate)
    gen.run(world.create_jobs(names, ns, tpu, want_ready=1))
    ok = world.tracker.wait_ready([(ns, n) for n in names], cfg.timeout)

    # ---- park leg: explicit requests, the culler is the executor
    park_t0: dict[str, float] = {}
    park_ms: dict[str, float] = {}

    def park_job(name):
        def run():
            park_t0[name] = time.monotonic()
            _request_park(world, ns, name)
        return run

    if storm:
        gen.run([park_job(n) for n in names])
    else:
        # paced: one park in flight at a time — clean per-op latency,
        # no herd contention in the cycle numbers
        for name in names:
            park_job(name)()
            _wait_each([name],
                       lambda n: _is_parked(_annots(world, ns, n)),
                       cfg.timeout, park_ms, park_t0)
    never_parked = _wait_each(
        [n for n in names if n not in park_ms],
        lambda n: _is_parked(_annots(world, ns, n)),
        cfg.timeout, park_ms, park_t0,
    )
    ok = ok and not never_parked

    # while parked: zero pods (the chips are actually free — the STS
    # scale-down is async, so give it a settle window) and every
    # checkpoint ref must round-trip through the store
    parked_pods = len(world.cached.list("pods", namespace=ns)["items"])
    settle_deadline = time.monotonic() + cfg.timeout
    while parked_pods and time.monotonic() < settle_deadline:
        time.sleep(0.02)
        parked_pods = len(
            world.cached.list("pods", namespace=ns)["items"])
    lost = _lost_checkpoints(world, ns, names)
    phase_parked = 0
    for name in names:
        try:
            nb = world.cached.get("notebooks", name, namespace=ns,
                                  group=GROUP)
        except errors.NotFound:
            continue
        if (nb.get("status") or {}).get("phase") == "Parked":
            phase_parked += 1

    # ---- resume leg
    resume_t0: dict[str, float] = {}
    resume_ms: dict[str, float] = {}

    def resume_job(name):
        def run():
            resume_t0[name] = time.monotonic()
            _request_resume(world, ns, name)
        return run

    herd_t0 = time.monotonic()
    if storm:
        gen.run([resume_job(n) for n in names])
    else:
        for name in names:
            resume_job(name)()
            _wait_each([name],
                       lambda n: _is_resumed(world, ns, n, 1),
                       cfg.timeout, resume_ms, resume_t0)
    never_resumed = _wait_each(
        [n for n in names if n not in resume_ms],
        lambda n: _is_resumed(world, ns, n, 1),
        cfg.timeout, resume_ms, resume_t0,
    )
    herd_drain_ms = (time.monotonic() - herd_t0) * 1000.0
    ok = ok and not never_resumed and lost == 0 and parked_pods == 0

    extra = {
        "storm": storm,
        "parked": len(park_ms),
        "resumed": len(resume_ms),
        "never_parked": never_parked,
        "never_resumed": never_resumed,
        "phase_parked": phase_parked,
        "pods_while_parked": parked_pods,
        "lost_checkpoints": lost,
        "park_ms": percentiles(list(park_ms.values())),
        "resume_ms": percentiles(list(resume_ms.values())),
        "herd_drain_ms": round(herd_drain_ms, 3) if storm else None,
    }
    violating = [(ns, n) for n in never_parked + never_resumed]
    return _park_finish(
        world, cfg, started, ok, extra,
        slo_samples={"resume_latency": list(resume_ms.values())},
        violating=violating,
    )


def scenario_park_during_gang(cfg: BenchConfig) -> ScenarioResult:
    """Gangs vs half as many pools: park the placed gangs to let the
    queued half through, then resume the parked half once the runners
    drain. Booking-release and re-admission, audited per tick."""
    started = time.monotonic()
    store_dir = tempfile.mkdtemp(prefix="parkbench-")
    world = _mk_park_world(cfg, "park_during_gang", store_dir,
                           scheduler=True)
    try:
        return _run_park_during_gang(cfg, started, world)
    finally:
        world.stop()
        shutil.rmtree(store_dir, ignore_errors=True)


def _mk_pool(kube, pool: str) -> None:
    for h in range(4):
        kube.create("nodes", {
            "metadata": {
                "name": f"node-{pool}-{h}",
                "labels": {
                    tpu_mod.SEL_NODEPOOL: pool,
                    tpu_mod.SEL_ACCELERATOR: "tpu-v5-lite-podslice",
                    tpu_mod.SEL_TOPOLOGY: "4x4",
                },
            },
            "status": {"capacity": {tpu_mod.RESOURCE_TPU: "4"}},
        })


def _pool_of(world, ns: str, name: str) -> str | None:
    annots = _annots(world, ns, name)
    return (annots or {}).get(tpu_mod.ANNOTATION_NODEPOOL)


def _audit_double_bookings(world, ns: str) -> int:
    """One cached LIST (an atomic snapshot — the sched_contention
    rationale): >1 live notebook annotated onto a one-slice pool."""
    pools: dict[str, int] = {}
    for nb in world.cached.list("notebooks", namespace=ns,
                                group=GROUP)["items"]:
        pool = (nb["metadata"].get("annotations") or {}).get(
            tpu_mod.ANNOTATION_NODEPOOL)
        if pool:
            pools[pool] = pools.get(pool, 0) + 1
    return sum(1 for n in pools.values() if n > 1)


def _run_park_during_gang(cfg: BenchConfig, started: float,
                          world) -> ScenarioResult:
    ns = "bench"
    n = max(2, cfg.n - cfg.n % 2)       # even: half place, half queue
    pools = max(1, n // 2)
    for p in range(pools):
        _mk_pool(world.kube, f"park-pool-{p}")
    world.start()
    names = [f"gpk-{i:02d}" for i in range(n)]
    tpu = {"generation": "v5e", "topology": "4x4"}
    LoadGenerator(cfg.concurrency, cfg.pattern, cfg.rate).run(
        world.create_jobs(names, ns, tpu, want_ready=4)
    )
    double_bookings = 0

    def settle(probe, timeout: float) -> bool:
        nonlocal double_bookings
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            double_bookings += _audit_double_bookings(world, ns)
            if probe():
                return True
            time.sleep(0.02)
        return False

    # phase 1: the first half places and turns Ready (pools full)
    ok = settle(
        lambda: sum(1 for nm in names
                    if _ready_replicas(world, ns, nm) >= 4) >= pools,
        cfg.timeout,
    )
    placed = [nm for nm in names if _pool_of(world, ns, nm)]

    # phase 2: park every placed gang — their slices must free and the
    # queued half must place into them and turn Ready
    park_t0 = time.monotonic()
    for name in placed:
        _request_park(world, ns, name)
    ok = settle(
        lambda: all(_is_parked(_annots(world, ns, nm))
                    for nm in placed),
        cfg.timeout,
    ) and ok
    park_to_parked_ms = (time.monotonic() - park_t0) * 1000.0
    lost = _lost_checkpoints(world, ns, placed)
    second_wave = [nm for nm in names if nm not in placed]
    ok = settle(
        lambda: all(_ready_replicas(world, ns, nm) >= 4
                    for nm in second_wave),
        cfg.timeout,
    ) and ok

    # phase 3: drain the runners, then resume the parked gangs through
    # re-admission — they must place again and return to Ready
    for name in second_wave:
        try:
            world.kube.delete("notebooks", name, namespace=ns,
                              group=GROUP)
        except errors.NotFound:
            pass
    resume_t0 = time.monotonic()
    for name in placed:
        _request_resume(world, ns, name)
    ok = settle(
        lambda: all(_is_resumed(world, ns, nm, 4) for nm in placed),
        cfg.timeout,
    ) and ok
    resume_ms = (time.monotonic() - resume_t0) * 1000.0
    ok = ok and double_bookings == 0 and lost == 0 and bool(placed)

    extra = {
        "gangs": n,
        "pools": pools,
        "parked_gangs": len(placed),
        "second_wave_served": sum(
            1 for nm in second_wave
            if (r := world.tracker.record(ns, nm)) is not None
            and r.ready is not None),
        "double_bookings": double_bookings,
        "lost_checkpoints": lost,
        "park_all_ms": round(park_to_parked_ms, 3),
        "resume_all_ms": round(resume_ms, 3),
    }
    return _park_finish(world, cfg, started, ok, extra,
                        slo_samples={"resume_latency": [resume_ms]})


def _oversub_arm(cfg: BenchConfig, oversubscribe: bool,
                 store_dir: str) -> dict:
    """One A/B arm: cfg.n 16-chip gangs vs 2 one-slice pools (32
    physical chips). With oversubscription ON, tpusched parks the
    coldest Ready tenant per stuck waiter and the whole fleet gets
    served; OFF, the queue wedges at physical capacity."""
    arm = "on" if oversubscribe else "off"
    world = _mk_park_world(cfg, f"park_oversubscribe_{arm}", store_dir,
                           scheduler=True, oversubscribe=oversubscribe)
    try:
        return _oversub_arm_in(cfg, world, oversubscribe)
    finally:
        world.stop()


def _oversub_arm_in(cfg: BenchConfig, world,
                    oversubscribe: bool) -> dict:
    ns = "bench"
    pools = 2
    physical_chips = pools * 16
    for p in range(pools):
        _mk_pool(world.kube, f"osub-pool-{p}")
    world.start()
    names = [f"osub-{i:03d}" for i in range(cfg.n)]
    tpu = {"generation": "v5e", "topology": "4x4"}
    LoadGenerator(cfg.concurrency, cfg.pattern, cfg.rate).run(
        world.create_jobs(names, ns, tpu, want_ready=4)
    )
    double_bookings = 0
    deadline = time.monotonic() + cfg.timeout
    while time.monotonic() < deadline:
        double_bookings += _audit_double_bookings(world, ns)
        served = sum(
            1 for nm in names
            if (r := world.tracker.record(ns, nm)) is not None
            and r.ready is not None
        )
        if served == len(names):
            break
        if not oversubscribe and served >= pools:
            # baseline: physical capacity is the ceiling — give the
            # queue one settle window to prove nobody else places, then
            # stop burning the bench budget on a wedge by design
            time.sleep(min(2.0, cfg.timeout / 4))
            double_bookings += _audit_double_bookings(world, ns)
            break
        time.sleep(0.02)
    served = [nm for nm in names
              if (r := world.tracker.record(ns, nm)) is not None
              and r.ready is not None]
    parked = [nm for nm in names if _is_parked(_annots(world, ns, nm))]
    lost = _lost_checkpoints(world, ns, names)
    ratio = round(len(served) * 16 / physical_chips, 3)
    world.stop()
    summary = world.tracker.summary()
    samples = [
        ms for nm in served
        if (r := world.tracker.record(ns, nm)) is not None
        and (ms := r.phase_ms().get("create_to_ready")) is not None
    ]
    slo = slo_mod.report({"create_to_ready": samples})
    attained = (slo.get("create_to_ready") or {}).get("attainment")
    return {
        "oversubscribe": oversubscribe,
        "n": cfg.n,
        "pools": pools,
        "physical_chips": physical_chips,
        "served": len(served),
        "served_chips": len(served) * 16,
        "oversubscription_ratio": ratio,
        "parked": len(parked),
        "parks_requested": int(
            world.sched.metrics.parks.value()) if world.sched else 0,
        "double_bookings": double_bookings,
        "lost_checkpoints": lost,
        "create_to_ready_ms": percentiles(samples),
        "slo_attainment": attained,
        "slo": slo,
        "_summary": summary,
        "_journal_jsonl": world.journal.to_jsonl(),
    }


def scenario_park_oversubscribe(cfg: BenchConfig) -> ScenarioResult:
    """The headline A/B — oversubscription ratio at held SLO."""
    started = time.monotonic()
    store_a = tempfile.mkdtemp(prefix="parkbench-")
    store_b = tempfile.mkdtemp(prefix="parkbench-")
    try:
        baseline = _oversub_arm(cfg, False, store_a)
        oversub = _oversub_arm(cfg, True, store_b)
    finally:
        shutil.rmtree(store_a, ignore_errors=True)
        shutil.rmtree(store_b, ignore_errors=True)
    summary = oversub.pop("_summary")
    baseline.pop("_summary")
    journal_jsonl = oversub.pop("_journal_jsonl")
    baseline.pop("_journal_jsonl")
    base_att = baseline["slo_attainment"]
    over_att = oversub["slo_attainment"]
    # the acceptance bar (ISSUE headline): ratio >= 1.5x at SLO
    # attainment no worse than the non-oversubscribed baseline
    slo_held = (over_att is None or base_att is None
                or over_att >= base_att)
    ok = (
        oversub["oversubscription_ratio"] >= 1.5
        and oversub["oversubscription_ratio"]
        > baseline["oversubscription_ratio"]
        and slo_held
        and oversub["double_bookings"] == 0
        and baseline["double_bookings"] == 0
        and oversub["lost_checkpoints"] == 0
        and oversub["served"] == cfg.n
    )
    summary = dict(summary)
    summary["extra"] = {
        "schema": "park-oversubscribe-ab/v1",
        "arms": {"baseline": baseline, "oversubscribe": oversub},
        "oversubscription_ratio": oversub["oversubscription_ratio"],
        "baseline_ratio": baseline["oversubscription_ratio"],
        "slo_attainment_held": slo_held,
        "double_bookings": (oversub["double_bookings"]
                            + baseline["double_bookings"]),
        "lost_checkpoints": oversub["lost_checkpoints"],
        "journal": {},
        "event_count": 0,
    }
    summary["slo"] = oversub["slo"]
    return ScenarioResult(
        name="park_oversubscribe",
        elapsed_s=time.monotonic() - started,
        records=[], summary=summary, ok=ok,
        journal_jsonl=journal_jsonl,
    )


PARK_SCENARIOS = {
    "park_resume_cycle": scenario_park_resume_cycle,
    "park_resume_storm": scenario_park_resume_storm,
    "park_during_gang": scenario_park_during_gang,
    "park_oversubscribe": scenario_park_oversubscribe,
}

# registration into the shared scenario table (run_scenario + the CLI)
SCENARIOS.update(PARK_SCENARIOS)
