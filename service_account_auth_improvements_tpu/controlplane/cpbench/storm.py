"""cpbench ``storm_scale`` family: trace-driven arrival load at the
100k-CR regime, the hot paths it exposed, and the autoscaler that
closes the saturation loop.

Three scenarios (docs/controlplane_bench.md "Storm scale",
tools/bench_gate.py ``--storm`` for the CI legs):

``storm_scale``      the tentpole arm. A composed arrival schedule
                     (cpbench/arrivals.py: workshop storm + diurnal
                     tide + idler tail) over tens of thousands of
                     heterogeneous tenants — 1-chip dabblers beside
                     4x4 gang trainers — drives the sharded
                     multi-replica plane; at ``--full`` this is the
                     100k-CR / 1M+-watch-event regime. Rides on a
                     hot-path A/B pair first: the SAME schedule with
                     the optimizations off (full O(pools) feasibility
                     sweep per reconcile, per-event namespace filter
                     in the FakeKube watch fanout) vs on (PoolIndex
                     shape buckets, the ``FAKEKUBE_WATCH_FASTPATH``
                     zero-copy fanout) — the optimizations are gated by the
                     recorded ratio, not vibes.
``storm_autoscale``  the saturation loop closed end to end: a fleet
                     aggregator scrapes per-replica saturation gauges
                     over real HTTP, the ``replica="fleet"`` roll-up
                     feeds engine/autoscale.py, and the autoscaler
                     scales 1→N through the EXISTING cpshard
                     join/leave protocol under a workshop storm —
                     then back down on the ebb without a flap.
                     Saturation onset → new replica covering shards
                     is the ``scale_up_latency`` SLO's sample.
``storm_chaos``      429-storm + apiserver blackout composed WITH the
                     workshop storm: no lost CRs, no dual reconciles,
                     and the autoscaler holds on missing evidence
                     (a failed scrape must never move membership)
                     and never leaves its bounds.

The reconciler here carries a real placement sweep (the tpusched hot
path) so the A/B measures the production-shaped cost, but commits
nothing: the system under test is sweep cost + fanout + queueing at
storm arrival shape, not placement correctness (cpbench/policy.py owns
that).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

from service_account_auth_improvements_tpu.controlplane.cpbench import (
    arrivals,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.ha import (
    _HAReconciler,
    _HAReplica,
    _HAWorld,
    _arm_samples,
    _wait_timeout,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.loadgen import (  # noqa: E501
    LoadGenerator,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.scenarios import (  # noqa: E501
    SCENARIOS,
    BenchConfig,
    ScenarioResult,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.tracker import (  # noqa: E501
    Tracker,
    percentiles,
)
from service_account_auth_improvements_tpu.controlplane.engine.autoscale import (  # noqa: E501
    AutoscaleConfig,
    ReplicaAutoscaler,
    drain_then_leave,
)
from service_account_auth_improvements_tpu.controlplane.metrics import (
    Gauge,
)
from service_account_auth_improvements_tpu.controlplane.obs import (
    slo as slo_mod,
)
from service_account_auth_improvements_tpu.controlplane.obs.fleet import (
    BUSY_FAMILY,
    DEPTH_FAMILY,
    FleetAggregator,
    lease_replicas_fn,
)
from service_account_auth_improvements_tpu.controlplane.scheduler import (
    Demand,
    PoolIndex,
    SlicePool,
    best_fit,
    feasible_pools,
)


@contextlib.contextmanager
def _env(name: str, value: str):
    """Scoped env toggle for the A/B arms (FAKEKUBE_WATCH_FASTPATH is
    read per watch() call, so it must hold for the arm's whole life,
    re-watches included). Arms run sequentially; no concurrency risk."""
    old = os.environ.get(name)
    os.environ[name] = value
    try:
        yield
    finally:
        if old is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = old


# ------------------------------------------------------------ inventory

def _inventory(pools_per_class: int = 16) -> dict[str, SlicePool]:
    """A fleet-scale pool inventory: the 3 tenant demand shapes plus 12
    decoy slice classes (other generations, same topologies). The decoy
    mass is the point — an un-indexed feasibility sweep pays for every
    pool in the fleet on every reconcile, the indexed sweep only for
    the shape-matched bucket (~1/15th here). 15 classes x 16 = 240
    pools."""
    shapes = [("v4", "1x1", 1, 4), ("v4", "2x2", 1, 8),
              ("v4", "4x4", 4, 4)]
    for gen in ("v2", "v3", "v5e", "v5p"):
        shapes += [(gen, "1x1", 1, 4), (gen, "2x2", 1, 8),
                   (gen, "4x4", 4, 4)]
    pools: dict[str, SlicePool] = {}
    for gen, topo, hosts, chips in shapes:
        for i in range(pools_per_class):
            name = f"{gen}-{topo}-{i:02d}"
            pools[name] = SlicePool(
                name=name, generation=gen, topology=topo,
                num_hosts=hosts, chips_per_host=chips,
            )
    return pools


#: demand per tenant profile, keyed by the 1-char code embedded in CR
#: names (``st-<code>-<seq>``) so the reconciler can recover the shape
#: from the request alone — no per-CR side table at 100k keys
_DEMANDS = {
    p.name[0]: Demand(p.generation, p.topology, p.total_chips,
                      p.num_hosts)
    for p in arrivals.DEFAULT_PROFILES
}


# ------------------------------------------------- replicas, saturated

class _StormReconciler(_HAReconciler):
    """The HA stamp-Ready reconciler with the tpusched hot path in
    front: one feasibility sweep + best-fit per reconcile over a
    fleet-scale inventory. ``index=None`` is the un-optimized arm
    (O(pools) per sweep); ``work_s`` adds actuation dwell so the
    autoscale arms can saturate a replica at bench populations."""

    # set per-world by _StormReplica on the per-replica subclass
    pools: dict = {}
    index = None
    used: dict = {}
    work_s: float = 0.0

    def reconcile(self, request):
        code = request.name.rsplit("-", 2)[-2]
        demand = _DEMANDS.get(code)
        if demand is not None and self.pools:
            feasible_pools(self.pools, self.used, demand,
                           index=self.index)
            best_fit(self.pools, self.used, demand, index=self.index)
        if self.work_s:
            # dwell only on the not-yet-Ready path: re-deliveries of a
            # stamped CR must stay cheap or the drain never ends
            try:
                obj = self.cached.get("notebooks", request.name,
                                      namespace=request.namespace,
                                      group=self.group)
            except Exception:
                obj = None
            if obj is not None \
                    and not (obj.get("status") or {}).get(
                        "readyReplicas"):
                time.sleep(self.work_s)
        return super().reconcile(request)


class _SatMirror:
    """Per-replica saturation gauges on the replica's OWN scraped
    registry. The engine's gauges of the same names live on the
    process-global registry (engine/metrics.py registers once per
    process) — correct in production where each replica IS a process,
    invisible here where N bench replicas share one. The mirror
    publishes the same numbers from the same sources (queue depth /
    worker busy ratio per controller) under the same family names, so
    the fleet aggregator's ``replica="fleet"`` roll-up reads exactly
    what a production scrape would."""

    def __init__(self, mgr, registry):
        self._mgr = mgr
        self._depth = Gauge(DEPTH_FAMILY,
                            "workqueue depth per worker", ("name",),
                            registry=registry)
        self._busy = Gauge(BUSY_FAMILY,
                           "reconcile worker busy ratio",
                           ("controller",), registry=registry)

    def publish(self) -> None:
        for ctl in self._mgr._controllers:
            workers = max(ctl.workers, 1)
            self._depth.labels(ctl.name).set(len(ctl.queue) / workers)
            self._busy.labels(ctl.name).set(ctl.busy.ratio())


class _StormReplica(_HAReplica):
    rec_base = _StormReconciler

    def __init__(self, kube, idx, world, serve=False):
        super().__init__(kube, idx, world, serve=serve)
        # the dynamic per-replica subclass ha.py builds means these are
        # per-replica class attrs, not shared mutations of the base
        cls = type(self.rec)
        cls.pools = world.pools
        cls.index = world.pool_index
        cls.used = world.pool_used
        cls.work_s = world.work_s
        self.sat = (_SatMirror(self.mgr, self.registry)
                    if self.registry is not None else None)


class _StormWorld(_HAWorld):
    """The HA world plus placement state and (optionally) elastic
    membership: in ``autoscale`` mode only replica 0 starts; the rest
    are constructed cold and join/leave through the autoscaler's
    callbacks — the same ShardRuntime join/leave path every other arm
    exercises, just driven by saturation instead of a script."""

    replica_cls = _StormReplica

    def __init__(self, cfg, tracker, replicas, *, use_index=True,
                 work_s=0.0, autoscale=False, serve=False):
        self.pools = _inventory()
        self.pool_index = PoolIndex(self.pools) if use_index else None
        self.pool_used: dict = {}
        self.work_s = work_s
        self.autoscale_mode = autoscale
        self.active: list[_StormReplica] = []
        super().__init__(cfg, tracker, replicas, serve=serve)

    def start(self) -> None:
        if not self.autoscale_mode:
            super().start()
            return
        self.active = [self.replicas[0]]
        self.replicas[0].start()
        self._ready_inf.start()
        self._ready_inf.wait_for_sync(10)

    def stop(self) -> None:
        if not self.autoscale_mode:
            super().stop()
            return
        self._ready_inf.stop()
        for r in self.replicas:
            if r in self.active:
                r.stop()
            else:
                # never started: only its ops server (brought up in
                # __init__) needs tearing down
                r._shutdown_server()

    def live_replicas(self):
        if self.autoscale_mode:
            return [r for r in self.active
                    if not r.runtime.member._stop.is_set()]
        return super().live_replicas()

    # ------------------------------------- autoscaler membership hooks

    def scale_up(self) -> bool:
        for r in self.replicas:
            if r not in self.active:
                self.active.append(r)
                r.start()
                return True
        return False

    def scale_down(self) -> bool:
        if len(self.active) <= 1:
            return False
        victim = self.active[-1]

        def drained():
            return all(
                len(c.queue) == 0 and not c.queue.processing()
                for c in victim.mgr._controllers
            )

        # the ordering contract under test in schedsim's
        # autoscale_membership model: drain BEFORE leave
        drain_then_leave(drained, victim.stop, timeout_s=10.0)
        self.active.remove(victim)
        return True


# ------------------------------------------------------------ arrivals

def _plan(n: int, span_s: float, seed: int):
    """The composed storm-tide-tail schedule with tenants assigned:
    ~45% workshop storm, ~35% diurnal tide, ~20% idler tail, merged
    and rescaled onto [0, span_s]. One tenant per ~12 arrivals keeps
    the --full run in the tens-of-thousands-of-tenants regime."""
    storm_n = max(1, int(n * 0.45))
    tide_n = max(1, int(n * 0.35))
    tail_n = max(1, n - storm_n - tide_n)
    sched = arrivals.compose(
        arrivals.workshop_storm(storm_n, window_s=span_s * 0.4,
                                seed=seed, start_s=span_s * 0.1),
        arrivals.diurnal_tide(tide_n, period_s=span_s, seed=seed + 1),
        arrivals.idler_tail(tail_n, span_s=span_s, seed=seed + 2),
    )
    offsets = arrivals.rescale(sched, span_s)[:n]
    tenants = arrivals.tenant_mix(max(8, n // 12), seed=seed)
    return arrivals.assign_tenants(offsets, tenants, seed=seed)


def _pairs_for(plan, prefix: str):
    """(namespace, name) per arrival: the profile code rides in the
    name (the reconciler's demand lookup), the tenant hashes to one of
    8 namespaces (keeps the fake striped, same as the HA spread)."""
    pairs = []
    for i, a in enumerate(plan):
        ns = f"st-{int(a.tenant[1:]) % 8}"
        pairs.append((ns, f"{prefix}-{a.profile[0]}-{i:06d}"))
    return pairs


# ------------------------------------------------------------- the arm

def _storm_arm(cfg: BenchConfig, tracker: Tracker, *, replicas: int,
               prefix: str, n: int, span_s: float, optimized: bool,
               seed: int) -> dict:
    """One measured arm: sharded world, composed arrival schedule
    paced by the loadgen, full invariant accounting. ``optimized``
    flips BOTH hot-path levers together — PoolIndex on the feasibility
    sweep and the watch-fanout fast path — because that is the A/B the
    gate grades: the plane as shipped vs the plane as found."""
    with _env("FAKEKUBE_WATCH_FASTPATH", "1" if optimized else "0"):
        world = _StormWorld(cfg, tracker, replicas,
                            use_index=optimized)
        try:
            world.start()
            covered = world.wait_covered(15)
            plan = _plan(n, span_s, seed)
            pairs = _pairs_for(plan, prefix)
            offsets = [a.offset_s for a in plan]
            t0 = time.monotonic()
            LoadGenerator(cfg.concurrency, "schedule",
                          offsets=offsets).run(
                world.create_jobs(pairs))
            arm_ok = tracker.wait_ready(
                pairs, _wait_timeout(cfg) + span_s)
            elapsed = time.monotonic() - t0
            led = world.ledger.snapshot()
            samples = _arm_samples(tracker, pairs)
            orphaned = sum(
                1 for ns, name in pairs
                if (r := tracker.record(ns, name)) is None
                or r.ready is None
            )
            delivered = world.watch_events_delivered()
            return {
                "arm": {
                    "replicas": replicas,
                    "n": n,
                    "optimized": optimized,
                    "covered_before_load": covered,
                    "span_s": round(span_s, 3),
                    "elapsed_s": round(elapsed, 3),
                    "arrival_burstiness": arrivals.burstiness(offsets),
                    "create_to_ready_ms": percentiles(samples),
                    "throughput_rps": (round(n / elapsed, 1)
                                       if elapsed else None),
                    "reconciles_by_replica": led["counts"],
                    "dual_reconciles": len(led["violations"]),
                    "orphaned_keys": orphaned,
                    "watch_events_delivered": delivered,
                    "events_per_cr": (round(delivered / n, 2)
                                      if n else None),
                    "tenants": len({a.tenant for a in plan}),
                },
                "samples": samples,
                "ok": (arm_ok and covered and not led["violations"]
                       and orphaned == 0),
                "dual": len(led["violations"]),
                "orphaned": orphaned,
            }
        finally:
            world.stop()


def scenario_storm_scale(cfg: BenchConfig) -> ScenarioResult:
    """Hot-path A/B at a tenth of the population, then the main storm
    arm at full population on 4 replicas with both optimizations on.
    --full is the 100k-CR / 1M+-watch-event acceptance arm (5 watchers
    x ~2 events per CR ~= 10 events/CR)."""
    started = time.monotonic()
    tracker = Tracker("storm_scale")

    ab_n = max(40, min(10_000, cfg.n if cfg.n <= 10_000
                       else cfg.n // 10))
    # a deliberately tight span: the submission window must not hide
    # the per-reconcile cost difference behind arrival pacing
    ab_span = max(0.5, ab_n / 5000.0)
    base = _storm_arm(cfg, tracker, replicas=2, prefix="ab0", n=ab_n,
                      span_s=ab_span, optimized=False, seed=cfg.seed)
    opt = _storm_arm(cfg, tracker, replicas=2, prefix="ab1", n=ab_n,
                     span_s=ab_span, optimized=True, seed=cfg.seed)
    b_p95 = (base["arm"]["create_to_ready_ms"] or {}).get("p95")
    o_p95 = (opt["arm"]["create_to_ready_ms"] or {}).get("p95")
    b_tput = base["arm"]["throughput_rps"]
    o_tput = opt["arm"]["throughput_rps"]
    hotpath_ab = {
        "n": ab_n,
        "baseline": base["arm"],
        "optimized": opt["arm"],
        "p95_ratio": (round(o_p95 / b_p95, 3)
                      if o_p95 and b_p95 else None),
        "throughput_ratio": (round(o_tput / b_tput, 3)
                             if o_tput and b_tput else None),
    }

    span = max(2.0, cfg.n / 2500.0)
    main = _storm_arm(cfg, tracker, replicas=4, prefix="st", n=cfg.n,
                      span_s=span, optimized=True, seed=cfg.seed + 7)

    summary = tracker.summary()
    summary["extra"] = {
        "hotpath_ab": hotpath_ab,
        "storm": main["arm"],
        "dual_reconciles": base["dual"] + opt["dual"] + main["dual"],
        "orphaned_keys": (base["orphaned"] + opt["orphaned"]
                          + main["orphaned"]),
        "event_count": 0,
        "journal": {},
    }
    summary["slo"] = slo_mod.report({"create_to_ready":
                                     main["samples"]})
    ok = base["ok"] and opt["ok"] and main["ok"]
    return ScenarioResult(
        name="storm_scale", elapsed_s=time.monotonic() - started,
        records=tracker.records(), summary=summary, ok=ok,
    )


# ----------------------------------------------------- autoscale loop

def _drive_autoscaler(world: _StormWorld, replicas_fn, agg, asc, stop,
                      up_samples: list, bounds: dict,
                      period_s: float = 0.12) -> None:
    """The coordinator loop: publish each live replica's saturation
    mirror, scrape the fleet, feed the roll-up to the autoscaler.

    The missing-evidence contract lives HERE, not in the roll-up: an
    EMPTY discovery result (lease_replicas_fn returns {} on a 503'd
    apiserver — a discovery outage is not a crash) or a partial scrape
    (a current member dark) rolls up as depth 0 / busy 0, which an
    unguarded consumer would read as "idle" and scale DOWN during the
    outage. Both feed the autoscaler None instead — the hold rule
    storm_chaos pins (docs/ha.md "Autoscaler")."""
    while not stop.is_set():
        for r in world.active:
            if r.sat is not None:
                r.sat.publish()
        try:
            if not replicas_fn():
                sat = None
            else:
                snap = agg.scrape_once()
                sat = (None if snap.get("partial")
                       else (snap.get("saturation") or {}).get("fleet"))
        except Exception:
            sat = None
        if asc._classify(sat) == "saturated" \
                and bounds.get("onset") is None:
            bounds["onset"] = time.monotonic()
        asc.observe(sat)
        n_active = len(world.active)
        bounds["lo"] = min(bounds["lo"], n_active)
        bounds["hi"] = max(bounds["hi"], n_active)
        stop.wait(period_s)


def _autoscale_world(cfg: BenchConfig, tracker: Tracker,
                     max_replicas: int, flap_window_s: float):
    """World + aggregator + autoscaler wired the production shape:
    lease-discovered replicas, HTTP scrapes, saturation roll-up,
    join/leave through cpshard. Returns (world, agg, asc, up_samples,
    bounds, driver_stop, driver_thread) — caller starts the driver."""
    world = _StormWorld(cfg, tracker, max_replicas, autoscale=True,
                        serve=True, work_s=0.02)
    replicas_fn = lease_replicas_fn(world.kube.client_for("fleet"),
                                    group=world.group,
                                    default_lease_duration=world.lease_s)
    agg = FleetAggregator(replicas_fn)
    up_samples: list[float] = []
    bounds = {"lo": max_replicas, "hi": 0, "onset": None}

    def scale_up():
        t0 = bounds.get("onset")
        world.scale_up()
        if world.wait_covered(15) and t0 is not None:
            up_samples.append((time.monotonic() - t0) * 1000.0)
        bounds["onset"] = None

    asc = ReplicaAutoscaler(
        lambda: len(world.active), scale_up, world.scale_down,
        AutoscaleConfig(min_replicas=1, max_replicas=max_replicas,
                        cooldown_s=0.8, up_consecutive=2,
                        down_consecutive=6,
                        flap_window_s=flap_window_s),
        journal=world.journal,
    )
    stop = threading.Event()
    driver = threading.Thread(
        target=_drive_autoscaler,
        args=(world, replicas_fn, agg, asc, stop, up_samples, bounds),
        name="storm-autoscaler", daemon=True)
    return world, agg, asc, up_samples, bounds, stop, driver


def _autoscale_record(asc, world, up_samples, bounds) -> dict:
    rec = asc.snapshot()
    rec.update({
        "final_replicas": len(world.active),
        "min_active_observed": bounds["lo"],
        "max_active_observed": bounds["hi"],
        "scale_up_latency_ms": percentiles(up_samples),
    })
    return rec


def _wait_scaled_down(world: _StormWorld, timeout: float) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if len(world.active) == 1:
            return True
        time.sleep(0.05)
    return len(world.active) == 1


def scenario_storm_autoscale(cfg: BenchConfig) -> ScenarioResult:
    """Workshop storm against ONE replica of a 3-replica world: the
    saturation roll-up must scale membership up through cpshard while
    the storm lands (the scale_up_latency SLO's samples: saturation
    onset -> new replica covering shards), then the ebb must scale it
    back to one replica with zero flaps."""
    started = time.monotonic()
    tracker = Tracker("storm_autoscale")
    world, agg, asc, up_samples, bounds, stop, driver = \
        _autoscale_world(cfg, tracker, max_replicas=3,
                         flap_window_s=1.6)
    try:
        world.start()
        covered = world.wait_covered(15)
        driver.start()
        # arrival rate ~2x one replica's drain rate (2 workers / 20 ms
        # dwell = ~100/s): the storm MUST saturate the single replica
        span = max(1.6, cfg.n / 140.0)
        plan = _plan(cfg.n, span, cfg.seed)
        pairs = _pairs_for(plan, "au")
        LoadGenerator(cfg.concurrency, "schedule",
                      offsets=[a.offset_s for a in plan]).run(
            world.create_jobs(pairs))
        all_ready = tracker.wait_ready(pairs, _wait_timeout(cfg) + span)
        scaled_up = bounds["hi"] > 1
        # the ebb: sustained idle must walk membership back to min
        # the ebb outlasts the BusyRatio trailing window (30 s): the
        # busy blend must decay under busy_low before idle streaks run
        ebbed = _wait_scaled_down(world, 60.0)
    finally:
        stop.set()
        driver.join(timeout=5)
        led = world.ledger.snapshot()
        world.stop()
    rec = _autoscale_record(asc, world, up_samples, bounds)
    orphaned = sum(
        1 for ns, name in pairs
        if (r := tracker.record(ns, name)) is None or r.ready is None
    )
    summary = tracker.summary()
    summary["extra"] = {
        "autoscale": rec,
        "dual_reconciles": len(led["violations"]),
        "orphaned_keys": orphaned,
        "watch_events_delivered": world.watch_events_delivered(),
        "event_count": 0,
        "journal": dict(world.journal.counts()),
    }
    summary["slo"] = slo_mod.report({
        "create_to_ready": _arm_samples(tracker, pairs),
        "scale_up_latency": up_samples,
    })
    ok = (all_ready and covered and scaled_up and ebbed
          and not led["violations"] and orphaned == 0
          and rec["flaps"] == 0 and rec["scale_ups"] >= 1
          and rec["scale_downs"] >= 1
          and bounds["hi"] <= 3 and bounds["lo"] >= 1)
    return ScenarioResult(
        name="storm_autoscale", elapsed_s=time.monotonic() - started,
        records=tracker.records(), summary=summary, ok=ok,
    )


def scenario_storm_chaos(cfg: BenchConfig) -> ScenarioResult:
    """The composed-chaos invariants: a 429 storm against the manager
    clients DURING the workshop storm, then a full apiserver blackout
    with reconciles in flight. Every CR must still reach Ready, the
    ledger must stay clean through the lease churn, and the autoscaler
    — blind while lease discovery 503s — must hold rather than move
    membership on missing evidence, and never leave its bounds."""
    started = time.monotonic()
    tracker = Tracker("storm_chaos")
    world, agg, asc, up_samples, bounds, stop, driver = \
        _autoscale_world(cfg, tracker, max_replicas=3,
                         flap_window_s=1.6)
    chaos = world.kube.enable_chaos(seed=cfg.seed)
    chaos.journal = world.journal
    try:
        world.start()
        covered = world.wait_covered(15)
        driver.start()
        span = max(1.6, cfg.n / 140.0)
        plan = _plan(cfg.n, span, cfg.seed)
        pairs = _pairs_for(plan, "ch")
        # 429s rain on the manager clients (NOT the shard clients —
        # heartbeats surviving a 429 storm is the apf/exempt story,
        # not this one) for the storm's whole window
        chaos.storm_429(clients=("manager-*",),
                        duration_s=span + 2.0, rate=0.3,
                        retry_after=1)
        LoadGenerator(cfg.concurrency, "schedule",
                      offsets=[a.offset_s for a in plan]).run(
            world.create_jobs(pairs))
        # lights out with the backlog still draining: leases expire,
        # scrapes fail, the autoscaler goes blind
        blackout_s = min(cfg.chaos_window_s, 1.5)
        chaos.start_blackout(blackout_s, sever=True)
        time.sleep(blackout_s + 0.2)
        all_ready = tracker.wait_ready(
            pairs, _wait_timeout(cfg) + span + blackout_s + 10.0)
        # the ebb outlasts the BusyRatio trailing window (30 s): the
        # busy blend must decay under busy_low before idle streaks run
        ebbed = _wait_scaled_down(world, 60.0)
    finally:
        stop.set()
        driver.join(timeout=5)
        led = world.ledger.snapshot()
        world.stop()
    rec = _autoscale_record(asc, world, up_samples, bounds)
    orphaned = sum(
        1 for ns, name in pairs
        if (r := tracker.record(ns, name)) is None or r.ready is None
    )
    held_blind = sum(
        1 for d in asc.decisions
        if d["state"] == "missing" and d["action"] == "hold"
    )
    summary = tracker.summary()
    summary["extra"] = {
        "autoscale": rec,
        "dual_reconciles": len(led["violations"]),
        "dual_reconcile_samples": led["violations"][:8],
        "orphaned_keys": orphaned,
        "blackout_s": blackout_s,
        "held_on_missing_evidence": held_blind,
        "watch_events_delivered": world.watch_events_delivered(),
        "event_count": 0,
        "journal": dict(world.journal.counts()),
    }
    summary["slo"] = slo_mod.report({
        "create_to_ready": _arm_samples(tracker, pairs),
    })
    # held_blind > 0: the blackout must actually have exercised the
    # hold-on-missing-evidence rule (~12 scrapes land inside a 1.5 s
    # window at the driver's cadence) — a run where it never went
    # blind proved nothing about outage behavior
    ok = (all_ready and covered and ebbed
          and not led["violations"] and orphaned == 0
          and rec["flaps"] == 0 and held_blind > 0
          and bounds["hi"] <= 3 and bounds["lo"] >= 1)
    return ScenarioResult(
        name="storm_chaos", elapsed_s=time.monotonic() - started,
        records=tracker.records(), summary=summary, ok=ok,
    )


STORM_SCENARIOS = {
    "storm_scale": scenario_storm_scale,
    "storm_autoscale": scenario_storm_autoscale,
    "storm_chaos": scenario_storm_chaos,
}

SCENARIOS.update(STORM_SCENARIOS)

__all__ = ["STORM_SCENARIOS", "scenario_storm_scale",
           "scenario_storm_autoscale", "scenario_storm_chaos"]
