"""cpbench ``ha_scale`` family: the sharded control plane, measured.

Three scenarios prove (and gate) the two halves of the HA work — see
docs/ha.md for the protocol and tools/bench_gate.py ``--failover`` for
the CI legs:

``ha_scale``     1/2/4-replica sweep over one FakeKube: N sharded
                 Manager replicas (engine/shard.py) reconcile the same
                 CR population, each owning a disjoint key space.
                 Reports create→Ready tail latency and per-replica
                 reconcile throughput per arm, plus the two invariants
                 every arm must hold — 0 dual reconciles (the ledger
                 wraps every replica's reconcile and records overlap),
                 0 orphaned keys (every CR reaches Ready). At ``--full``
                 this is the ROADMAP's 10k-CR / 100k-watch-event scale:
                 the 4-replica arm alone delivers ~100k watch events
                 across its informers.
``ha_failover``  leader-kill mid-drain: the replica holding the
                 coordinator Lease is killed (leases abandoned, not
                 released) while half the population is still being
                 created. The orphaned shards must be re-covered and
                 their keys reconciled within the ``failover`` SLO
                 (obs/slo.py) — per-CR create→Ready-through-the-kill
                 samples feed its p95 — with 0 dual reconciles through
                 the handoff and 0 orphaned keys.
``ha_apf``       the priority-and-fairness A/B (kube/apf.py): a
                 storming client with and without flow schemas, beside
                 a protected kubelet lane and a live watch consumer.
                 With APF on, the protected lane's p95 must hold
                 (±20% of its no-storm baseline) while the storming
                 client's throughput is measurably squeezed (429s with
                 Retry-After, honored).

The reconciler here is deliberately minimal (observe → stamp status
Ready): the system under test is the CONTROL PLANE's scale-out —
shard routing, handoff, informer fan-in, queue throughput — not the
notebook lifecycle, which every other scenario already measures.
"""

from __future__ import annotations

import copy
import threading
import time

from service_account_auth_improvements_tpu.controlplane.controllers.notebook import (  # noqa: E501
    GROUP,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.loadgen import (  # noqa: E501
    LoadGenerator,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.scenarios import (  # noqa: E501
    SCENARIOS,
    BenchConfig,
    ScenarioResult,
    _nb,
    by_client_delta,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.tracker import (  # noqa: E501
    Tracker,
    percentiles,
)
from service_account_auth_improvements_tpu.controlplane.engine import (
    Informer,
    Manager,
    Reconciler,
)
from service_account_auth_improvements_tpu.controlplane.engine.serve import (
    serve_ops,
)
from service_account_auth_improvements_tpu.controlplane.engine.shard import (
    DEFAULT_NUM_SHARDS,
    ShardRuntime,
)
from service_account_auth_improvements_tpu.controlplane.kube import (
    FakeKube,
    errors,
)
from service_account_auth_improvements_tpu.controlplane.kube.apf import (
    APF,
    FlowSchema,
    PriorityLevel,
)
from service_account_auth_improvements_tpu.controlplane.metrics import (
    Registry,
)
from service_account_auth_improvements_tpu.controlplane.obs import (
    Journal,
    Tracer,
    object_trace_id,
)
from service_account_auth_improvements_tpu.controlplane.obs import (
    slo as slo_mod,
)
from service_account_auth_improvements_tpu.controlplane.obs.fleet import (
    FleetAggregator,
    lease_replicas_fn,
)

#: shard-protocol timings for the bench worlds: short leases so the
#: failover arm measures the protocol, not a 15 s production expiry —
#: the SLO target stays the production ceiling either way
HA_LEASE_S = 1.0
HA_TICK_S = 0.1

#: the APF verdict thresholds have ONE definition — the gate's
#: (tools/bench_gate.py, stdlib-only so the import is cheap): the
#: scenario's recorded protected_held/ok and the CI leg judging the
#: same record must never disagree
from tools.bench_gate import (  # noqa: E402  (after the module docstring block above)
    APF_PROTECTED_FLOOR_MS,
    APF_PROTECTED_MAX_RATIO,
    APF_STORM_MAX_RATIO,
)


def _wait_timeout(cfg: BenchConfig) -> float:
    """Ready-wait deadline scaled to population: --full drives 10k CRs
    through a GIL'd plane — a flat 30 s would time out the healthy
    path it is trying to measure."""
    return cfg.timeout + cfg.n * 0.01


class _Ledger:
    """The dual-reconcile detector: wraps every replica's reconcile so
    any moment where two replicas run the SAME key concurrently is
    recorded as a violation — the invariant the shard handoff protocol
    exists to hold. Also the per-replica throughput ledger."""

    def __init__(self):
        self._lock = threading.Lock()
        self._inflight: dict[tuple, str] = {}
        self.violations: list[tuple] = []
        self.counts: dict[str, int] = {}

    def wrap(self, reconciler, replica: str) -> None:
        orig = reconciler.reconcile

        def wrapped(req):
            key = (req.namespace or "", req.name)
            with self._lock:
                other = self._inflight.get(key)
                if other is not None and other != replica:
                    self.violations.append((key, other, replica))
                self._inflight[key] = replica
                self.counts[replica] = self.counts.get(replica, 0) + 1
            try:
                return orig(req)
            finally:
                with self._lock:
                    if self._inflight.get(key) == replica:
                        del self._inflight[key]

        reconciler.reconcile = wrapped

    def snapshot(self) -> dict:
        with self._lock:
            return {"violations": list(self.violations),
                    "counts": dict(self.counts)}


class _HAReconciler(Reconciler):
    """Minimal level-triggered reconciler: cached read, stamp status
    Ready exactly once. Conflicts raise into the worker's backoff (the
    production retry path); a deleted key is not an error."""

    resource = "notebooks"
    group = GROUP

    def __init__(self, client, cached, tracker=None, slo=None):
        self.client = client
        self.cached = cached
        self.tracker = tracker
        self.slo = slo

    def reconcile(self, request):
        try:
            obj = self.cached.get("notebooks", request.name,
                                  namespace=request.namespace,
                                  group=GROUP)
        except errors.NotFound:
            return None
        # ADOPT the CR's trace id before any early return (uid-derived,
        # annotation honored for uid-less objects — obs/trace.py): on a
        # handed-off key the gaining replica's tracer must bind its
        # spans into the SAME trace the losing replica used, or the
        # fleet stitcher (obs/fleet.py) renders two half-lifecycles.
        # This is what the notebook controller does in production; the
        # early-return path matters because a gained already-Ready key
        # still gets a reconcile span worth attributing.
        object_trace_id("notebooks", obj)
        if (obj.get("status") or {}).get("readyReplicas"):
            return None
        obj = copy.deepcopy(obj)
        obj["status"] = {"readyReplicas": 1}
        try:
            self.client.update_status("notebooks", obj)
        except errors.NotFound:
            return None
        # the stamping replica observes create→Ready into ITS OWN SLO
        # engine — per-replica samples are the fleet aggregator's merge
        # input, and only the stamper knows the lifecycle completed here
        if self.slo is not None and self.tracker is not None:
            rec = self.tracker.record(request.namespace, request.name)
            if rec is not None and rec.created is not None:
                self.slo.observe(
                    "create_to_ready",
                    (time.monotonic() - rec.created) * 1000.0)
        return None


class _HAReplica:
    """One Manager replica of the sharded plane: tagged client, its own
    tracer (journal shared with the world), a ShardRuntime attached to
    the Manager, and a per-replica reconciler class so apiserver
    attribution and engine metrics split by replica."""

    #: reconciler base — the storm family (cpbench/storm.py) swaps in a
    #: reconciler with a placement sweep on the hot path; the dynamic
    #: per-replica subclass below is built from whatever this names
    rec_base = _HAReconciler

    def __init__(self, kube, idx: int, world: "_HAWorld",
                 serve: bool = False):
        self.identity = f"r{idx}"
        self.client = kube.client_for(f"manager-{self.identity}")
        self.trace = Tracer(max_traces=256)
        world.journal.attach(self.trace)
        self.mgr = Manager(self.client, tracer=self.trace,
                           default_workers=2)
        # fleet arms: a REAL per-replica ops server (fresh registry —
        # the process-global one is shared by every replica in this
        # process and would multi-count) whose URL the member Lease
        # advertises, exactly the production discovery path
        self.registry = self.slo = self.server = None
        self.port = None
        ops_url = None
        if serve:
            self.registry = Registry()
            self.slo = slo_mod.SloEngine(registry=self.registry)
            self.slo.attach(self.trace)
            self.server = serve_ops(0, host="127.0.0.1",
                                    registry=self.registry,
                                    tracer=self.trace, slo=self.slo)
            self.port = self.server.server_address[1]
            ops_url = f"http://127.0.0.1:{self.port}"
        self.runtime = ShardRuntime(
            kube.client_for(f"shard-{self.identity}"),
            identity=self.identity, group=world.group,
            num_shards=world.num_shards,
            lease_duration=world.lease_s, tick_period=world.tick_s,
            journal=world.journal, ops_url=ops_url,
        )
        self.mgr.attach_shard(self.runtime.member)
        rec_cls = type(f"HARec_{self.identity}", (self.rec_base,), {})
        self.rec = rec_cls(self.client, self.mgr.cached_client(),
                           tracker=world.tracker, slo=self.slo)
        world.ledger.wrap(self.rec, self.identity)
        self.mgr.add_reconciler(self.rec)
        # watch-event delivery ledger: one int cell per informer — each
        # informer dispatches from its own single thread, so a plain
        # increment is race-free and costs nothing
        self.delivered = [0]

        def count(ev_type, obj, _cell=self.delivered):
            _cell[0] += 1

        self.mgr.informer("notebooks", GROUP).add_handler(count)

    def start(self) -> None:
        self.runtime.start()
        self.mgr.start()

    def stop(self) -> None:
        self.mgr.stop()
        self.runtime.stop()
        self._shutdown_server()

    def kill(self) -> None:
        """Crash: workers/informers stop, every Lease is abandoned
        un-cleared — successors must wait out the expiry (what the
        failover arm times)."""
        self.mgr.stop()
        self.runtime.kill()
        self._shutdown_server()

    def _shutdown_server(self) -> None:
        if self.server is not None:
            self.server.shutdown()
            self.server.server_close()
            self.server = None


class _HAWorld:
    """One FakeKube + N sharded replicas + a ready-watch, for one arm."""

    #: replica class — the storm family subclasses it (placement state
    #: + per-replica saturation mirror) without copying the world
    replica_cls = _HAReplica

    def __init__(self, cfg: BenchConfig, tracker: Tracker, replicas: int,
                 num_shards: int = DEFAULT_NUM_SHARDS,
                 lease_s: float = HA_LEASE_S, tick_s: float = HA_TICK_S,
                 serve: bool = False):
        self.kube = FakeKube()
        self.kube.default_client_id = "cpbench"
        self.group = "ha"
        self.num_shards = num_shards
        self.lease_s = lease_s
        self.tick_s = tick_s
        self.tracker = tracker
        self.journal = Journal()
        self.ledger = _Ledger()
        self.replicas = [self.replica_cls(self.kube, i, self,
                                          serve=serve)
                         for i in range(replicas)]
        self._ready_delivered = [0]
        self._ready_inf = Informer(self.kube.client_for("cpbench"),
                                   "notebooks", group=GROUP)
        self._ready_inf.add_handler(self._on_notebook)

    def _on_notebook(self, ev_type: str, nb: dict) -> None:
        self._ready_delivered[0] += 1
        if ev_type == "DELETED":
            return
        if (nb.get("status") or {}).get("readyReplicas"):
            meta = nb["metadata"]
            self.tracker.note_ready(meta.get("namespace"), meta["name"])

    def start(self) -> None:
        for r in self.replicas:
            r.start()
        self._ready_inf.start()
        self._ready_inf.wait_for_sync(10)

    def stop(self) -> None:
        self._ready_inf.stop()
        for r in self.replicas:
            r.stop()

    def live_replicas(self) -> list["_HAReplica"]:
        return [r for r in self.replicas
                if not r.runtime.member._stop.is_set()]

    def replicas_map(self) -> dict:
        """``replicas_fn`` for the fleet aggregator: live replicas'
        ops URLs — the in-process stand-in for Lease discovery (the
        Leases DO carry the same URLs via ops_url; reading them back
        through lease_replicas_fn is what tests/test_fleet.py pins)."""
        return {r.identity: f"http://127.0.0.1:{r.port}"
                for r in self.live_replicas() if r.port is not None}

    def wait_covered(self, timeout: float = 10.0) -> bool:
        """Block until the live replicas' ACTIVE shards cover the whole
        space disjointly — the arm's steady state; creating load before
        it would measure coordination latency, which ha_failover times
        deliberately instead."""
        every = set(range(self.num_shards))
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            owned = [r.runtime.member.active_shards()
                     for r in self.live_replicas()]
            union: set = set()
            total = 0
            for shards in owned:
                union |= shards
                total += len(shards)
            if union == every and total == len(every):
                return True
            time.sleep(0.02)
        return False

    def watch_events_delivered(self) -> int:
        return sum(r.delivered[0] for r in self.replicas) \
            + self._ready_delivered[0]

    def create_jobs(self, names_ns: list[tuple[str, str]]):
        def job(ns, name):
            def run():
                self.tracker.expect(ns, name)
                self.kube.create("notebooks", _nb(name, ns, None))
            return run

        return [job(ns, name) for ns, name in names_ns]


def _spread(names: list[str]) -> list[tuple[str, str]]:
    """(namespace, name) pairs across 8 namespaces — the shard hash
    covers both, and multiple namespaces keep the fake striped."""
    return [(f"ha-{i % 8}", n) for i, n in enumerate(names)]


def _arm_samples(tracker: Tracker, pairs) -> list[float]:
    out = []
    for ns, name in pairs:
        rec = tracker.record(ns, name)
        if rec is not None:
            ms = rec.phase_ms().get("create_to_ready")
            if ms is not None:
                out.append(ms)
    return out


def _fleet_record(snap: dict) -> dict:
    """The per-arm fleet evidence bench_gate --fleet grades, cut from a
    fleetz/v1 snapshot."""
    return {
        "attributed_fraction": snap["attributed_fraction"],
        "stitched_multi_replica": snap["stitched_multi_replica"],
        "handoff_gap_spans": snap["handoff_gap_spans"],
        "trace_count": snap["trace_count"],
        "partial": snap["partial"],
        "replicas_up": sum(1 for r in (snap["replicas"] or {}).values()
                           if r.get("up")),
        "slo": {name: {k: row[k] for k in ("attainment", "n", "met")}
                for name, row in (snap["slo"] or {}).items()
                if row.get("n")},
        "saturation": snap.get("saturation"),
    }


def _scale_arm(cfg: BenchConfig, tracker: Tracker, replicas: int,
               prefix: str, fleet: bool = False,
               induce_handoff: bool = False,
               serve: bool | None = None) -> dict:
    """One replica arm of the sweep. ``fleet`` adds a FleetAggregator
    doing REAL lease discovery + HTTP scrapes at 10 Hz throughout the
    load (the overhead the A/B measures); ``serve`` (default: follows
    ``fleet``) brings up the per-replica ops servers + Lease ops-URL
    advertisement without scraping — the A/B's off leg, so the paired
    delta isolates the scrape cost. ``induce_handoff`` gracefully stops
    one replica after the load drains so its keys re-route — the
    stitched-trace / handoff-gap evidence."""
    world = _HAWorld(cfg, tracker, replicas,
                     serve=fleet if serve is None else serve)
    agg = None
    fleet_rec = None
    try:
        world.start()
        covered = world.wait_covered(15)
        if fleet:
            # production-shape discovery: read the ops URLs back off
            # the member Leases the replicas are heartbeating
            agg = FleetAggregator(
                lease_replicas_fn(
                    world.kube.client_for("fleet"), group=world.group,
                    default_lease_duration=world.lease_s,
                ),
                # 2 Hz: these arms share one GIL with the replicas —
                # a 10 Hz cadence measurably inflates create→Ready p95
                # and the overhead A/B would grade the bench harness,
                # not the scrape cost
                period_s=0.5,
            )
            agg.start()
        pairs = _spread([f"{prefix}-{i:05d}" for i in range(cfg.n)])
        t0 = time.monotonic()
        LoadGenerator(cfg.concurrency, cfg.pattern, cfg.rate).run(
            world.create_jobs(pairs)
        )
        arm_ok = tracker.wait_ready(pairs, _wait_timeout(cfg))
        elapsed = time.monotonic() - t0
        if agg is not None and induce_handoff:
            agg.scrape_once()  # capture the victim's spans while alive
            victim = world.replicas[-1]
            victim.stop()
            covered = world.wait_covered(15) and covered
            # the gained keys requeue from cache on the survivors; the
            # stitcher needs their (early-return) reconcile spans
            snap = None
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                snap = agg.scrape_once()
                if snap["stitched_multi_replica"] \
                        and snap["handoff_gap_spans"]:
                    break
                time.sleep(0.1)
            fleet_rec = _fleet_record(snap)
        elif agg is not None:
            fleet_rec = _fleet_record(agg.scrape_once())
        led = world.ledger.snapshot()
        samples = _arm_samples(tracker, pairs)
        orphaned = len(pairs) - sum(
            1 for ns, n in pairs
            if (r := tracker.record(ns, n)) is not None
            and r.ready is not None
        )
        delivered = world.watch_events_delivered()
        reconciles = sum(led["counts"].values())
        arm = {
            "replicas": replicas,
            "n": len(pairs),
            "covered_before_load": covered,
            "elapsed_s": round(elapsed, 3),
            "create_to_ready_ms": percentiles(samples),
            "reconciles_by_replica": led["counts"],
            "reconciles_per_s": round(reconciles / elapsed, 1)
            if elapsed else None,
            "per_replica_throughput_rps": {
                r: round(c / elapsed, 1)
                for r, c in led["counts"].items()
            } if elapsed else {},
            "dual_reconciles": len(led["violations"]),
            "orphaned_keys": orphaned,
            "watch_events_delivered": delivered,
            "epochs": {r.identity: r.runtime.member.epoch
                       for r in world.replicas},
        }
        if fleet_rec is not None:
            arm["fleet"] = fleet_rec
        return {
            "arm": arm,
            "samples": samples,
            "ok": arm_ok and covered and not led["violations"]
            and orphaned == 0,
            "dual": len(led["violations"]),
            "orphaned": orphaned,
            "delivered": delivered,
        }
    finally:
        if agg is not None:
            agg.stop()
        world.stop()


def scenario_ha_scale(cfg: BenchConfig) -> ScenarioResult:
    """The replica sweep: same population, 1/2/4 sharded replicas.

    The multi-replica arms run with the fleet plane LIVE — per-replica
    ops servers, Lease-advertised URLs, a FleetAggregator scraping over
    real HTTP at 10 Hz — and record the stitched-trace evidence
    bench_gate --fleet grades. The 4-replica arm gracefully stops one
    replica post-load to induce a handoff; the 2-replica arm runs an
    extra scrape-off pass first so ``fleet_overhead`` is a paired A/B
    on create→Ready p95 (servers up in both — the delta isolates the
    SCRAPE cost, the only new per-request work)."""
    started = time.monotonic()
    tracker = Tracker("ha_scale")
    sweep: dict[str, dict] = {}
    all_samples: list[float] = []
    dual_total = orphaned_total = delivered_total = 0
    ok = True

    # overhead A/B "off" leg: 2 replicas, servers up, nothing scraping
    off = _scale_arm(cfg, tracker, 2, "ha2off", fleet=False, serve=True)
    all_samples.extend(off["samples"])
    ok = ok and off["ok"]
    dual_total += off["dual"]
    orphaned_total += off["orphaned"]
    delivered_total += off["delivered"]

    for replicas in (1, 2, 4):
        res = _scale_arm(
            cfg, tracker, replicas, f"ha{replicas}",
            fleet=replicas >= 2, induce_handoff=replicas >= 4,
        )
        sweep[str(replicas)] = res["arm"]
        all_samples.extend(res["samples"])
        dual_total += res["dual"]
        orphaned_total += res["orphaned"]
        delivered_total += res["delivered"]
        ok = ok and res["ok"]

    p95_off = (percentiles(off["samples"]) or {}).get("p95")
    p95_on = (sweep["2"]["create_to_ready_ms"] or {}).get("p95")
    summary = tracker.summary()
    summary["extra"] = {
        "replica_sweep": sweep,
        "num_shards": DEFAULT_NUM_SHARDS,
        "dual_reconciles": dual_total,
        "orphaned_keys": orphaned_total,
        "watch_events_delivered": delivered_total,
        "fleet_overhead": {
            "p95_off_ms": p95_off,
            "p95_on_ms": p95_on,
            "ratio": (round(p95_on / p95_off, 3)
                      if p95_on and p95_off else None),
        },
        "event_count": 0,
        "journal": {},
    }
    summary["slo"] = slo_mod.report({"create_to_ready": all_samples})
    return ScenarioResult(
        name="ha_scale", elapsed_s=time.monotonic() - started,
        records=tracker.records(), summary=summary, ok=ok,
    )


def scenario_ha_failover(cfg: BenchConfig) -> ScenarioResult:
    """Leader-kill mid-drain: kill the coordinator-holding replica with
    work outstanding; time until its orphaned shards' keys reconcile."""
    started = time.monotonic()
    tracker = Tracker("ha_failover")
    world = _HAWorld(cfg, tracker, replicas=3)
    try:
        world.start()
        covered = world.wait_covered(15)
        gen = LoadGenerator(cfg.concurrency, cfg.pattern, cfg.rate)

        wave1 = _spread([f"fo-a-{i:05d}" for i in range(cfg.n // 2)])
        gen.run(world.create_jobs(wave1))
        ok = tracker.wait_ready(wave1, _wait_timeout(cfg)) and covered

        # the replica holding the coordinator Lease is the victim — the
        # literal "leader-kill" arm
        victim = None
        deadline = time.monotonic() + 10
        while victim is None and time.monotonic() < deadline:
            for r in world.replicas:
                if r.runtime.is_coordinator():
                    victim = r
                    break
            time.sleep(0.02)
        killed = victim.identity if victim is not None else None
        t_kill = time.monotonic()
        if victim is not None:
            victim.kill()
        # wave 2 lands INTO the failover window: the survivors own ~2/3
        # of it immediately, the dead replica's third waits for the
        # re-election + re-map + barrier + requeue — the tail the
        # failover SLO bounds
        wave2 = _spread([f"fo-b-{i:05d}"
                         for i in range(cfg.n - len(wave1))])
        gen.run(world.create_jobs(wave2))

        survivors = [r for r in world.replicas if r is not victim]
        elected_ms = recovered_ms = None
        every = set(range(world.num_shards))
        deadline = time.monotonic() + _wait_timeout(cfg)
        while time.monotonic() < deadline:
            if elected_ms is None and any(
                    r.runtime.is_coordinator() for r in survivors):
                elected_ms = round(
                    (time.monotonic() - t_kill) * 1000.0, 1)
            union: set = set()
            for r in survivors:
                union |= r.runtime.member.active_shards()
            if union == every:
                recovered_ms = round(
                    (time.monotonic() - t_kill) * 1000.0, 1)
                break
            time.sleep(0.02)
        ok = tracker.wait_ready(wave2, _wait_timeout(cfg)) and ok
        failover_samples = [
            (r.ready - t_kill) * 1000.0
            for ns, n in wave2
            if (r := tracker.record(ns, n)) is not None
            and r.ready is not None and r.ready > t_kill
        ]
        led = world.ledger.snapshot()
        orphaned = sum(
            1 for ns, n in wave1 + wave2
            if (r := tracker.record(ns, n)) is None or r.ready is None
        )
    finally:
        world.stop()
    summary = tracker.summary()
    summary["extra"] = {
        "replicas": 3,
        "killed": killed,
        "coordinator_elected_ms": elected_ms,
        "shards_recovered_ms": recovered_ms,
        "failover_ms": percentiles(failover_samples),
        "dual_reconciles": len(led["violations"]),
        "dual_reconcile_samples": led["violations"][:8],
        "orphaned_keys": orphaned,
        "reconciles_by_replica": led["counts"],
        "watch_events_delivered": world.watch_events_delivered(),
        "event_count": 0,
        "journal": dict(world.journal.counts()),
    }
    summary["slo"] = slo_mod.report({
        "create_to_ready": _arm_samples(tracker, wave1),
        "failover": failover_samples,
    })
    ok = ok and killed is not None and recovered_ms is not None \
        and not led["violations"] and orphaned == 0
    return ScenarioResult(
        name="ha_failover", elapsed_s=time.monotonic() - started,
        records=tracker.records(), summary=summary, ok=ok,
    )


# ------------------------------------------------------------- APF A/B

def _apf_engine() -> APF:
    """The A/B's flow catalog: kubelet assured, watches in their own
    lane, the bench's staging traffic bounded, leases exempt — and NO
    schema for the storming client, which therefore lands in the small
    catch-all level. That asymmetry is the design point: protection is
    declared, storms are whatever is left."""
    return APF(
        levels=[
            PriorityLevel("exempt", exempt=True),
            PriorityLevel("node-critical", shares=40),
            PriorityLevel("watch-lane", shares=10, queue_wait_s=0.1),
            PriorityLevel("bench", shares=30),
            # the catch-all is deliberately tight: a tiny share, a
            # queue worth 5 ms of it — an unclassified storm burns its
            # burst, then eats 429 + Retry-After (which its client
            # honors, so the squeeze shows up as throughput, not CPU)
            PriorityLevel("global-default", shares=2,
                          queue_wait_s=0.005, burst_s=0.05),
        ],
        schemas=[
            FlowSchema("system-leases", "exempt", plurals=("leases",)),
            FlowSchema("kubelet", "node-critical",
                       clients=("kubelet",)),
            FlowSchema("watches", "watch-lane", verbs=("watch",)),
            FlowSchema("bench", "bench", clients=("cpbench",)),
        ],
        total_rate=2000.0,
        default_level="global-default",
    )


def _protected_loop(kube, n: int, names: list[str], ns: str) -> dict:
    """The kubelet-lane workload: n paced read/status ops with per-op
    latency. Returns latency percentiles + 429 count (must stay 0 — a
    protected lane that gets throttled failed the whole point)."""
    client = kube.client_for("kubelet")
    lat_ms: list[float] = []
    throttled = 0
    for i in range(n):
        name = names[i % len(names)]
        t0 = time.monotonic()
        try:
            if i % 4 == 3:
                obj = copy.deepcopy(
                    client.get("notebooks", name, namespace=ns,
                               group=GROUP))
                obj["status"] = {"readyReplicas": 1, "beat": i}
                client.update_status("notebooks", obj)
            elif i % 16 == 8:
                client.list("notebooks", namespace=ns, group=GROUP)
            else:
                client.get("notebooks", name, namespace=ns, group=GROUP)
        except errors.TooManyRequests:
            throttled += 1
        except errors.ApiError:
            pass
        lat_ms.append((time.monotonic() - t0) * 1000.0)
        time.sleep(0.002)   # a paced kubelet, not a tight loop
    return {"latency_ms": percentiles(lat_ms), "throttled": throttled}


def _storm(kube, stop: threading.Event, ns: str, seed: int,
           honor_retry_after: bool = True) -> dict:
    """One storming controller thread: tight create/patch loop,
    retrying THROUGH 429s by honoring Retry-After (what every real
    controller's backoff does — the squeeze works because the client
    cooperates, and the throughput number shows the squeeze). Wake-ups
    are jittered: four threads honoring the same integer Retry-After
    would otherwise wake as a herd, and the herd's GIL blip — not any
    apiserver behavior — would dominate the protected lane's p95."""
    import random

    rng = random.Random(seed)
    client = kube.client_for("storm-ctl")
    out = {"ops": 0, "throttled": 0}
    i = 0
    while not stop.is_set():
        i += 1
        name = f"storm-{threading.current_thread().name}-{i % 64}"
        try:
            try:
                client.patch(
                    "notebooks", name,
                    {"metadata": {"annotations": {"storm/seq": str(i)}}},
                    namespace=ns, group=GROUP)
            except errors.NotFound:
                client.create("notebooks", _nb(name, ns, None))
            out["ops"] += 1
        except errors.TooManyRequests as e:
            out["throttled"] += 1
            if honor_retry_after:
                retry = min(float(e.retry_after or 1), 1.0)
                stop.wait(retry * (0.75 + 0.5 * rng.random()))
        except errors.ApiError:
            pass
    return out


def _apf_arm(kube, cfg: BenchConfig, ns: str, names: list[str],
             storm: bool) -> dict:
    """One A/B arm: optional storm threads around the protected loop.
    The storm warms in for 0.3 s first so the protected percentiles
    measure SUSTAINED throttling, not the burst-bucket transient."""
    stop = threading.Event()
    results: list[dict] = []
    threads = []
    if storm:
        # the storm works its OWN namespace: its object churn must not
        # grow the protected lane's LIST — otherwise the protected p95
        # measures store size, not flow control (measured: the
        # every-16th-op LIST quintupled once storm CRs shared the ns)
        storm_ns = f"{ns}-storm"

        def run(idx):
            results.append(_storm(kube, stop, storm_ns,
                                  seed=cfg.seed + idx))

        threads = [threading.Thread(target=run, args=(i,), name=f"s{i}",
                                    daemon=True) for i in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.3)
    t0 = time.monotonic()
    protected = _protected_loop(kube, cfg.n, names, ns)
    elapsed = time.monotonic() - t0
    stop.set()
    for t in threads:
        t.join(timeout=5)
    storm_ops = sum(r["ops"] for r in results)
    storm_429 = sum(r["throttled"] for r in results)
    arm = {
        "protected_p50_ms": (protected["latency_ms"] or {}).get("p50"),
        "protected_p95_ms": (protected["latency_ms"] or {}).get("p95"),
        "protected_throttled": protected["throttled"],
        "elapsed_s": round(elapsed, 3),
    }
    if storm:
        window = elapsed + 0.3
        arm["storm_ops"] = storm_ops
        arm["storm_ops_s"] = round(storm_ops / window, 1)
        arm["storm_429s"] = storm_429
    return arm


def scenario_ha_apf(cfg: BenchConfig) -> ScenarioResult:
    """The APF A/B: protected lane p95 must hold under a storm when
    flow schemas are on; the storm must be measurably squeezed."""
    started = time.monotonic()
    tracker = Tracker("ha_apf")
    kube = FakeKube()
    kube.default_client_id = "cpbench"
    ns = "apf"
    names = [f"prot-{i}" for i in range(64)]
    for name in names:
        kube.create("notebooks", _nb(name, ns, None))
    api_t0 = kube.request_counts_snapshot(by_client=True)

    # live watch consumer for the whole scenario: the "watch lane keeps
    # its seat" evidence — emit→receipt lag feeds the watch_delivery SLO
    lag_ms: list[float] = []
    stop_watch = threading.Event()

    def consume():
        rv = 0
        while not stop_watch.is_set():
            try:
                for ev in kube.watch("notebooks", resource_version=rv,
                                     group=GROUP, timeout=0.5):
                    meta = (ev.get("object") or {}).get("metadata") or {}
                    if meta.get("resourceVersion"):
                        rv = int(meta["resourceVersion"])
                    sent = ev.get("emittedAt")
                    now = time.monotonic()
                    if sent is not None and now >= sent:
                        lag_ms.append((now - sent) * 1000.0)
                    if stop_watch.is_set():
                        return
            except errors.ApiError:
                stop_watch.wait(0.05)

    watcher = threading.Thread(target=consume, name="apf-watch",
                               daemon=True)
    watcher.start()

    baseline = _apf_arm(kube, cfg, ns, names, storm=False)
    no_apf = _apf_arm(kube, cfg, ns, names, storm=True)
    kube.enable_apf(apf=_apf_engine())
    with_apf = _apf_arm(kube, cfg, ns, names, storm=True)
    apf_snapshot = kube.apf.snapshot()
    kube.disable_apf()
    stop_watch.set()
    watcher.join(timeout=5)

    base_p95 = baseline["protected_p95_ms"] or 0.0
    apf_p95 = with_apf["protected_p95_ms"] or 0.0
    protected_ratio = round(apf_p95 / base_p95, 3) if base_p95 else None
    # the lane "holds" when its p95 stays within ±20% of the no-storm
    # baseline OR under an absolute floor: these are sub-millisecond
    # in-memory ops, and on a loaded shared box a single 2 ms scheduler
    # slice in either arm would flap a pure-ratio verdict (the no-APF
    # storm arm measures ~10 ms — an order of magnitude, not jitter)
    protected_held = (
        protected_ratio is not None
        and (protected_ratio <= APF_PROTECTED_MAX_RATIO
             or apf_p95 <= APF_PROTECTED_FLOOR_MS)
    )
    noapf_ops = no_apf.get("storm_ops_s") or 0.0
    apf_ops = with_apf.get("storm_ops_s") or 0.0
    storm_ratio = round(apf_ops / noapf_ops, 3) if noapf_ops else None

    summary = tracker.summary()
    summary["extra"] = {
        "apf": {
            "baseline": baseline,
            "storm_no_apf": no_apf,
            "storm_apf": with_apf,
            "protected_p95_ratio": protected_ratio,
            "protected_held": protected_held,
            "storm_throughput_ratio": storm_ratio,
            "storm_429s": with_apf.get("storm_429s", 0),
            "protected_429s": with_apf.get("protected_throttled", 0),
            "levels": apf_snapshot["levels"],
            "schemas": apf_snapshot["schemas"],
        },
        "throttled_by_client": {
            c: v.get("429", 0)
            for c, v in by_client_delta(
                kube.request_counts_snapshot(by_client=True),
                api_t0).items()
            if v.get("429")
        },
        "watch_lag_ms": percentiles(lag_ms),
        "event_count": 0,
        "journal": {},
    }
    summary["slo"] = slo_mod.report({"watch_delivery": lag_ms})
    ok = (
        with_apf.get("storm_429s", 0) > 0
        and with_apf.get("protected_throttled", 0) == 0
        and protected_held
        and storm_ratio is not None
        and storm_ratio <= APF_STORM_MAX_RATIO
    )
    return ScenarioResult(
        name="ha_apf", elapsed_s=time.monotonic() - started,
        records=tracker.records(), summary=summary, ok=ok,
    )


HA_SCENARIOS = {
    "ha_scale": scenario_ha_scale,
    "ha_failover": scenario_ha_failover,
    "ha_apf": scenario_ha_apf,
}

# registration, like the chaos family: importing the module is enough
SCENARIOS.update(HA_SCENARIOS)

#: re-exported so __main__ can keep the family out of the default
#: (latency-lane) run the way it keeps chaos out
__all__ = ["HA_SCENARIOS", "scenario_ha_scale", "scenario_ha_failover",
           "scenario_ha_apf"]
