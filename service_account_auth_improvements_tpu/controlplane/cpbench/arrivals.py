"""cpbench arrival processes: MMPP storms, tides, tails, traces.

Every bench arm before this one hit the apiserver at a constant rate
or in one burst (loadgen.py). Production Jupyter traffic is neither:
XSEDE's Jupyter-at-scale deployments (arXiv:1805.04781) see **workshop
storms** (hundreds of spawns inside two minutes), **diurnal tides**
(the gateway's day, a slow sinusoid), and a **long tail of idlers**
trickling in around the clock. This module generates those shapes and
replays recorded traces, so the storm_scale family (cpbench/storm.py)
drives the plane with traffic shaped like the deployments the paper
targets instead of a constant drip.

Three design rules, load-bearing for the bench contract:

- **Deterministic.** Every generator takes a ``seed`` and draws from
  its own ``random.Random`` — same knobs, same schedule, byte for
  byte. Cross-run comparability is what makes the hot-path A/B
  (bench_gate --storm) a measurement instead of a dice roll.
- **Composable.** A shape returns plain arrival offsets (seconds from
  t=0); :func:`compose` merges any number of them and :func:`rescale`
  compresses a day-long tide into a bench-sized span. The 100k-CR
  recipe in docs/controlplane_bench.md is storm + tide + tail summed.
- **Replayable.** :func:`write_trace`/:func:`load_trace` round-trip a
  schedule through the pinned ``arrivals-trace/v1`` JSONL schema, so a
  future production trace can drive the identical bench path the
  synthetic shapes use today.

Tenancy rides along: :func:`tenant_mix` draws tens of thousands of
heterogeneous tenants — 1-chip dabblers dominating by count, 4x4 gang
trainers dominating by chips — and :func:`assign_tenants` pairs each
arrival with one, giving the storm reconciler's placement sweep a
realistic demand distribution (scheduler/placement.py shapes).
"""

from __future__ import annotations

import dataclasses
import json
import math
import random


@dataclasses.dataclass(frozen=True)
class Phase:
    """One MMPP state: a Poisson arrival rate held for an
    exponentially-distributed dwell."""

    name: str
    #: arrivals per second while the phase holds (0 = silence)
    rate: float
    #: mean phase duration, seconds (exponential)
    mean_dwell_s: float


class MMPP:
    """Markov-modulated Poisson process: arrivals are Poisson at the
    current phase's rate; the phase itself switches after an
    exponential dwell (uniformly to one of the OTHER phases — the
    classic 2-state burst/quiet chain, generalized). Exponential
    memorylessness makes the discard-at-boundary switch exact: an
    inter-arrival drawn past the phase end is simply abandoned and the
    next phase's clock starts at the boundary."""

    def __init__(self, phases, seed: int = 0):
        phases = tuple(phases)
        if not phases:
            raise ValueError("MMPP needs at least one phase")
        if all(p.rate <= 0 for p in phases):
            raise ValueError("MMPP needs at least one phase with rate > 0")
        for p in phases:
            if p.mean_dwell_s <= 0:
                raise ValueError(f"phase {p.name!r} mean_dwell_s must be > 0")
        self.phases = phases
        self.seed = seed

    def offsets(self, n: int) -> list[float]:
        """``n`` arrival offsets (seconds from t=0), sorted."""
        rng = random.Random(self.seed)
        out: list[float] = []
        t = 0.0
        phase = self.phases[0]
        phase_end = rng.expovariate(1.0 / phase.mean_dwell_s)
        while len(out) < n:
            if phase.rate > 0:
                nxt = t + rng.expovariate(phase.rate)
            else:
                nxt = phase_end
            if nxt >= phase_end:
                # phase switch at the boundary, arrival discarded
                t = phase_end
                others = [p for p in self.phases if p is not phase]
                phase = rng.choice(others) if others else phase
                phase_end = t + rng.expovariate(1.0 / phase.mean_dwell_s)
                continue
            t = nxt
            out.append(t)
        return out


def interarrivals(offsets) -> list[float]:
    return [b - a for a, b in zip(offsets, offsets[1:])]


def burstiness(offsets) -> float | None:
    """Coefficient of variation of the inter-arrival gaps: 1.0 is a
    homogeneous Poisson process, > 1 is bursty (the storm signature a
    constant-rate loadgen can never produce). None under 3 arrivals."""
    gaps = interarrivals(offsets)
    if len(gaps) < 2:
        return None
    mean = sum(gaps) / len(gaps)
    if mean <= 0:
        return None
    var = sum((g - mean) ** 2 for g in gaps) / len(gaps)
    return math.sqrt(var) / mean


# ------------------------------------------------------------- shapes

def workshop_storm(n: int, *, window_s: float = 120.0, seed: int = 0,
                   start_s: float = 0.0) -> list[float]:
    """The XSEDE signature: ~n spawns packed into roughly ``window_s``
    (hundreds in two minutes at production numbers), hot bursts broken
    by brief lulls — a 2-state MMPP with a >20:1 rate ratio."""
    if n <= 0:
        return []
    base = n / window_s
    storm = Phase("storm", rate=base * 1.6, mean_dwell_s=window_s / 6.0)
    lull = Phase("lull", rate=base * 0.05, mean_dwell_s=window_s / 20.0)
    return [start_s + t for t in MMPP((storm, lull), seed=seed).offsets(n)]


def diurnal_tide(n: int, *, period_s: float = 600.0, seed: int = 0,
                 start_s: float = 0.0, floor: float = 0.1) -> list[float]:
    """The gateway's day: a sinusoidal-rate Poisson process (thinning
    against the peak rate), ``floor`` being the overnight fraction of
    peak. ``period_s`` is one full day — :func:`rescale` compresses a
    real 86400 s tide into a bench-sized span."""
    if n <= 0:
        return []
    if not 0.0 <= floor <= 1.0:
        raise ValueError("floor must be in [0, 1]")
    rng = random.Random(seed)
    peak = 2.0 * n / period_s
    out: list[float] = []
    t = 0.0
    while len(out) < n:
        t += rng.expovariate(peak)
        phase01 = (t % period_s) / period_s
        envelope = floor + (1.0 - floor) * 0.5 * (
            1.0 - math.cos(2.0 * math.pi * phase01))
        if rng.random() <= envelope:
            out.append(start_s + t)
    return out


def idler_tail(n: int, *, span_s: float = 900.0, seed: int = 0,
               start_s: float = 0.0) -> list[float]:
    """The long-tail idlers: a thin homogeneous Poisson drip across
    ``span_s`` — individually invisible, collectively the population
    that keeps caches warm and stores large."""
    if n <= 0:
        return []
    rng = random.Random(seed)
    rate = n / span_s
    out: list[float] = []
    t = 0.0
    while len(out) < n:
        t += rng.expovariate(rate)
        out.append(start_s + t)
    return out


def compose(*schedules) -> list[float]:
    """Merge shape schedules into one sorted arrival list — storms ride
    on tides ride on the idler tail."""
    out: list[float] = []
    for s in schedules:
        out.extend(s)
    out.sort()
    return out


def rescale(offsets, span_s: float) -> list[float]:
    """Compress or stretch a schedule to span ``span_s`` starting at 0,
    preserving relative shape — the bench's pacing knob (a day-long
    tide replayed in 30 s still tides)."""
    offsets = list(offsets)
    if not offsets:
        return []
    lo, hi = offsets[0], offsets[-1]
    width = hi - lo
    if width <= 0:
        return [0.0] * len(offsets)
    return [(t - lo) * span_s / width for t in offsets]


# ------------------------------------------------------------ tenants

@dataclasses.dataclass(frozen=True)
class TenantProfile:
    name: str
    #: draw weight in the mix (fractions of the population)
    weight: float
    generation: str
    topology: str
    total_chips: int
    num_hosts: int


#: the heterogeneity the ROADMAP asks for: dabblers dominate by count,
#: gang trainers dominate by chips. Shapes are real placement demands
#: (scheduler/placement.py Demand fields) so the storm reconciler's
#: feasibility sweep exercises the same slice classes tpusched does.
DEFAULT_PROFILES = (
    TenantProfile("dabbler", 0.78, "v4", "1x1", total_chips=1,
                  num_hosts=1),
    TenantProfile("classroom", 0.17, "v4", "2x2", total_chips=4,
                  num_hosts=1),
    TenantProfile("gang_trainer", 0.05, "v4", "4x4", total_chips=16,
                  num_hosts=4),
)

#: the pinned tenant-row schema — tests/test_arrivals.py asserts these
#: exact keys; a rename rots every recorded trace's tenant table
TENANT_FIELDS = ("tenant", "profile", "generation", "topology",
                 "total_chips", "num_hosts")


def tenant_mix(num_tenants: int, *, seed: int = 0,
               profiles=DEFAULT_PROFILES) -> list[dict]:
    """``num_tenants`` tenant rows drawn by profile weight, seeded.
    Row keys are exactly :data:`TENANT_FIELDS`."""
    profiles = tuple(profiles)
    if not profiles:
        raise ValueError("tenant_mix needs at least one profile")
    rng = random.Random(seed)
    weights = [p.weight for p in profiles]
    picks = rng.choices(profiles, weights=weights, k=num_tenants)
    return [
        {
            "tenant": f"t{i:06d}",
            "profile": p.name,
            "generation": p.generation,
            "topology": p.topology,
            "total_chips": p.total_chips,
            "num_hosts": p.num_hosts,
        }
        for i, p in enumerate(picks)
    ]


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One scheduled spawn: when, and as whom."""

    offset_s: float
    tenant: str
    profile: str


def assign_tenants(offsets, tenants, *, seed: int = 0) -> list[Arrival]:
    """Pair each arrival with a tenant row (uniform over tenants —
    dabblers already dominate by population, not by per-tenant
    activity). Offsets are rounded to microseconds so a schedule
    survives the trace round-trip bit-exact."""
    tenants = list(tenants)
    if not tenants:
        raise ValueError("assign_tenants needs at least one tenant")
    rng = random.Random(seed)
    return [Arrival(round(t, 6), row["tenant"], row["profile"])
            for t, row in ((t, rng.choice(tenants)) for t in offsets)]


# -------------------------------------------------------------- trace

#: pinned trace schema: every row carries it, and load_trace rejects
#: anything else — replayed production traces and synthetic schedules
#: must be indistinguishable to the bench
TRACE_SCHEMA = "arrivals-trace/v1"


def write_trace(path: str, arrivals) -> int:
    """Serialize a schedule as ``arrivals-trace/v1`` JSONL; returns the
    row count. Deterministic: same schedule, same bytes."""
    arrivals = list(arrivals)
    with open(path, "w", encoding="utf-8") as f:
        for a in arrivals:
            f.write(json.dumps({
                "schema": TRACE_SCHEMA,
                "offset_s": a.offset_s,
                "tenant": a.tenant,
                "profile": a.profile,
            }, sort_keys=True) + "\n")
    return len(arrivals)


def load_trace(path: str) -> list[Arrival]:
    """Parse an ``arrivals-trace/v1`` JSONL file back into the exact
    schedule :func:`write_trace` recorded (offsets re-sorted — a trace
    spliced from multiple recorders may interleave)."""
    out: list[Arrival] = []
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            if row.get("schema") != TRACE_SCHEMA:
                raise ValueError(
                    f"{path}:{lineno}: schema {row.get('schema')!r}, "
                    f"want {TRACE_SCHEMA!r}")
            out.append(Arrival(float(row["offset_s"]), row["tenant"],
                               row.get("profile", "")))
    out.sort(key=lambda a: a.offset_s)
    return out
