"""Scenario registry: what the bench measures, end to end.

Every scenario drives the REAL reconcile stack — ``engine.Manager`` +
informers + the production reconcilers — against a fresh ``FakeKube``
as a live in-process apiserver, with the ``FakeKubelet`` playing the
cluster around it. Nothing is stubbed between the CR create and the
status the user would ``kubectl wait`` on.

=================  =====================================================
``notebook_ready``  CR create → status Ready, single-host TPU notebook
                    (STS + services + status mirroring).
``gang_ready``      multi-host v4-16 gang (4 host pods born gated; the
                    controller lifts the gates only when the whole gang
                    exists with a consistent slice-pool identity).
``churn``           create/delete cycling with the culling controller
                    active: busy kernels keep most notebooks alive,
                    every 5th goes idle once Ready and must be culled
                    (stop annotation → replicas 0) before the cycle
                    deletes the rest.
``profile_fanout``  N Profiles → namespaces, TPU resource quotas, RBAC,
                    service accounts, cloud-IAM plugins.
``webhook_inject``  PodDefault admission latency through the production
                    merge engine (webhook/engine.py) with the
                    PodDefault list served by the apiserver per review.
``sched_contention`` N 4x4 gangs vs 4 one-slice pools through tpusched:
                    admission queue, priority preemption (every 5th
                    notebook is priority 100), placement as capacity
                    frees. Reports time-to-placement p50/p95/p99,
                    preemption count, and double-booking violations
                    (must be 0).
=================  =====================================================
"""

from __future__ import annotations

import dataclasses
import re
import threading
import time

from service_account_auth_improvements_tpu.controlplane.controllers import (
    helpers,
)
from service_account_auth_improvements_tpu.controlplane.controllers.culling import (  # noqa: E501
    CullingReconciler,
)
from service_account_auth_improvements_tpu.controlplane.controllers.notebook import (  # noqa: E501
    GROUP,
    STOP_ANNOTATION,
    NotebookReconciler,
)
from service_account_auth_improvements_tpu.controlplane.controllers.profile import (  # noqa: E501
    ProfileReconciler,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.actuator import (  # noqa: E501
    FakeKubelet,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.loadgen import (  # noqa: E501
    LoadGenerator,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.tracker import (  # noqa: E501
    Tracker,
    percentiles,
    stage_attribution,
)
from service_account_auth_improvements_tpu.controlplane import obs
from service_account_auth_improvements_tpu.controlplane.obs import (
    Journal,
    Tracer,
)
from service_account_auth_improvements_tpu.controlplane.obs import (
    slo as slo_mod,
)
from service_account_auth_improvements_tpu.controlplane.engine import (
    CachedClient,
    Informer,
    Manager,
)
from service_account_auth_improvements_tpu.controlplane.kube import (
    FakeKube,
    errors,
)
from service_account_auth_improvements_tpu.controlplane.scheduler import (
    PRIORITY_ANNOTATION,
    SchedulerReconciler,
)
from service_account_auth_improvements_tpu.controlplane import tpu as tpu_mod
from service_account_auth_improvements_tpu.utils.env import get_env_bool
from service_account_auth_improvements_tpu.webhook.server import (
    review_response,
)


@dataclasses.dataclass
class BenchConfig:
    """One knob set shared by every scenario."""

    n: int = 20                      # CRs per scenario
    concurrency: int = 8             # concurrent apiserver writers
    pattern: str = "burst"           # arrival: "burst" | "rate"
    rate: float = 50.0               # creates/second for pattern="rate"
    actuation: str = "uniform:5,15"  # fake-kubelet latency (ms spec)
    seed: int = 0
    timeout: float = 30.0            # per-scenario ready deadline (s)
    churn_cycles: int = 2
    cull_period_minutes: float = 0.01   # culling probe cadence (36 s/60)
    # chaos-family knobs (cpbench/chaos.py). The blackout window must
    # comfortably exceed the informers' 3-consecutive-failures outage
    # threshold (~3 s of severed/503 watch attempts) or /readyz never
    # flips and the scenario can't observe the recovery it measures.
    chaos_window_s: float = 4.5      # apiserver blackout length
    chaos_stall_s: float = 2.0       # kubelet stall length
    chaos_pulses: int = 3            # 410-Gone storm pulses


@dataclasses.dataclass
class ScenarioResult:
    name: str
    elapsed_s: float
    records: list                    # Timelines (tests assert monotone)
    summary: dict                    # tracker.summary() + "extra"
    ok: bool
    #: black-box evidence for non-Ready/violating objects (journal tail
    #: + explain timelines) — the CLI writes it into bench_out/ so a
    #: failed gate carries its own evidence
    blackbox: dict | None = None
    #: the scenario's full decision journal as JSONL (Journal.to_jsonl)
    #: — the learned-placement harvest surface; ``cpbench
    #: --journal-out`` writes it next to the bench record so benches
    #: ARE the training-set generator (docs/scheduler.md). None for
    #: scenarios without a decision journal.
    journal_jsonl: str | None = None


# --------------------------------------------------------------- fixtures

def by_client_delta(snapshot: dict, t0: dict) -> dict:
    """Per-(client, verb) request delta between two
    ``request_counts_snapshot(by_client=True)`` snapshots, zero rows
    dropped."""
    out: dict = {}
    for client in sorted(set(snapshot) | set(t0)):
        cur, base = snapshot.get(client) or {}, t0.get(client) or {}
        verbs = {
            verb: cur.get(verb, 0) - base.get(verb, 0)
            for verb in sorted(set(cur) | set(base))
            if cur.get(verb, 0) - base.get(verb, 0)
        }
        if verbs:
            out[client] = verbs
    return out


def _nb(name: str, ns: str, tpu: dict | None) -> dict:
    spec: dict = {
        "template": {"spec": {"containers": [{
            "name": "notebook", "image": "ghcr.io/tpukf/jax:bench",
        }]}},
    }
    if tpu:
        spec["tpu"] = tpu
    return {"metadata": {"name": name, "namespace": ns}, "spec": spec}


class _NotebookWorld:
    """FakeKube + Manager + NotebookReconciler (+ optional culler) +
    FakeKubelet + a ready-watch, instrumented for one scenario."""

    def __init__(self, cfg: BenchConfig, scenario: str,
                 fetch_kernels=None, scheduler: bool = False,
                 relist_period: float = 0.0,
                 placement_policy: str | None = None,
                 policy_checkpoint: str | None = None,
                 preemption: bool = True,
                 parker=None, oversubscribe: bool = False):
        self.kube = FakeKube()
        # per-client request attribution (cpprof): the bench's own
        # traffic (creates, deletes, cache-miss polls) books under
        # "cpbench"; the Manager tags itself "manager" + installs the
        # reconcile-actor hook, the kubelet tags itself "kubelet" — so
        # extra.apiserver_requests_by_client names who stormed the
        # apiserver, not just how hard
        self.kube.default_client_id = "cpbench"
        self.tracker = Tracker(scenario)
        # per-world tracer: the span source for per-stage attribution,
        # isolated so scenarios can't read each other's lifecycles
        self.trace = Tracer(max_traces=4096)
        # per-world decision journal (cpscope): rides the tracer's
        # exporter hook, so placements/preemptions/reconcile outcomes
        # land without extra wiring; chaos scenarios point their
        # injector at it too — the black-box record a failing run dumps
        self.journal = Journal().attach(self.trace)
        # per-world SLO engine (isolated registry): absorbs the
        # controllers' production obs.slo_observe calls so scenarios
        # don't cross-pollute the process-global engine; the bench's own
        # attainment record still comes from exact tracker samples
        self.slo_engine = slo_mod.SloEngine().attach(self.trace)
        self._sources = None   # lazy ExplainSources (post-run snapshot)
        self.tracker.instrument_kube(self.kube, tracer=self.trace)
        # relist_period > 0 (chaos scenarios): periodic relists heal
        # silent watch-cache divergence injected by event drops
        self.mgr = Manager(self.kube, tracer=self.trace,
                           relist_period=relist_period)
        self.reconciler = NotebookReconciler(self.kube)
        self.tracker.instrument_reconciler(self.reconciler)
        self.reconciler.register(self.mgr)
        self.sched = None
        if scheduler:
            # tpusched owns admission: the notebook controller creates no
            # children until placement stamps the node-pool annotation
            self.reconciler.use_scheduler = True
            self.sched = SchedulerReconciler(
                self.kube, enable_preemption=preemption,
                placement_policy=placement_policy,
                policy_checkpoint=policy_checkpoint,
                oversubscribe=oversubscribe,
            )
            self.tracker.instrument_reconciler(self.sched)
            self.sched.register(self.mgr)
        self.culler = None
        if fetch_kernels is not None:
            # parker: wires checkpoint-park into the culler (park_resume
            # family) — the same plane the scheduler's oversubscription
            # mode depends on to actually free chips
            self.culler = CullingReconciler(
                self.kube, fetch_kernels=fetch_kernels, parker=parker
            )
            self.culler.check_period_minutes = cfg.cull_period_minutes
            self.tracker.instrument_reconciler(self.culler)
            self.culler.register(self.mgr)
        self.actuator = FakeKubelet(self.kube, cfg.actuation,
                                    seed=cfg.seed, tracer=self.trace,
                                    relist_period=relist_period)
        self.tracker.actuation_fn = self.actuator.actuation_for
        #: the manager's delegating read client — what the converted
        #: reconcilers read through; its stats() are the cached-read
        #: hit-rate evidence the gate holds to ≥0.9 (control-plane
        #: reads, not bench polling)
        self._mgr_cached = self.mgr.cached_client()
        #: what the SCENARIO poll loops read through: the same informer
        #: caches (so the bench's own waiting doesn't inflate the
        #: apiserver volume it measures) but over a "cpbench"-tagged
        #: client, so the rare cache-miss fallthroughs book under the
        #: bench in the per-client split — not under "manager", whose
        #: row exists to show the control plane's own appetite
        self.cached = CachedClient(
            self.kube.client_for("cpbench"), self.mgr._informers,
            namespace=self.mgr.namespace,
            # honor the documented cache A/B lever: ENGINE_CACHED_READS=0
            # must turn the bench's own polling live too, or the
            # cache-off apiserver-volume numbers stop being comparable
            enabled=get_env_bool("ENGINE_CACHED_READS", True),
        )
        self._api_t0 = self.kube.request_counts_snapshot()
        self._api_t0_by_client = self.kube.request_counts_snapshot(
            by_client=True
        )
        self._want: dict[tuple[str, str], int] = {}
        self._ready_inf = Informer(self.kube, "notebooks", group=GROUP,
                                   tracer=self.trace,
                                   relist_period=relist_period)
        self._ready_inf.add_handler(self._on_notebook)

    def _on_notebook(self, ev_type: str, nb: dict) -> None:
        if ev_type == "DELETED":
            return
        m = nb["metadata"]
        key = (m.get("namespace") or "", m["name"])
        want = self._want.get(key)
        ready = (nb.get("status") or {}).get("readyReplicas") or 0
        if want and ready >= want:
            self.tracker.note_ready(*key)

    def start(self) -> None:
        self.mgr.start()
        self.actuator.start()
        self._ready_inf.start()
        self._ready_inf.wait_for_sync(10)

    def stop(self) -> None:
        # idempotent: chaos scenarios stop via _chaos_result on the
        # normal path AND from a finally block on the exception path
        if getattr(self, "_stopped", False):
            return
        self._stopped = True
        self._ready_inf.stop()
        self.actuator.stop()
        self.mgr.stop()

    def attribution(self) -> dict:
        """Per-stage create→Ready attribution from the world's spans."""
        return stage_attribution(self.tracker.records(), self.trace)

    def apiserver_extra(self, reconciles: int) -> dict:
        """Apiserver request volume since world construction: per-verb
        deltas, GET+LIST per reconcile, and the cached-read hit rate —
        the before/after evidence for the delegating-read client."""
        now = self.kube.request_counts_snapshot()
        delta = {
            verb: now.get(verb, 0) - self._api_t0.get(verb, 0)
            for verb in sorted(set(now) | set(self._api_t0))
        }
        reads = delta.get("get", 0) + delta.get("list", 0)
        return {
            "apiserver_requests": delta,
            "apiserver_requests_by_client": by_client_delta(
                self.kube.request_counts_snapshot(by_client=True),
                self._api_t0_by_client,
            ),
            "apiserver_reads_per_reconcile": round(
                reads / max(reconciles, 1), 3
            ),
            "cached_reads": self._mgr_cached.stats(),
        }

    # ---------------------------------------------------- cpscope surface

    def _explain_sources(self):
        """One Event LIST per namespace + one journal snapshot, shared
        by every per-object explain (otherwise the post-run check is
        O(objects x (events + ring)) of redundant copying at --full
        scale). Cached: explain_check, event_count, and blackbox all run
        on the FINISHED world, so one snapshot serves them all."""
        if getattr(self, "_sources", None) is None:
            from service_account_auth_improvements_tpu.controlplane.obs.explain import (  # noqa: E501
                ExplainSources,
            )

            namespaces = tuple({r.namespace
                                for r in self.tracker.records()})
            self._sources = ExplainSources(
                kube=self.kube, journal=self.journal,
                namespaces=namespaces,
            )
        return self._sources

    def explain_check(self) -> dict:
        """Every tracked notebook must be explainable — the acceptance
        bar: /debug/explainz (this is its engine, called in-process)
        answers with a non-empty timeline for each CR the scenario
        drove."""
        records = self.tracker.records()
        sources = self._explain_sources()
        answered = 0
        for rec in records:
            e = obs.explain(rec.namespace, rec.name, kube=self.kube,
                            tracer=self.trace, journal=self.journal,
                            prefetched=sources)
            if e["timeline"]:
                answered += 1
        return {"answered": answered, "of": len(records)}

    def cpscope_extra(self, extra: dict) -> None:
        """Event/journal/explain evidence for the scenario report (call
        AFTER apiserver_extra — the counting LISTs here must not pollute
        the request-volume deltas the bench gates on)."""
        extra["event_count"] = self._explain_sources().total_events
        recorder_stats = self.reconciler.recorder.stats()
        if self.sched is not None:
            sched_stats = self.sched.recorder.stats()
            recorder_stats = {
                k: recorder_stats[k] + sched_stats[k]
                for k in recorder_stats
            }
        extra["recorder"] = recorder_stats
        extra["journal"] = self.journal.counts()
        extra["explainz"] = self.explain_check()

    def slo_record(self, extra_samples: dict | None = None) -> dict:
        """Per-scenario SLO attainment (obs/slo.py report shape):
        create→Ready always; callers add time-to-placement / recovery
        sample sets where the scenario produces them."""
        samples = {
            "create_to_ready": _create_to_ready_ms(self.tracker),
        }
        samples.update(extra_samples or {})
        return slo_mod.report(samples)

    def blackbox(self, violating=(), force: bool = False) -> dict | None:
        """Journal tail + explain timelines for every non-Ready (or
        explicitly named violating) object — the artifact a failed gate
        ships so diagnosis doesn't need a local re-run. None when the
        scenario has nothing to confess (and ``force`` is unset)."""
        failed = [(r.namespace, r.name) for r in self.tracker.records()
                  if r.ready is None]
        keys = sorted(set(failed) | set(violating))
        if not keys and not force:
            return None
        explains = {}
        sources = self._explain_sources()
        for ns, name in keys[:20]:   # cap: evidence, not a core dump
            rec = obs.explain(ns, name, kube=self.kube,
                              tracer=self.trace, journal=self.journal,
                              prefetched=sources)
            explains[f"{ns}/{name}"] = {
                "rendered": obs.render_explain(rec), "record": rec,
            }
        tail = self.journal.entries()[-1000:]
        return {
            "scenario": self.tracker.scenario,
            "non_ready": [f"{ns}/{name}" for ns, name in failed],
            "explain": explains,
            "journal_tail": tail,
        }

    def create_jobs(self, names: list[str], ns: str, tpu: dict | None,
                    want_ready: int):
        """One callable per CR: stamp the timeline, then POST."""

        def job(name):
            def run():
                self.tracker.expect(ns, name)
                self._want[(ns, name)] = want_ready
                self.kube.create("notebooks", _nb(name, ns, tpu))
            return run

        return [job(n) for n in names]


def _create_to_ready_ms(tracker) -> list[float]:
    """The ONE definition of the create→Ready SLO sample set (used by
    the world-based and tracker-only scenario paths alike, so the
    extraction rule can never silently diverge between them)."""
    return [
        ms for r in tracker.records()
        if (ms := r.phase_ms().get("create_to_ready")) is not None
    ]


def _slo_from_tracker(tracker) -> dict:
    """create→Ready SLO record for worlds without a _NotebookWorld
    (profile_fanout, webhook_inject) — every scenario reports
    attainment, uniformly (bench_gate --slo-report requires it)."""
    return slo_mod.report({"create_to_ready": _create_to_ready_ms(tracker)})


def _finish(world, cfg: BenchConfig, names: list[str], ns: str,
            started: float, extra: dict) -> ScenarioResult:
    keys = [(ns, n) for n in names]
    ok = world.tracker.wait_ready(keys, cfg.timeout)
    world.stop()
    summary = world.tracker.summary()
    summary["stage_attribution"] = world.attribution()
    extra.setdefault("gate_violations", world.actuator.gate_violations)
    extra.setdefault("pods_created", world.actuator.pods_created)
    extra.setdefault("pods_ready", world.actuator.pods_ready)
    extra.update(world.apiserver_extra(summary["reconciles"]))
    world.cpscope_extra(extra)
    summary["extra"] = extra
    summary["slo"] = world.slo_record()
    return ScenarioResult(
        name=world.tracker.scenario,
        elapsed_s=time.monotonic() - started,
        records=world.tracker.records(),
        summary=summary,
        ok=ok and summary["failed"] == 0,
        blackbox=world.blackbox(),
        journal_jsonl=world.journal.to_jsonl(),
    )


# -------------------------------------------------------------- scenarios

def scenario_notebook_ready(cfg: BenchConfig) -> ScenarioResult:
    """Single-host TPU notebook: create → STS → pod Ready → status Ready.
    The BASELINE.md headline number."""
    started = time.monotonic()
    world = _NotebookWorld(cfg, "notebook_ready")
    world.start()
    ns = "bench"
    names = [f"nb-{i}" for i in range(cfg.n)]
    tpu = {"generation": "v5e", "topology": "2x2"}   # 4 chips, 1 host
    LoadGenerator(cfg.concurrency, cfg.pattern, cfg.rate).run(
        world.create_jobs(names, ns, tpu, want_ready=1)
    )
    return _finish(world, cfg, names, ns, started, {})


def scenario_gang_ready(cfg: BenchConfig) -> ScenarioResult:
    """Multi-host v4-16 gang: 4 host pods born with scheduling gates;
    Ready requires the controller's gate-lift handshake (all pods exist,
    slice placement consistent, one pool per slice)."""
    started = time.monotonic()
    world = _NotebookWorld(cfg, "gang_ready")
    world.start()
    ns = "bench"
    names = [f"gang-{i}" for i in range(cfg.n)]
    tpu = {"generation": "v4", "topology": "2x2x4"}  # 16 chips, 4 hosts
    LoadGenerator(cfg.concurrency, cfg.pattern, cfg.rate).run(
        world.create_jobs(names, ns, tpu, want_ready=4)
    )
    keys = [(ns, n) for n in names]
    ok = world.tracker.wait_ready(keys, cfg.timeout)
    # gang correctness, checked while the world is still live
    gang_scheduled = conflicts = gated_left = 0
    for name in names:
        try:
            nb = world.cached.get("notebooks", name, namespace=ns,
                                  group=GROUP)
        except errors.NotFound:
            continue
        conds = {c.get("type") for c in
                 (nb.get("status") or {}).get("conditions") or []}
        gang_scheduled += "GangScheduled" in conds
        conflicts += "SlicePlacementConflict" in conds
        for pod in world.cached.list(
                "pods", namespace=ns,
                label_selector=f"notebook-name={name}")["items"]:
            if (pod.get("spec") or {}).get("schedulingGates"):
                gated_left += 1
    world.stop()
    summary = world.tracker.summary()
    summary["stage_attribution"] = world.attribution()
    extra = {
        "hosts_per_gang": 4,
        "gang_scheduled": gang_scheduled,
        "placement_conflicts": conflicts,
        "pods_still_gated": gated_left,
        "gate_violations": world.actuator.gate_violations,
        "pods_created": world.actuator.pods_created,
        "pods_ready": world.actuator.pods_ready,
        **world.apiserver_extra(summary["reconciles"]),
    }
    world.cpscope_extra(extra)
    summary["extra"] = extra
    summary["slo"] = world.slo_record()
    return ScenarioResult(
        name="gang_ready", elapsed_s=time.monotonic() - started,
        records=world.tracker.records(), summary=summary,
        ok=ok and summary["failed"] == 0 and gated_left == 0,
        blackbox=world.blackbox(),
        journal_jsonl=world.journal.to_jsonl(),
    )


_KERNELS_URL = re.compile(r"/notebook/([^/]+)/([^/]+)/api/kernels")


def scenario_churn(cfg: BenchConfig) -> ScenarioResult:
    """Create/delete cycling with culling active. Every 5th notebook
    turns idle once Ready and must be CULLED (probe → stop annotation →
    replicas 0); the rest stay busy under periodic kernel probes and are
    deleted at cycle end (cascade through ownerReferences)."""
    started = time.monotonic()
    ns = "bench"

    def fetch_kernels(url: str):
        m = _KERNELS_URL.search(url)
        if not m or m.group(1) != ns:
            return None
        name = m.group(2)
        idx = name.rsplit("-", 1)[-1]
        try:
            # cache-backed: this models the notebook's own HTTP kernels
            # endpoint, which in a real cluster never touches the
            # apiserver — the GET volume it would fake belongs to nobody
            nb = world.cached.get("notebooks", name, namespace=ns,
                                  group=GROUP)
        except errors.NotFound:
            return None
        ready = (nb.get("status") or {}).get("readyReplicas") or 0
        if not ready:
            # booting: unreachable (a busy answer here would stamp
            # last-activity=now, which only moves forward — the idle
            # timestamp below could then never win)
            return None
        if idx.isdigit() and int(idx) % 5 == 0:
            # idle since long ago → culled on the next probe
            return [{"execution_state": "idle",
                     "last_activity": "2000-01-01T00:00:00Z"}]
        return [{"execution_state": "busy"}]

    world = _NotebookWorld(cfg, "churn", fetch_kernels=fetch_kernels)
    world.start()
    gen = LoadGenerator(cfg.concurrency, cfg.pattern, cfg.rate)
    cycles = max(1, cfg.churn_cycles)
    per_cycle = max(1, cfg.n // cycles)
    tpu = {"generation": "v5e", "topology": "2x2"}
    culled_total = 0
    delete_ms: list[float] = []
    ok = True
    all_names: list[str] = []
    for c in range(cycles):
        names = [f"churn-c{c}-{i}" for i in range(per_cycle)]
        all_names += names
        gen.run(world.create_jobs(names, ns, tpu, want_ready=1))
        keys = [(ns, n) for n in names]
        ok = world.tracker.wait_ready(keys, cfg.timeout) and ok
        # the idle subset must get culled before the cycle tears down
        idle = [n for n in names if int(n.rsplit("-", 1)[-1]) % 5 == 0]
        deadline = time.monotonic() + cfg.timeout
        while idle and time.monotonic() < deadline:
            # cached poll: the bench's own waiting must not inflate the
            # apiserver GET volume it measures
            idle = [
                n for n in idle
                if STOP_ANNOTATION not in (
                    world.cached.get("notebooks", n, namespace=ns,
                                     group=GROUP)["metadata"]
                    .get("annotations") or {})
            ]
            if idle:
                time.sleep(0.02)
        ok = ok and not idle
        culled_total += len(
            [n for n in names if int(n.rsplit("-", 1)[-1]) % 5 == 0]
        ) - len(idle)

        def delete(name):
            def run():
                t0 = time.monotonic()
                world.kube.delete("notebooks", name, namespace=ns,
                                  group=GROUP)
                delete_ms.append((time.monotonic() - t0) * 1000.0)
            return run

        gen.run([delete(n) for n in names])
        deadline = time.monotonic() + cfg.timeout
        while time.monotonic() < deadline:
            if not world.cached.list("pods", namespace=ns)["items"]:
                break
            time.sleep(0.02)
        else:
            ok = False
    world.stop()
    summary = world.tracker.summary()
    summary["stage_attribution"] = world.attribution()
    extra = {
        "cycles": cycles,
        "culled": culled_total,
        "delete_cascade_ms": percentiles(delete_ms),
        "gate_violations": world.actuator.gate_violations,
        "pods_created": world.actuator.pods_created,
        **world.apiserver_extra(summary["reconciles"]),
    }
    world.cpscope_extra(extra)
    summary["extra"] = extra
    summary["slo"] = world.slo_record()
    return ScenarioResult(
        name="churn", elapsed_s=time.monotonic() - started,
        records=world.tracker.records(), summary=summary,
        ok=ok and summary["failed"] == 0,
        blackbox=world.blackbox(),
        journal_jsonl=world.journal.to_jsonl(),
    )


def scenario_profile_fanout(cfg: BenchConfig) -> ScenarioResult:
    """N Profiles → tenant namespaces with TPU chip quotas, RBAC,
    service accounts, Istio ACLs, and cloud-IAM plugin binds."""
    started = time.monotonic()
    kube = FakeKube()
    kube.default_client_id = "cpbench"
    tracker = Tracker("profile_fanout")
    tracker.instrument_kube(kube)
    mgr = Manager(kube)
    rec = ProfileReconciler(kube)
    tracker.instrument_reconciler(rec)
    rec.register(mgr)

    def on_profile(ev_type, obj):
        if ev_type == "DELETED":
            return
        cond = helpers.get_condition(obj, "Ready")
        if cond and cond.get("status") == "True":
            tracker.note_ready(None, obj["metadata"]["name"])

    ready_inf = Informer(kube, "profiles", group=GROUP)
    ready_inf.add_handler(on_profile)
    mgr.start()
    ready_inf.start()
    ready_inf.wait_for_sync(10)

    names = [f"cpb-user-{i}" for i in range(cfg.n)]

    def job(i, name):
        def run():
            tracker.expect(None, name)
            profile = {
                "metadata": {"name": name},
                "spec": {
                    "owner": {"kind": "User",
                              "name": f"user{i}@example.com"},
                    "resourceQuotaSpec": {"hard": {
                        "requests.google.com/tpu": "16",
                    }},
                },
            }
            if i % 2 == 0:
                profile["spec"]["plugins"] = [{
                    "kind": "WorkloadIdentity",
                    "spec": {"gcpServiceAccount":
                             f"bench-{i}@proj.iam.gserviceaccount.com"},
                }]
            kube.create("profiles", profile)
        return run

    LoadGenerator(cfg.concurrency, cfg.pattern, cfg.rate).run(
        [job(i, n) for i, n in enumerate(names)]
    )
    ok = tracker.wait_ready([(None, n) for n in names], cfg.timeout)
    ready_inf.stop()
    mgr.stop()
    summary = tracker.summary()
    api = kube.request_counts_snapshot()
    summary["extra"] = {
        "namespaces": len(kube.list("namespaces")["items"]),
        "quotas": len(kube.list("resourcequotas")["items"]),
        "rolebindings": len(kube.list(
            "rolebindings", group="rbac.authorization.k8s.io")["items"]),
        "serviceaccounts": len(kube.list("serviceaccounts")["items"]),
        # the profile reconciler still reads live (not converted); the
        # raw tally keeps it comparable across PRs
        "apiserver_requests": api,
        "apiserver_requests_by_client": kube.request_counts_snapshot(
            by_client=True
        ),
        "apiserver_reads_per_reconcile": round(
            (api.get("get", 0) + api.get("list", 0))
            / max(summary["reconciles"], 1), 3
        ),
        # cpscope: ProfileReady/ProfileError Events now land in tenant
        # namespaces (the PR 7 dead-grant gap, closed)
        "event_count": len(kube.list("events")["items"]),
        "recorder": rec.recorder.stats(),
        "journal": {},
    }
    summary["slo"] = _slo_from_tracker(tracker)
    return ScenarioResult(
        name="profile_fanout", elapsed_s=time.monotonic() - started,
        records=tracker.records(), summary=summary,
        ok=ok and summary["failed"] == 0,
    )


def scenario_webhook_inject(cfg: BenchConfig) -> ScenarioResult:
    """PodDefault admission latency: the AdmissionReview round through
    the production merge engine, PodDefaults listed from the apiserver
    per review (what the real webhook does per pod CREATE)."""
    started = time.monotonic()
    kube = FakeKube()
    kube.default_client_id = "cpbench"
    tracker = Tracker("webhook_inject")
    # the per-review PodDefault LIST is the webhook's own traffic — tag
    # it so the per-client split separates it from the bench's staging
    webhook_client = kube.client_for("webhook")
    namespaces = [f"wh-{i}" for i in range(min(8, max(1, cfg.n // 4)))]
    for ns in namespaces:
        for pd_name, labels in (("tpu-env", {"inject-tpu": "true"}),
                                ("proxy", {"inject-proxy": "true"})):
            kube.create("poddefaults", {
                "metadata": {"name": pd_name, "namespace": ns},
                "spec": {
                    "selector": {"matchLabels": labels},
                    "env": [{"name": f"CPB_{pd_name.upper()}",
                             "value": "1"}],
                    "volumeMounts": [{"name": pd_name,
                                      "mountPath": f"/mnt/{pd_name}"}],
                    "volumes": [{"name": pd_name, "emptyDir": {}}],
                },
            }, namespace=ns)

    def list_pds(ns):
        return webhook_client.list("poddefaults", namespace=ns)["items"]

    mutated = [0]
    mutated_lock = threading.Lock()

    def job(i):
        ns = namespaces[i % len(namespaces)]
        name = f"pod-{i}"

        def run():
            rec = tracker.expect(ns, name)
            review = {"request": {
                "uid": f"uid-{i}",
                "namespace": ns,
                "object": {
                    "metadata": {"name": name, "namespace": ns,
                                 "labels": {"inject-tpu": "true",
                                            "inject-proxy": "true"}},
                    "spec": {"containers": [{"name": "notebook",
                                             "image": "jax"}]},
                },
            }}
            rec.first_reconcile = time.monotonic()
            resp = review_response(review, list_pds)["response"]
            if resp.get("patch"):
                with mutated_lock:
                    mutated[0] += 1
            tracker.note_ready(ns, name)
        return run

    LoadGenerator(cfg.concurrency, cfg.pattern, cfg.rate).run(
        [job(i) for i in range(cfg.n)]
    )
    summary = tracker.summary()
    summary["extra"] = {
        "namespaces": len(namespaces),
        "poddefaults_per_namespace": 2,
        "mutated": mutated[0],
        "apiserver_requests_by_client": kube.request_counts_snapshot(
            by_client=True
        ),
        "event_count": len(kube.list("events")["items"]),
        "journal": {},
    }
    summary["slo"] = _slo_from_tracker(tracker)
    return ScenarioResult(
        name="webhook_inject", elapsed_s=time.monotonic() - started,
        records=tracker.records(), summary=summary,
        ok=summary["failed"] == 0 and mutated[0] == cfg.n,
    )


SCHED_POOLS = 4


def scenario_sched_contention(cfg: BenchConfig) -> ScenarioResult:
    """N pending v5e 4x4 gangs vs SCHED_POOLS one-slice pools, through
    the full tpusched pipeline: admission queue (every 5th notebook is
    priority 100 and may preempt), placement stamping the node-pool
    selector, gang gating on the assigned pool, Ready, delete — freeing
    the slice for the next in line. The scenario deletes each notebook
    once Ready and resumes preempted victims once their placement is
    cleared, so the queue drains to the last notebook.

    Reported: time-to-placement percentiles (create → node-pool
    annotation), preemption count, and double-booking violations — the
    number of poll ticks that ever saw two live notebooks share a pool
    (must be 0: a multi-host pool is one slice)."""
    started = time.monotonic()
    world = _NotebookWorld(cfg, "sched_contention", scheduler=True)
    ns = "bench"
    # 4 one-slice v5e 4x4 pools: 4 hosts x 4 chips each
    for p in range(SCHED_POOLS):
        for h in range(4):
            world.kube.create("nodes", {
                "metadata": {
                    "name": f"node-sp{p}-{h}",
                    "labels": {
                        tpu_mod.SEL_NODEPOOL: f"sched-pool-{p}",
                        tpu_mod.SEL_ACCELERATOR: "tpu-v5-lite-podslice",
                        tpu_mod.SEL_TOPOLOGY: "4x4",
                    },
                },
                "status": {"capacity": {tpu_mod.RESOURCE_TPU: "4"}},
            })
    placement_ms: dict[str, float] = {}
    placement_lock = threading.Lock()

    def on_placement(ev_type: str, nb: dict) -> None:
        if ev_type in ("DELETED", "SYNC"):
            return
        name = nb["metadata"]["name"]
        if (nb["metadata"].get("annotations") or {}).get(
                tpu_mod.ANNOTATION_NODEPOOL) is None:
            return
        rec = world.tracker.record(ns, name)
        if rec is None or rec.created is None:
            return
        with placement_lock:
            placement_ms.setdefault(
                name, (time.monotonic() - rec.created) * 1000.0
            )

    world._ready_inf.add_handler(on_placement)
    world.start()
    names = [f"cont-{i:03d}" for i in range(cfg.n)]

    def job(i, name):
        def run():
            world.tracker.expect(ns, name)
            world._want[(ns, name)] = 4
            nb = _nb(name, ns, {"generation": "v5e", "topology": "4x4"})
            if i % 5 == 4:
                nb["metadata"]["annotations"] = {
                    PRIORITY_ANNOTATION: "100",
                }
            world.kube.create("notebooks", nb)
        return run

    LoadGenerator(cfg.concurrency, cfg.pattern, cfg.rate).run(
        [job(i, n) for i, n in enumerate(names)]
    )

    deleted: set[str] = set()
    double_bookings = 0
    double_booking_samples: list[dict] = []  # first few, for diagnosis
    queued_peak = 0
    deadline = time.monotonic() + cfg.timeout
    while len(deleted) < len(names) and time.monotonic() < deadline:
        queued_peak = max(queued_peak, len(world.sched._queue))
        # One LIST is an ATOMIC snapshot: the informer cache applies the
        # event stream one event at a time under its lock, so a cached
        # list is a consistent prefix of apiserver history — per-name
        # GETs would read a torn cut where the scheduler has released a
        # victim's pool and stamped its successor between two reads,
        # blaming the legitimate hand-off as a double booking. (Cached
        # rather than live so the bench's 20 ms poll doesn't dominate
        # the LIST volume it reports.)
        snapshot = {
            o["metadata"]["name"]: o
            for o in world.cached.list("notebooks", namespace=ns,
                                       group=GROUP)["items"]
        }
        live_pools: dict[str, list[str]] = {}
        to_delete: list[str] = []
        to_resume: list[str] = []
        for name in names:
            if name in deleted:
                continue
            nb = snapshot.get(name)
            if nb is None:
                continue  # delete still cascading, or not created yet
            annots = nb["metadata"].get("annotations") or {}
            pool = annots.get(tpu_mod.ANNOTATION_NODEPOOL)
            if pool:
                live_pools.setdefault(pool, []).append(name)
            rec = world.tracker.record(ns, name)
            if rec is not None and rec.ready is not None:
                to_delete.append(name)
            elif STOP_ANNOTATION in annots and pool is None:
                # preempted victim, placement already released: resume it
                # so it re-queues (at its old priority) and drains too
                to_resume.append(name)
        for pool, members in live_pools.items():
            if len(members) > 1:
                double_bookings += 1
                if len(double_booking_samples) < 8:
                    double_booking_samples.append({
                        "pool": pool,
                        "members": {
                            m: {
                                "annotations": dict(
                                    snapshot[m]["metadata"].get(
                                        "annotations") or {}),
                                "readyReplicas": (snapshot[m].get("status")
                                                  or {}).get("readyReplicas"),
                            } for m in members
                        },
                    })
        for name in to_delete:
            try:
                world.kube.delete("notebooks", name, namespace=ns,
                                  group=GROUP)
            except errors.NotFound:
                pass
            deleted.add(name)
        for name in to_resume:
            try:
                world.kube.patch(
                    "notebooks", name,
                    {"metadata": {"annotations": {STOP_ANNOTATION: None}}},
                    namespace=ns, group=GROUP,
                )
            except errors.NotFound:
                pass
        time.sleep(0.02)
    ok = len(deleted) == len(names) and double_bookings == 0
    world.stop()
    summary = world.tracker.summary()
    summary["stage_attribution"] = world.attribution()
    extra = {
        "pools": SCHED_POOLS,
        "time_to_placement_ms": percentiles(list(placement_ms.values())),
        "placed": len(placement_ms),
        "preemptions": int(world.sched.metrics.preemptions.value()),
        "double_bookings": double_bookings,
        "double_booking_samples": double_booking_samples,
        "queued_peak": queued_peak,  # sampled, not derived: rate-paced
                                     # arrivals can drain before peaking
        "gate_violations": world.actuator.gate_violations,
        "pods_created": world.actuator.pods_created,
        **world.apiserver_extra(summary["reconciles"]),
    }
    world.cpscope_extra(extra)
    summary["extra"] = extra
    summary["slo"] = world.slo_record(
        {"time_to_placement": list(placement_ms.values())}
    )
    violating = [(ns, m) for s in double_booking_samples
                 for m in s["members"]]
    return ScenarioResult(
        name="sched_contention", elapsed_s=time.monotonic() - started,
        records=world.tracker.records(), summary=summary,
        ok=ok and summary["failed"] == 0 and len(placement_ms) == cfg.n,
        blackbox=world.blackbox(violating=violating),
        journal_jsonl=world.journal.to_jsonl(),
    )


def _stress_arm(cfg: BenchConfig, workers: int) -> dict:
    """One apiserver_stress sweep arm: ``workers`` writer threads drive
    a fresh FakeKube through a fixed create/update/patch/get/list/delete
    mix over ``cfg.n`` notebook CRs spread across namespaces, while a
    replay-from-0 watch consumer measures emit→receipt delivery lag and
    checks per-key event fidelity (ADDED first, strictly increasing RVs,
    DELETED terminal, nothing lost or duplicated). Returns the arm
    record for ``extra.workers_sweep``."""
    kube = FakeKube()
    kube.default_client_id = "cpbench"
    namespaces = [f"stress-{i}" for i in range(8)]
    api_t0 = kube.request_counts_snapshot()
    locks_t0 = obs.lock_contention_snapshot()
    per_worker = max(1, cfg.n // workers)
    emitted = [0] * workers          # watch events each worker caused
    ops = [0] * workers              # apiserver calls each worker made
    errors_seen: list[str] = []
    err_lock = threading.Lock()

    def worker(w: int) -> None:
        # a tagged handle per worker: the per-client split in the prof
        # record shows exactly who stormed the apiserver
        client = kube.client_for(f"stress-w{w}")
        try:
            for i in range(per_worker):
                ns = namespaces[(w + i) % len(namespaces)]
                name = f"cr-{w}-{i}"
                obj = client.create(
                    "notebooks", _nb(name, ns, {"generation": "v5e",
                                                "topology": "2x2"}))
                emitted[w] += 1
                # every write changes the object — the fake suppresses
                # no-op writes (no RV bump, no event), so an identical
                # payload would silently skew the emitted-event ledger
                obj["status"] = {"readyReplicas": 1, "seq": i}
                client.update_status("notebooks", obj)
                emitted[w] += 1
                client.patch(
                    "notebooks", name,
                    {"metadata": {"annotations": {"stress/seq": str(i)}}},
                    namespace=ns, group=GROUP)
                emitted[w] += 1
                client.get("notebooks", name, namespace=ns, group=GROUP)
                ops[w] += 4
                if i % 16 == 0:
                    client.list("notebooks", namespace=ns, group=GROUP)
                    ops[w] += 1
                if i % 4 == 3:
                    client.delete("notebooks", name, namespace=ns,
                                  group=GROUP)
                    emitted[w] += 1
                    ops[w] += 1
        except errors.ApiError as e:  # healthy cluster: nothing may fail
            with err_lock:
                errors_seen.append(repr(e))

    lag_ms: list[float] = []
    per_key: dict[str, list] = {}    # key -> [(rv, type), ...] in order
    watcher_done = threading.Event()
    workers_done = threading.Event()

    def watch_consumer() -> None:
        # replay-from-0 with an idle timeout: once the writers stop and
        # the backlog drains, 2 s of quiet ends the stream
        for ev in kube.watch("notebooks", resource_version=0,
                             group=GROUP, timeout=2.0):
            received = time.monotonic()
            sent = ev.get("emittedAt")
            if sent is not None and received >= sent:
                lag_ms.append((received - sent) * 1000.0)
            meta = ev["object"]["metadata"]
            key = f"{meta.get('namespace')}/{meta['name']}"
            per_key.setdefault(key, []).append(
                (int(meta["resourceVersion"]), ev["type"]))
            if workers_done.is_set() and \
                    sum(len(v) for v in per_key.values()) >= sum(emitted):
                break
        watcher_done.set()

    consumer = threading.Thread(target=watch_consumer,
                                name="stress-watch", daemon=True)
    consumer.start()
    t0 = time.monotonic()
    threads = [threading.Thread(target=worker, args=(w,),
                                name=f"stress-w{w}", daemon=True)
               for w in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - t0
    workers_done.set()
    drained = watcher_done.wait(cfg.timeout)

    ordering_violations = 0
    expected = sum(emitted)
    if not drained:
        # the consumer thread is still appending: iterating its dicts
        # now would crash the whole bench run ("dict changed size
        # during iteration") instead of failing the arm. Report the
        # failure from atomic reads only; the arm's seen<expected (and
        # the recorded error) fail the scenario honestly.
        with err_lock:
            errors_seen.append(
                f"watch consumer did not drain within {cfg.timeout}s"
            )
        seen = sum(len(v) for v in list(per_key.values()))
    else:
        for key, seq in per_key.items():
            rvs = [rv for rv, _ in seq]
            if rvs != sorted(rvs) or len(set(rvs)) != len(rvs):
                ordering_violations += 1
                continue
            if seq[0][1] != "ADDED":
                ordering_violations += 1
            if any(t == "DELETED" for _, t in seq[:-1]):
                ordering_violations += 1
        seen = sum(len(v) for v in per_key.values())
    locks = obs.lock_contention_top(since=locks_t0, limit=50)
    # throughput = apiserver REQUESTS per second; emitted tracks watch
    # events (for the fidelity ledger), which are the same writes seen
    # again — summing both would double-count every write
    total_ops = sum(ops)
    return {
        "workers": workers,
        "n": per_worker * workers,
        "elapsed_s": round(elapsed, 3),
        "throughput_ops_s": round(total_ops / elapsed, 1) if elapsed
        else None,
        "apiserver_requests": {
            verb: n - api_t0.get(verb, 0)
            for verb, n in kube.request_counts_snapshot().items()
        },
        "by_client": by_client_delta(
            kube.request_counts_snapshot(by_client=True), {}),
        # the serialization-point evidence, ONE definition shared with
        # extra.prof (obs.store_lock_wait_share; None without lock
        # instrumentation, i.e. no --profile / CPPROF_LOCKS /
        # CPLINT_LOCKWATCH)
        "store_lock_wait_share": (obs.store_lock_wait_share(locks,
                                                            elapsed)
                                  if locks else None),
        "watch_lag_ms": percentiles(lag_ms),
        "watch_events_expected": expected,
        "watch_events_seen": seen,
        "ordering_violations": ordering_violations,
        "errors": errors_seen[:8],
        "_lag_samples": lag_ms,      # stripped before the report
    }


def scenario_apiserver_stress(cfg: BenchConfig) -> ScenarioResult:
    """The apiserver itself under churn — the measurement substrate for
    the sharded/HA roadmap item. No Manager, no controllers: W writer
    threads drive create/update/patch/get/list/delete across namespaces
    against a fresh FakeKube per arm, swept at 1/2/4 workers, while a
    watch consumer measures emit→receipt delivery lag and audits event
    fidelity. Reports per-arm verb throughput, the store-lock wait
    share from cpprof's lock instrumentation, and watch-delivery lag —
    at 10k-CR scale (--full) a serialized fake would be the bottleneck
    the bench measures instead of the plane."""
    started = time.monotonic()
    tracker = Tracker("apiserver_stress")
    sweep: dict[str, dict] = {}
    lag_all: list[float] = []
    by_client_all: dict = {}
    ok = True
    for workers in (1, 2, 4):
        arm = _stress_arm(cfg, workers)
        lag_all.extend(arm.pop("_lag_samples"))
        for client, verbs in arm["by_client"].items():
            agg = by_client_all.setdefault(client, {})
            for verb, n in verbs.items():
                agg[verb] = agg.get(verb, 0) + n
        ok = ok and not arm["errors"] \
            and arm["ordering_violations"] == 0 \
            and arm["watch_events_seen"] == arm["watch_events_expected"]
        sweep[str(workers)] = arm
    summary = tracker.summary()
    shares = [a["store_lock_wait_share"] for a in sweep.values()
              if a["store_lock_wait_share"] is not None]
    summary["extra"] = {
        "workers_sweep": sweep,
        "watch_lag_ms": percentiles(lag_all),
        "store_lock_wait_share": (round(max(shares), 4) if shares
                                  else None),
        "throughput_ops_s": {
            w: a["throughput_ops_s"] for w, a in sweep.items()
        },
        "ordering_violations": sum(
            a["ordering_violations"] for a in sweep.values()),
        # the per-client split rides here so extra.prof.by_client (and
        # the --prof-report leg) see who the stormers were
        "apiserver_requests_by_client": by_client_all,
        "event_count": 0,
        "journal": {},
    }
    summary["slo"] = slo_mod.report({"watch_delivery": lag_all})
    return ScenarioResult(
        name="apiserver_stress", elapsed_s=time.monotonic() - started,
        records=tracker.records(), summary=summary, ok=ok,
    )


SCENARIOS = {
    "notebook_ready": scenario_notebook_ready,
    "gang_ready": scenario_gang_ready,
    "churn": scenario_churn,
    "profile_fanout": scenario_profile_fanout,
    "webhook_inject": scenario_webhook_inject,
    "sched_contention": scenario_sched_contention,
    "apiserver_stress": scenario_apiserver_stress,
}


def run_scenario(name: str, cfg: BenchConfig) -> ScenarioResult:
    return SCENARIOS[name](cfg)
