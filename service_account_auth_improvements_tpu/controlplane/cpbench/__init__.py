"""cpbench: control-plane latency & load benchmark subsystem.

BASELINE.md's #1 control-plane target — "Notebook-CR → pod-Ready p50:
measure and record" — needs a harness before it can have a number. This
package drives the REAL reconcile stack (engine/manager.py +
engine/informer.py + controllers/*) against ``kube/fake.py`` as a live
in-process apiserver, and measures it:

- ``actuator``: a fake StatefulSet-controller + scheduler + kubelet that
  creates pods from STS templates, binds them to (pool-consistent) nodes,
  and flips them Ready after a tunable latency distribution — so
  controller overhead is separable from actuation latency.
- ``tracker``: per-CR timelines (create → first reconcile → STS created →
  Ready) with p50/p95/p99 aggregation, wired through
  ``controlplane/metrics/registry.py`` histograms.
- ``loadgen``: configurable concurrency and arrival pattern (burst vs.
  constant-rate).
- ``scenarios``: the registry — ``notebook_ready``, ``gang_ready``,
  ``churn``, ``profile_fanout``, ``webhook_inject``.
- ``__main__``: the CLI. ``python -m
  service_account_auth_improvements_tpu.controlplane.cpbench --smoke``
  emits ``CONTROLPLANE_BENCH.json`` in ≤30 s on CPU with no JAX import
  anywhere on the path (the control plane is pure stdlib).

The reference's only control-plane performance artifact is a 300 s CI
pod-Ready ceiling (nb_controller_intergration_test.yaml:64); this gives
the rebuild measured percentiles future scheduling/HA PRs can regress
against (see docs/controlplane_bench.md).
"""

from service_account_auth_improvements_tpu.controlplane.cpbench.actuator import (  # noqa: F401
    FakeKubelet,
    LatencyDist,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.loadgen import (  # noqa: F401
    LoadGenerator,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.scenarios import (  # noqa: F401
    SCENARIOS,
    BenchConfig,
    ScenarioResult,
    run_scenario,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.chaos import (  # noqa: F401 — import registers the chaos family into SCENARIOS
    CHAOS_SCENARIOS,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.tracker import (  # noqa: F401
    RecoveryTracker,
    Timeline,
    Tracker,
    percentiles,
    stage_attribution,
)
