"""Chaos scenario family: the control plane under failure, measured.

Every healthy cpbench scenario assumes the apiserver answers and no
watch stream dies. These four do the opposite — they run the REAL
Manager/controllers/tpusched through a scripted injection schedule
(kube/chaos.py) and assert **recovery invariants**, with recovery-time
percentiles recorded into CONTROLPLANE_BENCH.json and gated by
tools/bench_gate.py:

===========================  ===========================================
``chaos_relist``             410 Gone storms + watch drops/reorders mid
                             tpusched drain: no pool is ever
                             double-booked across forced relists, queue
                             positions stay consistent, every informer
                             resync is timed.
``chaos_blackout``           total apiserver outage (every verb 503,
                             watch channels severed) with work in
                             flight: /readyz flips false during the
                             outage and recovers after; no in-flight
                             notebook loses its status writes.
``chaos_node_death``         a busy pool's nodes die mid-gang (pods
                             force-removed) and are auto-repaired: no
                             orphaned STS/pods, no pod left bound to a
                             dead node, every affected gang returns to
                             Ready.
``chaos_kubelet_stall``      the kubelet stops flipping Ready for a
                             window: nothing reads falsely Ready, the
                             control plane itself stays ready (the
                             cluster is sick, not the plane), and the
                             backlog drains on recovery.
===========================  ===========================================

Invariant glossary and injector catalog: docs/chaos.md.
"""

from __future__ import annotations

import dataclasses
import shutil
import tempfile
import time
import urllib.error
import urllib.request

from service_account_auth_improvements_tpu.controlplane.controllers.notebook import (  # noqa: E501
    GROUP,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.loadgen import (  # noqa: E501
    LoadGenerator,
)
from service_account_auth_improvements_tpu.controlplane.cpbench import (
    park as park_bench,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.scenarios import (  # noqa: E501
    SCENARIOS,
    BenchConfig,
    ScenarioResult,
    _NotebookWorld,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.tracker import (  # noqa: E501
    RecoveryTracker,
)
from service_account_auth_improvements_tpu.controlplane.engine.serve import (
    serve_ops,
)
from service_account_auth_improvements_tpu.controlplane.kube import errors
from service_account_auth_improvements_tpu.controlplane.kube.chaos import (
    ChaosSchedule,
)
from service_account_auth_improvements_tpu.controlplane.metrics import (
    Registry,
)
from service_account_auth_improvements_tpu.controlplane import (
    obs,
    parking,
    tpu as tpu_mod,
)


# ------------------------------------------------------ invariant helpers

def _orphaned_children(kube) -> int:
    """Invariant counter: children that survived their owners, plus pods
    bound to nodes that no longer exist. Checked LIVE at settle (chaos
    off), so zero means the cluster truly converged clean."""
    notebooks = kube.list("notebooks", group=GROUP)["items"]
    sts = kube.list("statefulsets", group="apps")["items"]
    pods = kube.list("pods")["items"]
    nodes = {n["metadata"]["name"] for n in kube.list("nodes")["items"]}
    live_uids = {o["metadata"]["uid"] for o in notebooks + sts}
    orphans = 0
    for obj in sts + pods:
        refs = obj["metadata"].get("ownerReferences") or []
        ref_uids = [r.get("uid") for r in refs if r.get("uid")]
        if ref_uids and not any(u in live_uids for u in ref_uids):
            orphans += 1
    for pod in pods:
        bound = (pod.get("spec") or {}).get("nodeName")
        if bound and bound not in nodes:
            orphans += 1
    return orphans


def _pool_bookings(notebooks: list[dict]) -> dict[str, list[str]]:
    """pool → live notebooks annotated onto it; any bucket longer than 1
    is a double booking (the shared invariant of chaos_relist's poll
    loop and chaos_node_death's settle check)."""
    live_pools: dict[str, list[str]] = {}
    for nb in notebooks:
        pool = (nb["metadata"].get("annotations") or {}).get(
            tpu_mod.ANNOTATION_NODEPOOL)
        if pool:
            live_pools.setdefault(pool, []).append(nb["metadata"]["name"])
    return live_pools


class _PositionChecker:
    """Queue-position consistency over poll samples. Restamps are
    written lock-free after each placement pass, and under chaos a
    conflicted restamp legitimately re-levels up to ~1 s later (the
    scheduler's re-enqueue backoff) — so a transient duplicate is
    eventual consistency at work, not a violation. Only a duplicate
    assignment that PERSISTS unchanged past ``PERSIST_S`` (a wedge
    nothing is coming to fix) or a position outside 1..total (never
    legal: the pair is written atomically) counts."""

    PERSIST_S = 2.5

    def __init__(self):
        self.violations = 0
        self._streak: tuple | None = None
        self._streak_since = 0.0
        self._streak_counted = False

    def feed(self, notebooks: list[dict]) -> None:
        positions: dict[int, list[str]] = {}
        for nb in notebooks:
            for cond in (nb.get("status") or {}).get("conditions") or []:
                if cond.get("type") != "Scheduled" or \
                        cond.get("status") != "False":
                    continue
                pos, total = cond.get("queuePosition"), cond.get(
                    "queueTotal")
                if pos is None:
                    continue
                if pos < 1 or (total is not None and pos > total):
                    self.violations += 1   # hard bound: no excuse
                positions.setdefault(pos, []).append(
                    nb["metadata"]["name"])
        dupes = tuple(sorted(
            (p, tuple(sorted(names)))
            for p, names in positions.items() if len(names) > 1
        ))
        now = time.monotonic()
        if dupes and dupes == self._streak:
            if not self._streak_counted and \
                    now - self._streak_since >= self.PERSIST_S:
                self.violations += 1
                self._streak_counted = True
        else:
            self._streak = dupes or None
            self._streak_since = now
            self._streak_counted = False


def _caches_coherent(world, ns: str) -> bool:
    """True when the cached view of the notebooks matches the live
    apiserver state, name→resourceVersion exact. A storm's dropped/
    reordered events make the watch caches silently diverge; recovery
    is the moment they re-converge (reconnect replay or 410→relist).
    Costs one live LIST — only polled while a pulse is unresolved."""
    if not world.mgr.informers_synced():
        return False
    cached = {
        o["metadata"]["name"]: o["metadata"]["resourceVersion"]
        for o in world.cached.list("notebooks", namespace=ns,
                                   group=GROUP)["items"]
    }
    live = {
        o["metadata"]["name"]: o["metadata"]["resourceVersion"]
        for o in world.kube.list("notebooks", namespace=ns,
                                 group=GROUP)["items"]
    }
    return cached == live


def _http_status(port: int, path: str) -> int | None:
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=2) as resp:
            return resp.status
    except urllib.error.HTTPError as e:
        return e.code
    except Exception:
        return None


def _http_body(port: int, path: str) -> str | None:
    """Body of a 200 response over real HTTP, else None — the explainz
    acceptance check goes through the actual ops port, not a function
    call, so a broken route can't hide behind a working engine."""
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5) as resp:
            return resp.read().decode()
    except Exception:
        return None


def _mk_pool(kube, pool: str, hosts: int = 4, chips: str = "4",
             accelerator: str = "tpu-v5-lite-podslice",
             topology: str = "4x4") -> None:
    for h in range(hosts):
        kube.create("nodes", {
            "metadata": {
                "name": f"node-{pool}-{h}",
                "labels": {
                    tpu_mod.SEL_NODEPOOL: pool,
                    tpu_mod.SEL_ACCELERATOR: accelerator,
                    tpu_mod.SEL_TOPOLOGY: topology,
                },
            },
            "status": {"capacity": {tpu_mod.RESOURCE_TPU: chips}},
        })


def _chaos_result(world, cfg: BenchConfig, started: float, ok: bool,
                  rec: RecoveryTracker, chaos, extra: dict,
                  schedule: ChaosSchedule | None = None) -> ScenarioResult:
    orphans = _orphaned_children(world.kube)
    world.stop()
    summary = world.tracker.summary()
    summary["stage_attribution"] = world.attribution()
    chaos_extra = rec.summary()
    extra.setdefault("double_bookings", 0)
    extra["orphaned_children"] = orphans
    extra["recovery_ms"] = chaos_extra["recovery_ms"]
    extra["invariant_violations"] = chaos_extra["invariant_violations"]
    extra["injections"] = chaos.summary()
    if schedule is not None:
        extra["schedule_errors"] = schedule.errors
    extra.update(world.apiserver_extra(summary["reconciles"]))
    world.cpscope_extra(extra)
    summary["extra"] = extra
    # SLO attainment: recovery samples against the chaos-family ceiling
    # (create→Ready rides along — an outage must not break the product
    # promise, only dent the headroom)
    summary["slo"] = world.slo_record({"recovery": rec.samples()})
    violations = sum(chaos_extra["invariant_violations"].values())
    return ScenarioResult(
        name=world.tracker.scenario,
        elapsed_s=time.monotonic() - started,
        records=world.tracker.records(),
        summary=summary,
        ok=(ok and summary["failed"] == 0 and orphans == 0
            and extra["double_bookings"] == 0 and violations == 0
            and bool(extra["recovery_ms"])),
        # a chaos run with ANY violation ships its flight record even if
        # every notebook eventually converged — the evidence of what the
        # injections did is the point
        blackbox=world.blackbox(force=bool(violations or orphans)),
    )


# -------------------------------------------------------------- scenarios

def scenario_chaos_blackout(cfg: BenchConfig) -> ScenarioResult:
    """Total apiserver outage with work in flight. A healthy first wave
    proves the baseline; a second wave lands just before every verb
    starts 503ing and every watch channel is severed. The ops sidecar's
    /readyz (real HTTP, the kubelet's view) must flip false during the
    sustained outage and recover after; every in-flight notebook must
    still converge to Ready — no dropped status write, no lost child."""
    started = time.monotonic()
    world = _NotebookWorld(cfg, "chaos_blackout")
    chaos = world.kube.enable_chaos(seed=cfg.seed)
    chaos.journal = world.journal   # injections land in the flight record
    rec = RecoveryTracker()
    server = serve_ops(
        0, host="127.0.0.1", registry=Registry(),
        ready_check=world.mgr.informers_synced,
        ready_detail=world.mgr.informer_status,
        # the explainz acceptance surface: conditions/Events from the
        # fake apiserver, spans from the world tracer, decisions (incl.
        # the blackout itself) from the world journal
        tracer=world.trace, kube=world.kube, journal=world.journal,
    )
    port = server.server_address[1]
    try:
        world.start()
        ns = "bench"
        tpu = {"generation": "v5e", "topology": "2x2"}
        gen = LoadGenerator(cfg.concurrency, cfg.pattern, cfg.rate)

        pre = [f"bo-pre-{i}" for i in range(max(1, cfg.n // 2))]
        gen.run(world.create_jobs(pre, ns, tpu, want_ready=1))
        ok = world.tracker.wait_ready([(ns, n) for n in pre],
                                      cfg.timeout)

        post = [f"bo-post-{i}" for i in range(cfg.n - len(pre))]
        gen.run(world.create_jobs(post, ns, tpu, want_ready=1))
        # lights out while the second wave's reconciles/flips are in
        # flight
        blackout_s = cfg.chaos_window_s
        chaos.start_blackout(blackout_s, sever=True)
        flipped = False
        lights_on = time.monotonic() + blackout_s
        while time.monotonic() < lights_on:
            if _http_status(port, "/readyz") == 503:
                flipped = True
            time.sleep(0.1)
        # recovery leg 1: how long until /readyz reads ready again
        readyz_recover_ms = None
        deadline = time.monotonic() + cfg.timeout
        while time.monotonic() < deadline:
            if _http_status(port, "/readyz") == 200:
                readyz_recover_ms = round(
                    (time.monotonic() - lights_on) * 1000.0, 3)
                rec.note_recovery("readyz", readyz_recover_ms)
                break
            time.sleep(0.05)
        # recovery leg 2: the backlog drains — every notebook Ready
        keys = [(ns, n) for n in pre + post]
        ok = world.tracker.wait_ready(keys, cfg.timeout) and ok
        for name in post:
            r = world.tracker.record(ns, name)
            if (r is not None and r.ready is not None
                    and r.ready > lights_on):
                rec.note_recovery("notebook_ready",
                                  (r.ready - lights_on) * 1000.0)
        if not flipped:
            rec.violation("readyz_never_flipped")
        if readyz_recover_ms is None:
            rec.violation("readyz_never_recovered")
        # acceptance: every RECOVERED notebook's explain timeline —
        # fetched over the real ops port — must name the blackout, not
        # just show a generic slow patch (the whole point of folding
        # ambient chaos decisions into per-object timelines)
        explainz_ok = blackout_named = recovered = 0
        for name in pre + post:
            r = world.tracker.record(ns, name)
            if r is None or r.ready is None:
                continue
            recovered += 1
            body = _http_body(port, f"/debug/explainz/{ns}/{name}")
            if body is not None:
                explainz_ok += 1
                if "blackout" in body:
                    blackout_named += 1
        if blackout_named < recovered:
            rec.violation("blackout_not_named",
                          recovered - blackout_named)
        return _chaos_result(world, cfg, started, ok, rec, chaos, {
            "blackout_s": blackout_s,
            "readyz_flipped_false": flipped,
            "readyz_recover_ms": readyz_recover_ms,
            "explainz_http": {"answered": explainz_ok,
                              "blackout_named": blackout_named,
                              "recovered": recovered},
        })
    finally:
        # an exception anywhere above must not leak the ops server (a
        # listening port) or the world's informer/kubelet threads into
        # the next scenario; both are idempotent on the normal path
        world.stop()
        server.shutdown()
        server.server_close()


def scenario_chaos_relist(cfg: BenchConfig) -> ScenarioResult:
    """410 Gone storms + watch drops/reorders against a live tpusched
    drain. Storm pulses compact the watch history (every reconnect
    relists) and sever channels while events are randomly dropped and
    reordered; the drain (delete-on-Ready, like sched_contention)
    continues throughout. Invariants: no poll tick ever sees two live
    notebooks booked onto one pool, queue positions stay consistent,
    and every pulse's informer resync is timed as recovery."""
    started = time.monotonic()
    # relist_period: dropped watch events leave caches silently stale at
    # a CURRENT resourceVersion — only a periodic relist can heal that
    # (the engine knob this scenario exists to prove out)
    world = _NotebookWorld(cfg, "chaos_relist", scheduler=True,
                           relist_period=0.75)
    chaos = world.kube.enable_chaos(seed=cfg.seed)
    chaos.journal = world.journal
    rec = RecoveryTracker()
    ns = "bench"
    pools = max(2, cfg.n // 4)
    for p in range(pools):
        _mk_pool(world.kube, f"storm-pool-{p}")
    live: dict = {}   # the body parks its ChaosSchedule here for cleanup
    try:
        return _run_chaos_relist(cfg, world, chaos, rec, ns, started,
                                 live)
    finally:
        # an exception mid-scenario must not leave the schedule thread
        # firing storms or the world's informer/kubelet threads alive
        # while the run unwinds (both stops are idempotent on the
        # normal path)
        if live.get("schedule") is not None:
            live["schedule"].stop()
        world.stop()


def _run_chaos_relist(cfg, world, chaos, rec, ns, started,
                      live) -> ScenarioResult:
    pools = max(2, cfg.n // 4)
    world.start()

    pulse_marks: list[float] = []

    def pulse():
        chaos.set_watch_faults(drop_rate=0.2, reorder_rate=0.2)
        chaos.gone_storm()
        chaos.sever_watches()
        pulse_marks.append(time.monotonic())

    def calm():
        chaos.set_watch_faults(0.0, 0.0)

    steps = []
    last_at = 0.5
    for i in range(max(1, cfg.chaos_pulses)):
        at = 0.5 + i * 0.9
        last_at = at
        steps.append((at, f"pulse-{i}", pulse))
        steps.append((at + 0.45, f"calm-{i}", calm))
    # final heal: one more connection reset AFTER fidelity is restored —
    # any event dropped inside the last fault window is replayed/relisted
    # on reconnect, so the drain can't wedge on a lost final MODIFIED
    steps.append((last_at + 0.9, "heal", chaos.sever_watches))
    schedule = live["schedule"] = ChaosSchedule(steps).start()

    names = [f"storm-{i:03d}" for i in range(cfg.n)]
    tpu = {"generation": "v5e", "topology": "4x4"}
    LoadGenerator(cfg.concurrency, cfg.pattern, cfg.rate).run(
        world.create_jobs(names, ns, tpu, want_ready=4)
    )
    positions = _PositionChecker()
    deleted: set[str] = set()
    double_bookings = 0
    resynced_after: set[int] = set()
    want_pulses = max(1, cfg.chaos_pulses)
    deadline = time.monotonic() + cfg.timeout
    # run until the drain completes AND every scheduled pulse has fired
    # and been timed to recovery — a small run that drains before the
    # first storm lands hasn't been chaos-tested at all
    while (len(deleted) < len(names)
           or len(resynced_after) < want_pulses) \
            and time.monotonic() < deadline:
        # time each pulse's recovery: storm → watch caches coherent with
        # the apiserver again (only judged once the pulse's fault window
        # is over — mid-faults incoherence is the injection, not the
        # recovery)
        for i, mark in enumerate(list(pulse_marks)):
            if i in resynced_after:
                continue
            if time.monotonic() - mark < 0.5:
                break
            if _caches_coherent(world, ns):
                rec.note_recovery(
                    "cache_coherent", (time.monotonic() - mark) * 1000.0)
                resynced_after.add(i)
        snapshot = world.cached.list("notebooks", namespace=ns,
                                     group=GROUP)["items"]
        positions.feed(snapshot)
        live = [nb for nb in snapshot
                if nb["metadata"]["name"] not in deleted]
        double_bookings += sum(
            1 for m in _pool_bookings(live).values() if len(m) > 1)
        to_delete = []
        for nb in live:
            r = world.tracker.record(ns, nb["metadata"]["name"])
            if r is not None and r.ready is not None:
                to_delete.append(nb["metadata"]["name"])
        for name in to_delete:
            try:
                world.kube.delete("notebooks", name, namespace=ns,
                                  group=GROUP)
            except errors.NotFound:
                pass  # already collected; counts as drained
            deleted.add(name)
        time.sleep(0.02)
    schedule.stop()
    chaos.set_watch_faults(0.0, 0.0)
    ok = len(deleted) == len(names)
    if double_bookings:
        rec.violation("double_booking", double_bookings)
    if positions.violations:
        rec.violation("queue_position", positions.violations)
    if len(resynced_after) < want_pulses:
        # a pulse whose caches never re-converged is the exact failure
        # this scenario hunts — partial recovery must not pass just
        # because EARLIER pulses produced recovery_ms samples
        rec.violation("pulse_never_recovered",
                      want_pulses - len(resynced_after))
    return _chaos_result(world, cfg, started, ok, rec, chaos, {
        "pools": pools,
        "pulses": len(pulse_marks),
        "double_bookings": double_bookings,
        "position_violations": positions.violations,
        "drained": len(deleted),
    }, schedule=schedule)


def scenario_chaos_node_death(cfg: BenchConfig) -> ScenarioResult:
    """A busy pool's nodes die mid-gang and are auto-repaired. Every
    gang gets its own pool and reaches Ready; then one placed pool's
    Node objects are deleted with their bound pods force-removed (the
    node controller's eventual pod GC). The fake STS controller must
    replace the pods, the scheduler's bind retry must pick them up when
    the repaired nodes register, and the gang must return to Ready —
    with no orphaned children, no pod bound to a dead node, and no
    double-booked pool at settle."""
    started = time.monotonic()
    world = _NotebookWorld(cfg, "chaos_node_death", scheduler=True)
    chaos = world.kube.enable_chaos(seed=cfg.seed)
    chaos.journal = world.journal
    rec = RecoveryTracker()
    ns = "bench"
    n = max(2, cfg.n)
    for p in range(n):
        _mk_pool(world.kube, f"death-pool-{p}")
    try:
        return _run_chaos_node_death(cfg, world, chaos, rec, ns, n,
                                     started)
    finally:
        world.stop()   # idempotent; covers the exception path


def _run_chaos_node_death(cfg, world, chaos, rec, ns, n,
                          started) -> ScenarioResult:
    world.start()
    names = [f"mort-{i:02d}" for i in range(n)]
    tpu = {"generation": "v5e", "topology": "4x4"}
    LoadGenerator(cfg.concurrency, cfg.pattern, cfg.rate).run(
        world.create_jobs(names, ns, tpu, want_ready=4)
    )
    keys = [(ns, n_) for n_ in names]
    ok = world.tracker.wait_ready(keys, cfg.timeout)

    # find a placed pool and kill it under its gang
    victim_pool = None
    victims: list[str] = []
    for name in names:
        try:
            nb = world.cached.get("notebooks", name, namespace=ns,
                                  group=GROUP)
        except errors.NotFound:
            continue
        pool = (nb["metadata"].get("annotations") or {}).get(
            tpu_mod.ANNOTATION_NODEPOOL)
        if pool:
            victim_pool = pool
            victims = [name]
            break
    killed = chaos.kill_nodes(victim_pool, tpu_mod.SEL_NODEPOOL) \
        if victim_pool else []
    # the gang must actually observe the death (readyReplicas drops).
    # No victim (nothing got placed — the run already failed) → don't
    # spin the full timeout waiting on an empty list
    observed_down = False
    deadline = time.monotonic() + (cfg.timeout if victims else 0)
    while time.monotonic() < deadline and not observed_down:
        for name in victims:
            try:
                nb = world.cached.get("notebooks", name, namespace=ns,
                                      group=GROUP)
            except errors.NotFound:
                continue
            if ((nb.get("status") or {}).get("readyReplicas") or 0) < 4:
                observed_down = True
        time.sleep(0.02)
    time.sleep(0.3)   # let the replacement pods pile up unbindable
    chaos.repair_nodes()
    repaired_at = time.monotonic()
    # recovery: each victim gang returns to full readiness
    pending = set(victims)
    deadline = time.monotonic() + cfg.timeout
    while pending and time.monotonic() < deadline:
        for name in list(pending):
            try:
                nb = world.cached.get("notebooks", name, namespace=ns,
                                      group=GROUP)
            except errors.NotFound:
                continue
            if ((nb.get("status") or {}).get("readyReplicas") or 0) >= 4:
                rec.note_recovery(
                    "re_ready",
                    (time.monotonic() - repaired_at) * 1000.0)
                pending.discard(name)
        time.sleep(0.02)
    ok = ok and observed_down and not pending
    if not observed_down:
        rec.violation("death_not_observed")
    if pending:
        rec.violation("gang_never_recovered", len(pending))
    # settle: one live booking per pool
    double = sum(
        1 for m in _pool_bookings(
            world.cached.list("notebooks", namespace=ns,
                              group=GROUP)["items"]
        ).values() if len(m) > 1)
    if double:
        rec.violation("double_booking", double)
    return _chaos_result(world, cfg, started, ok, rec, chaos, {
        "pools": n,
        "nodes_killed": len(killed),
        "victim_pool": victim_pool,
        "victim_gangs": victims,
        "observed_down": observed_down,
        "double_bookings": double,
    })


def scenario_chaos_kubelet_stall(cfg: BenchConfig) -> ScenarioResult:
    """The kubelet wedges: pods schedule and bind but stop flipping
    Ready for a window. Nothing may read falsely Ready during the stall
    (the tracker would see it), the control plane itself must STAY
    ready (/readyz semantics: the cluster is sick, the plane is not),
    and the backlog must drain once the stall lifts — recovery is
    unstall → Ready per held notebook."""
    started = time.monotonic()
    world = _NotebookWorld(cfg, "chaos_kubelet_stall")
    chaos = world.kube.enable_chaos(seed=cfg.seed)
    chaos.journal = world.journal
    rec = RecoveryTracker()
    try:
        return _run_chaos_kubelet_stall(cfg, world, chaos, rec, started)
    finally:
        world.stop()   # idempotent; covers the exception path


def _run_chaos_kubelet_stall(cfg, world, chaos, rec,
                             started) -> ScenarioResult:
    world.start()
    ns = "bench"
    tpu = {"generation": "v5e", "topology": "2x2"}
    gen = LoadGenerator(cfg.concurrency, cfg.pattern, cfg.rate)

    pre = [f"st-pre-{i}" for i in range(max(1, cfg.n // 2))]
    gen.run(world.create_jobs(pre, ns, tpu, want_ready=1))
    ok = world.tracker.wait_ready([(ns, n) for n in pre], cfg.timeout)

    world.actuator.stall()
    chaos._note("kubelet_stalled")
    held = [f"st-held-{i}" for i in range(cfg.n - len(pre))]
    gen.run(world.create_jobs(held, ns, tpu, want_ready=1))
    stall_until = time.monotonic() + cfg.chaos_stall_s
    false_ready = 0
    plane_ready_samples = 0
    plane_ready_true = 0
    while time.monotonic() < stall_until:
        for name in held:
            r = world.tracker.record(ns, name)
            if r is not None and r.ready is not None:
                false_ready += 1
        plane_ready_samples += 1
        plane_ready_true += int(world.mgr.informers_synced())
        time.sleep(0.05)
    world.actuator.unstall()
    chaos._note("kubelet_unstalled")
    unstalled_at = time.monotonic()
    ok = world.tracker.wait_ready([(ns, n) for n in held],
                                  cfg.timeout) and ok
    for name in held:
        r = world.tracker.record(ns, name)
        if r is not None and r.ready is not None and \
                r.ready > unstalled_at:
            rec.note_recovery("unstall_to_ready",
                              (r.ready - unstalled_at) * 1000.0)
    if false_ready:
        rec.violation("false_ready", false_ready)
    if plane_ready_true < plane_ready_samples:
        # a sick cluster must not read as a sick control plane
        rec.violation("plane_flapped_during_stall",
                      plane_ready_samples - plane_ready_true)
    return _chaos_result(world, cfg, started, ok, rec, chaos, {
        "stall_s": cfg.chaos_stall_s,
        "false_ready": false_ready,
        "held_notebooks": len(held),
        "plane_ready_during_stall":
            plane_ready_true == plane_ready_samples,
    })


def scenario_chaos_429_storm(cfg: BenchConfig) -> ScenarioResult:
    """Apiserver flow control squeezing the CONTROLLERS mid-drain — the
    429-storm injector the PR 6 chaos item promised (kube/chaos.py
    ``storm_429``). Pulses of sustained 429 + Retry-After hit every
    control-plane flow (the manager's informer traffic and each
    reconciler's actor-attributed requests) while a tpusched drain is
    in flight; the kubelet and the bench's own lanes keep their seats.
    Invariants: every controller retries THROUGH the throttling without
    losing a booking — 0 double-booked pools at any tick, 0 orphans,
    the drain completes — and each pulse's recovery (storm end → next
    notebook Ready) is timed."""
    started = time.monotonic()
    world = _NotebookWorld(cfg, "chaos_429_storm", scheduler=True)
    chaos = world.kube.enable_chaos(seed=cfg.seed)
    chaos.journal = world.journal
    rec = RecoveryTracker()
    ns = "bench"
    pools = max(2, cfg.n // 4)
    for p in range(pools):
        _mk_pool(world.kube, f"storm429-pool-{p}")
    live: dict = {}
    try:
        return _run_chaos_429_storm(cfg, world, chaos, rec, ns, pools,
                                    started, live)
    finally:
        if live.get("schedule") is not None:
            live["schedule"].stop()
        chaos.end_storm_429()
        world.stop()


def _run_chaos_429_storm(cfg, world, chaos, rec, ns, pools, started,
                         live) -> ScenarioResult:
    world.start()
    #: who gets squeezed: the manager's own traffic and every
    #: reconcile-actor flow — NOT the kubelet ("the kubelet keeps its
    #: lane") and not the bench's poll client
    squeezed = ("manager", "*Reconciler")
    window_s = max(0.8, cfg.chaos_stall_s / 2)
    pulse_marks: list[float] = []
    pulse_pending: list[int] = []

    def pulse():
        pending = sum(1 for r in world.tracker.records()
                      if r.ready is None)
        pulse_pending.append(pending)
        chaos.storm_429(clients=squeezed, duration_s=window_s,
                        rate=1.0, retry_after=1)
        pulse_marks.append(time.monotonic() + window_s)  # pulse END

    want_pulses = max(1, cfg.chaos_pulses - 1)
    steps = []
    for i in range(want_pulses):
        steps.append((0.15 + i * (window_s + 1.0), f"storm429-{i}",
                      pulse))
    schedule = live["schedule"] = ChaosSchedule(steps).start()

    gen = LoadGenerator(cfg.concurrency, cfg.pattern, cfg.rate)
    tpu = {"generation": "v5e", "topology": "4x4"}
    all_names: list[str] = []
    wave = 0

    def create_wave():
        nonlocal wave
        names = [f"thr-w{wave}-{i:03d}" for i in range(cfg.n)]
        wave += 1
        all_names.extend(names)
        gen.run(world.create_jobs(names, ns, tpu, want_ready=4))

    create_wave()
    deleted: set[str] = set()
    double_bookings = 0
    deadline = time.monotonic() + cfg.timeout \
        + want_pulses * (window_s + 1.0)
    while time.monotonic() < deadline:
        drained = len(deleted) == len(all_names)
        pulses_over = (len(pulse_marks) >= want_pulses
                       and time.monotonic() > pulse_marks[-1] + 0.1)
        if drained and pulses_over:
            break
        if drained:
            # the drain outran the storm schedule: top up with another
            # wave so every pulse throttles controllers doing REAL work
            # — a pulse fired into an idle plane proves nothing
            create_wave()
        snapshot = world.cached.list("notebooks", namespace=ns,
                                     group=GROUP)["items"]
        live_nbs = [nb for nb in snapshot
                    if nb["metadata"]["name"] not in deleted]
        double_bookings += sum(
            1 for m in _pool_bookings(live_nbs).values() if len(m) > 1)
        for nb in live_nbs:
            r = world.tracker.record(ns, nb["metadata"]["name"])
            if r is not None and r.ready is not None:
                name = nb["metadata"]["name"]
                try:
                    world.kube.delete("notebooks", name, namespace=ns,
                                      group=GROUP)
                except errors.NotFound:
                    pass
                deleted.add(name)
        time.sleep(0.02)
    schedule.stop()
    chaos.end_storm_429()
    ok = len(deleted) == len(all_names) \
        and len(pulse_marks) >= want_pulses
    # recovery per pulse: storm end → the next notebook turning Ready
    # (throttled controllers resumed converging work)
    readies = sorted(r.ready for r in world.tracker.records()
                     if r.ready is not None)
    for end_mark in pulse_marks:
        after = [t for t in readies if t > end_mark]
        if after:
            rec.note_recovery("post_storm_ready",
                              (after[0] - end_mark) * 1000.0)
    if double_bookings:
        rec.violation("double_booking", double_bookings)
    if pulse_marks and not any(pulse_pending):
        # every pulse fired into an already-drained world: the scenario
        # throttled nobody doing real work — that is not evidence
        rec.violation("storm_missed_work")
    throttled_by_client = {
        c: v.get("429", 0)
        for c, v in world.kube.request_counts_snapshot(
            by_client=True).items()
        if v.get("429")
    }
    if not throttled_by_client:
        rec.violation("storm_never_throttled")
    if throttled_by_client.get("kubelet") or \
            throttled_by_client.get("cpbench"):
        # the protected lanes must keep their seats: a throttled
        # kubelet/bench client means the squeeze hit the wrong flows
        rec.violation("protected_lane_throttled")
    return _chaos_result(world, cfg, started, ok, rec, chaos, {
        "pools": pools,
        "pulses": len(pulse_marks),
        "pulse_window_s": window_s,
        "pulse_pending": pulse_pending,
        "squeezed_clients": list(squeezed),
        "double_bookings": double_bookings,
        "drained": len(deleted),
        "throttled_by_client": throttled_by_client,
    }, schedule=schedule)


def scenario_chaos_park_blackout(cfg: BenchConfig) -> ScenarioResult:
    """Parked checkpoints survive a blackout. Half the fleet is placed
    and Ready on one-slice pools, the other half queued behind them.
    Park requests are stamped on every placed notebook and the apiserver
    goes dark (every verb 503, watch channels severed) while the
    culler's checkpoint+stop patches are in flight; a second outage
    lands the same way mid-resume. Invariants: zero lost checkpoints
    (every Parked CR's ref still restores), zero CRs stopped-with-parked
    but missing their checkpoint ref (the single-patch commit held
    through the outage), zero double bookings while freed pools re-admit
    the waiters, and every parked notebook both parks and resumes after
    lights-on."""
    started = time.monotonic()
    store = tempfile.mkdtemp(prefix="cpbench-park-chaos-")
    try:
        return _run_chaos_park_blackout(cfg, started, store)
    finally:
        shutil.rmtree(store, ignore_errors=True)


def _park_observe(fn, default):
    """Bench-side observation during an outage: the poll reads ride the
    same apiserver the blackout is 503ing, so an unobservable tick
    reports ``default`` instead of crashing the scenario — nothing the
    tick would have seen can change until the lights come back on."""
    try:
        return fn()
    except errors.ApiError:
        return default


def _run_chaos_park_blackout(cfg: BenchConfig, started: float,
                             store: str) -> ScenarioResult:
    world = park_bench._mk_park_world(cfg, "chaos_park_blackout", store,
                                      scheduler=True)
    chaos = world.kube.enable_chaos(seed=cfg.seed)
    chaos.journal = world.journal
    rec = RecoveryTracker()
    try:
        world.start()
        ns = "bench"
        n = max(2, cfg.n - cfg.n % 2)
        pools = [f"pkbo-pool-{i}" for i in range(n // 2)]
        for p in pools:
            # one 2x2 slice per pool: >1 booking on a pool is a double
            # booking by construction
            _mk_pool(world.kube, p, hosts=1, chips="4", topology="2x2")
        tpu = {"generation": "v5e", "topology": "2x2"}
        names = [f"pkbo-{i:02d}" for i in range(n)]
        gen = LoadGenerator(cfg.concurrency, cfg.pattern, cfg.rate)
        gen.run(world.create_jobs(names, ns, tpu, want_ready=1))
        # capacity fits exactly half: wait for that many Ready, the rest
        # hold queue positions behind them
        first: list[str] = []
        deadline = time.monotonic() + cfg.timeout
        while time.monotonic() < deadline and len(first) < len(pools):
            first = [nm for nm in names
                     if (r := world.tracker.record(ns, nm)) is not None
                     and r.ready is not None]
            time.sleep(0.05)
        ok = len(first) == len(pools)
        waiters = [nm for nm in names if nm not in first]

        # stamp park requests, then lights out while the checkpoint+stop
        # patches are in flight on the culler's cadence
        for nm in first:
            park_bench._request_park(world, ns, nm)
        lights_on = time.monotonic() + cfg.chaos_window_s
        chaos.start_blackout(cfg.chaos_window_s, sever=True)
        double_bookings = 0
        parked: set[str] = set()
        deadline = time.monotonic() + cfg.timeout + cfg.chaos_window_s
        while time.monotonic() < deadline and len(parked) < len(first):
            for nm in first:
                if nm in parked:
                    continue
                a = _park_observe(
                    lambda nm=nm: park_bench._annots(world, ns, nm),
                    None)
                if a is not None and park_bench._is_parked(a):
                    parked.add(nm)
                    rec.note_recovery("park", max(
                        0.0, (time.monotonic() - lights_on) * 1000.0))
            double_bookings = max(double_bookings, _park_observe(
                lambda: park_bench._audit_double_bookings(world, ns), 0))
            time.sleep(0.05)
        if len(parked) < len(first):
            rec.violation("park_never_completed",
                          len(first) - len(parked))
        # mid-park atomicity: parked-but-checkpointless would mean the
        # outage tore the single-patch commit apart
        torn = 0
        for nm in first:
            a = park_bench._annots(world, ns, nm) or {}
            if parking.PARKED_ANNOTATION in a and \
                    parking.CHECKPOINT_ANNOTATION not in a:
                torn += 1
        if torn:
            rec.violation("stopped_without_checkpoint", torn)
        lost = park_bench._lost_checkpoints(world, ns, names)
        if lost:
            rec.violation("lost_checkpoint", lost)
        # the parks freed real chips: the queued half must place and
        # converge on the released pools
        ok = world.tracker.wait_ready(
            [(ns, nm) for nm in waiters], cfg.timeout) and ok

        # drain the second wave, then a second outage mid-resume
        for nm in waiters:
            try:
                world.kube.delete("notebooks", nm, namespace=ns,
                                  group=GROUP)
            except errors.NotFound:
                pass
        for nm in sorted(parked):
            park_bench._request_resume(world, ns, nm)
        lights_on = time.monotonic() + cfg.chaos_window_s
        chaos.start_blackout(cfg.chaos_window_s, sever=True)
        resumed: set[str] = set()
        deadline = time.monotonic() + cfg.timeout + cfg.chaos_window_s
        while time.monotonic() < deadline and len(resumed) < len(parked):
            for nm in sorted(parked):
                if nm not in resumed and _park_observe(
                        lambda nm=nm: park_bench._is_resumed(
                            world, ns, nm, 1), False):
                    resumed.add(nm)
                    rec.note_recovery("resume", max(
                        0.0, (time.monotonic() - lights_on) * 1000.0))
            double_bookings = max(double_bookings, _park_observe(
                lambda: park_bench._audit_double_bookings(world, ns), 0))
            time.sleep(0.05)
        if len(resumed) < len(parked):
            rec.violation("resume_never_completed",
                          len(parked) - len(resumed))
        lost_after = park_bench._lost_checkpoints(world, ns, names)
        if lost_after:
            rec.violation("lost_checkpoint_post_resume", lost_after)
        ok = (ok and torn == 0 and lost == 0 and lost_after == 0
              and double_bookings == 0
              and len(parked) == len(first)
              and len(resumed) == len(parked))
        return _chaos_result(world, cfg, started, ok, rec, chaos, {
            "pools": len(pools),
            "parked": len(parked),
            "resumed": len(resumed),
            "double_bookings": double_bookings,
            "lost_checkpoints": lost + lost_after,
            "stopped_without_checkpoint": torn,
        })
    finally:
        world.stop()


def scenario_chaos_alert_fidelity(cfg: BenchConfig) -> ScenarioResult:
    """The fleet's page alert is TRUSTWORTHY: zero false fires over a
    healthy canary lane, fires during an injected apiserver blackout,
    resolves promptly after recovery. The full production pipeline runs
    over real HTTP — a canary SloEngine exposes cumulative counters on
    an ops port, the FleetAggregator scrapes/merges them, and the
    AlertEngine evaluates the SRE-workbook page rule (14.4x burn over
    both windows, windows compressed via ``AlertRule.scaled`` so the
    REAL window math runs against a seconds-long outage). The canary is
    an apiserver LIST on a deadline; the blackout 503s it instantly, so
    every dark tick is a violation the moment it happens — no waiting
    out a timeout to learn the apiserver is gone."""
    started = time.monotonic()
    world = _NotebookWorld(cfg, "chaos_alert_fidelity")
    chaos = world.kube.enable_chaos(seed=cfg.seed)
    chaos.journal = world.journal
    rec = RecoveryTracker()
    registry = Registry()
    canary = obs.Objective(
        "canary_probe",
        "alert-fidelity canary: apiserver LIST round-trip under the "
        "probe deadline (an outage violates instantly)",
        target_ms=250.0,
    )
    canary_slo = obs.SloEngine(objectives=(canary,), registry=registry)
    # the workbook page rule with compressed windows: scaled() shrinks
    # the 5 m short window to 0.8 s; the long window is then pinned to
    # 2.5 s (the workbook's 1:12 ratio would need a 10 s+ blackout to
    # saturate — the threshold/two-window math is what's under test,
    # not the wall-clock size of the windows)
    base = next(r for r in obs.DEFAULT_RULES if r.severity == "page")
    page = dataclasses.replace(base.scaled(0.8 / base.short_s),
                               long_s=2.5)
    engine = obs.AlertEngine(
        objectives=(canary,), rules=(page,),
        journal=world.journal,
        recorder=obs.EventRecorder(world.kube, "cpfleet-bench"),
        namespace="bench",
    )
    server = serve_ops(0, host="127.0.0.1", registry=registry,
                       tracer=world.trace, slo=canary_slo,
                       alerts=engine)
    port = server.server_address[1]
    agg = obs.FleetAggregator(
        lambda: {"replica-0": f"http://127.0.0.1:{port}"},
        objectives=(canary,), alerts=engine, journal=world.journal,
    )
    try:
        return _run_chaos_alert_fidelity(
            cfg, world, chaos, rec, started,
            canary=canary, canary_slo=canary_slo, engine=engine,
            agg=agg, page=page, port=port)
    finally:
        world.stop()
        server.shutdown()
        server.server_close()


def _run_chaos_alert_fidelity(cfg, world, chaos, rec, started, *,
                              canary, canary_slo, engine, agg, page,
                              port) -> ScenarioResult:
    world.start()
    ns = "bench"
    tpu = {"generation": "v5e", "topology": "2x2"}
    names = [f"fid-{i}" for i in range(cfg.n)]
    LoadGenerator(cfg.concurrency, cfg.pattern, cfg.rate).run(
        world.create_jobs(names, ns, tpu, want_ready=1))
    ok = world.tracker.wait_ready([(ns, n) for n in names], cfg.timeout)

    canaries = 0

    def tick():
        # one canary probe + one full fleet scrape (real HTTP): the
        # exact production data path metric → scrape → merge → evaluate
        nonlocal canaries
        canaries += 1
        t0 = time.monotonic()
        try:
            world.kube.list("notebooks", namespace=ns, group=GROUP)
            canary_ms = (time.monotonic() - t0) * 1000.0
        except errors.ApiError:
            canary_ms = canary.target_ms * 20
        canary_slo.observe("canary_probe", canary_ms)
        agg.scrape_once()
        time.sleep(0.08)

    def page_row() -> dict:
        return next(r for r in engine.status()["rules"]
                    if r["severity"] == "page")

    # phase 1 — healthy lane, longer than the long window: any fire
    # here is a false fire (the zero-false-positives half of fidelity)
    healthy_until = time.monotonic() + page.long_s + 1.0
    while time.monotonic() < healthy_until:
        tick()
    false_fires = page_row()["fired_count"]
    if false_fires:
        rec.violation("alert_false_fire", false_fires)

    # phase 2 — lights out; the page must fire while the outage is
    # still in progress (an alert that fires after recovery is a report,
    # not a page)
    blackout_s = cfg.chaos_window_s
    dark_at = time.monotonic()
    lights_on = dark_at + blackout_s
    chaos.start_blackout(blackout_s, sever=True)
    fired_ms = None
    alertz_saw_firing = False
    while time.monotonic() < lights_on:
        tick()
        if fired_ms is None and page_row()["state"] == "firing":
            fired_ms = round((time.monotonic() - dark_at) * 1000.0, 3)
            rec.note_recovery("alert_fire", fired_ms)
            # acceptance over the wire: /alertz (always answerable,
            # even mid-outage — the ops port is not the apiserver)
            body = _http_body(port, "/alertz")
            alertz_saw_firing = bool(body) and '"firing"' in body
    if fired_ms is None:
        rec.violation("page_never_fired")

    # phase 3 — recovery: healthy canaries drain the short window and
    # the page must resolve (the multi-window shape's whole point: no
    # hour of post-incident paging)
    resolved_ms = None
    deadline = time.monotonic() + cfg.timeout
    while time.monotonic() < deadline:
        tick()
        if fired_ms is not None and page_row()["state"] == "ok":
            resolved_ms = round(
                (time.monotonic() - lights_on) * 1000.0, 3)
            rec.note_recovery("alert_resolve", resolved_ms)
            break
    if fired_ms is not None and resolved_ms is None:
        rec.violation("page_never_resolved")

    # the plane itself must also have survived: a post-outage wave
    # converges (informers healed), so alert fidelity never trades away
    # the blackout scenario's recovery promise
    post = [f"fid-post-{i}" for i in range(max(1, cfg.n // 2))]
    LoadGenerator(cfg.concurrency, cfg.pattern, cfg.rate).run(
        world.create_jobs(post, ns, tpu, want_ready=1))
    ok = world.tracker.wait_ready([(ns, n) for n in post],
                                  cfg.timeout) and ok

    fired = fired_ms is not None
    resolved = resolved_ms is not None
    ok = ok and false_fires == 0 and fired and resolved
    return _chaos_result(world, cfg, started, ok, rec, chaos, {
        "blackout_s": blackout_s,
        "alert_fidelity": {
            "false_fires": false_fires,
            "fired_during_blackout": fired,
            "resolved_after_recovery": resolved,
            "fire_after_ms": fired_ms,
            "resolve_after_ms": resolved_ms,
            "alertz_http_firing": alertz_saw_firing,
            "canaries": canaries,
            "page_rule": {"threshold": page.burn_threshold,
                          "short_s": page.short_s,
                          "long_s": page.long_s},
        },
    })


CHAOS_SCENARIOS = {
    "chaos_relist": scenario_chaos_relist,
    "chaos_blackout": scenario_chaos_blackout,
    "chaos_node_death": scenario_chaos_node_death,
    "chaos_kubelet_stall": scenario_chaos_kubelet_stall,
    "chaos_429_storm": scenario_chaos_429_storm,
    "chaos_park_blackout": scenario_chaos_park_blackout,
    "chaos_alert_fidelity": scenario_chaos_alert_fidelity,
}

# the family registers into the shared scenario table (run_scenario and
# the CLI reach it there); importing this module is the registration
SCENARIOS.update(CHAOS_SCENARIOS)
