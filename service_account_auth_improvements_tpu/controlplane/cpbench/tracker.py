"""Latency tracker: per-CR timelines + percentile aggregation.

One ``Timeline`` per CR records the monotonic instants of the lifecycle
the bench measures: **create** (stamped by the load generator just
before the POST, so create ≤ first-reconcile is monotone by
construction) → **first reconcile** (stamped by wrapping the
reconciler's ``reconcile`` — the instrumentation point controller-
runtime exposes as ``controller_runtime_reconcile_time_seconds``) →
**STS created** (stamped by wrapping ``FakeKube.create``, the exact
apiserver write) → **Ready** (stamped by a watch on the primary
resource, the same observation path a user's ``kubectl wait`` has).

Durations are observed into a ``metrics/registry.py`` Histogram
(``cpbench_phase_seconds{scenario,phase}``) — the Prometheus surface a
deployed bench would scrape — while raw samples are kept for EXACT
percentiles in the JSON report (bucketed histograms can only
interpolate; a regression gate wants the real p99).
"""

from __future__ import annotations

import dataclasses
import threading
import time

from service_account_auth_improvements_tpu.controlplane import obs
from service_account_auth_improvements_tpu.controlplane.metrics import (
    Counter,
    Histogram,
    Registry,
)

#: histogram buckets shaped for control-plane latencies (5 ms .. 60 s)
PHASE_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5,
                 10, 30, 60)


def percentiles(samples, qs=(50, 95, 99)) -> dict:
    """Exact percentiles (linear interpolation) of raw samples, plus
    mean/max. Returns {} for no samples."""
    if not samples:
        return {}
    xs = sorted(samples)
    out = {}
    for q in qs:
        pos = (q / 100.0) * (len(xs) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(xs) - 1)
        out[f"p{q}"] = xs[lo] + (xs[hi] - xs[lo]) * (pos - lo)
    out["mean"] = sum(xs) / len(xs)
    out["max"] = xs[-1]
    out["n"] = len(xs)
    return out


@dataclasses.dataclass
class Timeline:
    """Per-CR lifecycle instants (time.monotonic seconds)."""

    namespace: str
    name: str
    created: float | None = None
    first_reconcile: float | None = None
    sts_created: float | None = None
    ready: float | None = None
    actuation: float = 0.0     # kubelet-injected seconds (critical path)
    #: internal: a ready observation is in flight (claimed before the
    #: actuation lookup so `ready` only becomes visible fully attributed)
    claimed: bool = False

    @property
    def key(self) -> tuple[str, str]:
        return (self.namespace, self.name)

    def phase_ms(self) -> dict:
        """Durations from create, in milliseconds (None where the phase
        never happened)."""

        def d(t):
            return ((t - self.created) * 1000.0
                    if t is not None and self.created is not None else None)

        out = {
            "create_to_first_reconcile": d(self.first_reconcile),
            "create_to_sts_created": d(self.sts_created),
            "create_to_ready": d(self.ready),
        }
        if out["create_to_ready"] is not None:
            out["actuation"] = self.actuation * 1000.0
            out["controller_overhead"] = max(
                out["create_to_ready"] - out["actuation"], 0.0
            )
        return out


class Tracker:
    """Collects timelines and reconcile-loop counters for one scenario."""

    def __init__(self, scenario: str, registry: Registry | None = None):
        self.scenario = scenario
        self.registry = registry or Registry()
        self.hist = Histogram(
            "cpbench_phase_seconds",
            "control-plane bench phase latency",
            labels=("scenario", "phase"), buckets=PHASE_BUCKETS,
            registry=self.registry,
        )
        self.m_reconciles = Counter(
            "cpbench_reconciles_total", "reconcile calls observed",
            labels=("scenario",), registry=self.registry,
        )
        self._lock = threading.Condition()
        self._records: dict[tuple[str, str], Timeline] = {}
        self.reconciles = 0
        self.requeues = 0
        self.backoffs = 0
        #: optional (ns, name) -> seconds of kubelet-injected latency;
        #: scenarios point this at FakeKubelet.actuation_for so ready
        #: observations can split actuation from controller overhead
        self.actuation_fn = None

    # ------------------------------------------------------------- records

    def expect(self, namespace: str | None, name: str) -> Timeline:
        """Register a CR about to be created; call BEFORE the create so
        the timeline is monotone by construction."""
        rec = Timeline(namespace or "", name, created=time.monotonic())
        with self._lock:
            self._records[rec.key] = rec
        return rec

    def records(self) -> list[Timeline]:
        with self._lock:
            return list(self._records.values())

    def record(self, namespace: str | None, name: str) -> Timeline | None:
        with self._lock:
            return self._records.get((namespace or "", name))

    # ----------------------------------------------------- instrumentation

    def instrument_reconciler(self, reconciler) -> None:
        """Wrap ``reconcile`` to stamp first-reconcile and count
        reconciles / requeues / backoff-retries (the queue's
        add_rate_limited path is entered exactly when reconcile raises)."""
        orig = reconciler.reconcile

        def wrapped(req):
            now = time.monotonic()
            with self._lock:
                self.reconciles += 1
                rec = self._records.get((req.namespace or "", req.name))
                if rec is not None and rec.first_reconcile is None:
                    rec.first_reconcile = now
            self.m_reconciles.labels(self.scenario).inc()
            try:
                result = orig(req)
            except Exception:
                with self._lock:
                    self.backoffs += 1
                raise
            if result is not None and (result.requeue
                                       or result.requeue_after):
                with self._lock:
                    self.requeues += 1
            return result

        reconciler.reconcile = wrapped

    def instrument_kube(self, kube, tracer=None) -> None:
        """Wrap ``FakeKube.create`` to stamp the first owned-STS create
        per CR at the apiserver write itself (no watch-dispatch skew).
        With a tracer, the notebook POST itself (apiserver lock + watch
        fanout — real time under burst load) becomes an
        ``apiserver.create`` span on the CR's trace."""
        orig = kube.create

        def create(plural, obj, namespace=None, group=None):
            t0 = time.monotonic()
            out = orig(plural, obj, namespace=namespace, group=group)
            if tracer is not None and plural == "notebooks":
                meta = out.get("metadata") or {}
                tracer.record(
                    "apiserver.create",
                    obs.object_key("notebooks", meta.get("namespace"),
                                   meta.get("name", "")),
                    t0, time.monotonic(),
                )
            if plural == "statefulsets":
                meta = out.get("metadata") or {}
                nb = (meta.get("labels") or {}).get("notebook-name")
                if nb:
                    now = time.monotonic()
                    with self._lock:
                        rec = self._records.get(
                            (meta.get("namespace") or "", nb))
                        if rec is not None and rec.sts_created is None:
                            rec.sts_created = now
            return out

        kube.create = create

    # -------------------------------------------------------------- ready

    def note_ready(self, namespace: str | None, name: str) -> None:
        """Idempotent: the first observation wins (watch handlers fire
        for every later status refresh too)."""
        now = time.monotonic()
        with self._lock:
            rec = self._records.get((namespace or "", name))
            if rec is None or rec.claimed:
                return
            rec.claimed = True
        # attribute actuation BEFORE publishing readiness: a waiter that
        # wakes from wait_ready and summarizes immediately must never
        # see ready set with actuation still 0.0 (it would book the
        # whole kubelet latency as controller overhead)
        actuation = (self.actuation_fn(rec.namespace, rec.name)
                     if self.actuation_fn is not None else 0.0)
        with self._lock:
            rec.actuation = actuation
            rec.ready = now
            self._lock.notify_all()
        for phase, ms in rec.phase_ms().items():
            if ms is not None:
                self.hist.labels(self.scenario, phase).observe(ms / 1000.0)

    def wait_ready(self, keys, timeout: float) -> bool:
        """Block until every (ns, name) in ``keys`` has a ready stamp."""
        keys = [(ns or "", name) for ns, name in keys]
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                missing = [
                    k for k in keys
                    if (r := self._records.get(k)) is None
                    or r.ready is None
                ]
                if not missing:
                    return True
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._lock.wait(min(left, 0.2))

    # ------------------------------------------------------------- summary

    def summary(self) -> dict:
        recs = self.records()
        phases: dict[str, list] = {}
        for rec in recs:
            for phase, ms in rec.phase_ms().items():
                if ms is not None:
                    phases.setdefault(phase, []).append(ms)
        completed = sum(1 for r in recs if r.ready is not None)
        return {
            "n": len(recs),
            "completed": completed,
            "failed": len(recs) - completed,
            "phases_ms": {p: percentiles(v) for p, v in phases.items()},
            "reconciles": self.reconciles,
            "requeues": self.requeues,
            "backoffs": self.backoffs,
        }


# ------------------------------------------------------ chaos bookkeeping

class RecoveryTracker:
    """Chaos-scenario ledger: recovery-time samples per injection kind
    plus invariant-violation counters.

    A chaos scenario's verdict is two-sided — *did the invariants hold*
    (violations, must be zero) and *how fast did the plane heal*
    (recovery samples, reported as p50/p95 in CONTROLPLANE_BENCH.json
    and gated by tools/bench_gate.py). Thread-safe: watch handlers and
    the scenario's poll loop both stamp it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._samples: dict[str, list[float]] = {}
        self._violations: dict[str, int] = {}

    def note_recovery(self, kind: str, ms: float) -> None:
        """One healed-after-injection sample (milliseconds)."""
        with self._lock:
            self._samples.setdefault(kind, []).append(ms)

    def violation(self, kind: str, n: int = 1) -> None:
        """An invariant broke (double booking, orphan, false-ready...)."""
        with self._lock:
            self._violations[kind] = self._violations.get(kind, 0) + n

    def violations(self, kind: str) -> int:
        with self._lock:
            return self._violations.get(kind, 0)

    def samples(self) -> list[float]:
        """Flat list of every recovery sample (ms) — the raw input to
        the per-scenario SLO attainment record (obs/slo.py)."""
        with self._lock:
            return [s for v in self._samples.values() for s in v]

    def recovery_ms(self) -> dict:
        """{kind: percentiles} over every sample recorded so far; the
        flat union rides under the "all" key so the gate has one field
        to require."""
        with self._lock:
            per = {k: percentiles(v, qs=(50, 95))
                   for k, v in self._samples.items() if v}
            every = [s for v in self._samples.values() for s in v]
        if every:
            per["all"] = percentiles(every, qs=(50, 95))
        return per

    def summary(self) -> dict:
        with self._lock:
            violations = dict(self._violations)
        return {"recovery_ms": self.recovery_ms(),
                "invariant_violations": violations}


# -------------------------------------------------- per-stage attribution

#: cptrace span name → attribution stage. Claim priority (the tuple
#: order) resolves overlaps: the kubelet's injected latency is ground
#: truth; admission-queue waits subsume the workqueue/reconcile churn
#: that happens while parked; what remains books to queue/work/delivery.
STAGE_OF_SPAN = {
    "kubelet.actuation": "kubelet",
    "sched.queue_wait": "sched_queue_wait",
    "queue.wait": "queue_wait",
    "reconcile": "reconcile",
    "apiserver.create": "apiserver",
    "informer.deliver": "deliver",
}
STAGE_ORDER = ("kubelet", "sched_queue_wait", "queue_wait", "reconcile",
               "apiserver", "deliver")


def _merge(intervals: list) -> list:
    """Sorted union of (start, end) intervals."""
    out: list = []
    for a, b in sorted(intervals):
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _subtract(intervals: list, claimed: list) -> list:
    """``intervals`` minus already-claimed time (both merged/sorted)."""
    out = []
    for a, b in intervals:
        cur = a
        for ca, cb in claimed:
            if cb <= cur or ca >= b:
                continue
            if ca > cur:
                out.append((cur, ca))
            cur = max(cur, cb)
            if cur >= b:
                break
        if cur < b:
            out.append((cur, b))
    return out


def stage_attribution(records, tracer, plural: str = "notebooks") -> dict:
    """Where each CR's create→Ready wall time went, from its cptrace
    spans: per-stage DISJOINT milliseconds (overlaps resolved by
    STAGE_ORDER claim priority, so stages can never sum past the total)
    plus the attributed fraction — the share of wall time the trace
    explains. The regression gate on the full run wants ≥ 0.95."""
    per_stage: dict[str, list] = {}
    fractions: list[float] = []
    unattributed: list[float] = []
    for rec in records:
        if rec.created is None or rec.ready is None:
            continue
        total = rec.ready - rec.created
        if total <= 0:
            continue
        snap = tracer.snapshot(
            key=obs.object_key(plural, rec.namespace, rec.name)
        )
        if snap is None:
            continue
        by_stage: dict[str, list] = {}
        for s in snap["spans"]:
            stage = STAGE_OF_SPAN.get(s["name"])
            if stage is None or s["end"] is None:
                continue
            a = max(s["start"], rec.created)
            b = min(s["end"], rec.ready)
            if b > a:
                by_stage.setdefault(stage, []).append((a, b))
        claimed: list = []
        for stage in STAGE_ORDER:
            mine = _subtract(_merge(by_stage.get(stage, [])), claimed)
            per_stage.setdefault(stage, []).append(
                sum(b - a for a, b in mine) * 1000.0
            )
            claimed = _merge(claimed + mine)
        accounted = sum(b - a for a, b in claimed)
        fractions.append(accounted / total)
        unattributed.append((total - accounted) * 1000.0)
    if not fractions:
        return {}
    return {
        "stages_ms": {
            stage: percentiles(vals)
            for stage, vals in per_stage.items() if any(vals)
        },
        "unattributed_ms": percentiles(unattributed),
        "attributed_fraction": {
            "min": round(min(fractions), 4),
            "mean": round(sum(fractions) / len(fractions), 4),
            "n": len(fractions),
        },
    }
