"""Load generator: concurrency + arrival pattern for apiserver writes.

Three arrival patterns, the shapes that stress a control plane
differently (NotebookOS, arXiv:2503.20591 — spawn storms at lecture
start vs. steady drip):

- ``burst``: all jobs handed to the worker pool at once; effective
  arrival rate = pool drain rate. The thundering-herd case (a class of
  students clicking "launch" together) — stresses workqueue dedup and
  informer fan-out.
- ``rate``: submissions paced at a constant ``rate``/second (a Poisson
  mean would wander between runs; constant spacing keeps runs
  comparable). The steady-state case — stresses the per-CR critical
  path with the system otherwise quiet.
- ``schedule``: each job submitted at an explicit per-job offset from
  t=0 — the trace/arrival-process case (cpbench/arrivals.py MMPP
  storms, tides, replayed traces). The offsets list is the schedule;
  determinism is the generator's job, pacing is this one's.

Jobs run on a bounded thread pool either way: ``concurrency`` models
how many clients write the apiserver at once, not how many CRs exist.
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor


class LoadGenerator:
    def __init__(self, concurrency: int = 8, pattern: str = "burst",
                 rate: float = 50.0, offsets=None):
        if pattern not in ("burst", "rate", "schedule"):
            raise ValueError(f"unknown arrival pattern {pattern!r}")
        if concurrency < 1:
            raise ValueError("concurrency must be >= 1")
        if pattern == "rate" and rate <= 0:
            raise ValueError("rate must be > 0")
        if pattern == "schedule":
            if offsets is None:
                raise ValueError("pattern 'schedule' needs offsets")
            offsets = list(offsets)
            if any(b < a for a, b in zip(offsets, offsets[1:])):
                raise ValueError("schedule offsets must be sorted")
        self.concurrency = concurrency
        self.pattern = pattern
        self.rate = rate
        self.offsets = offsets

    def run(self, jobs) -> list:
        """Execute callables under the arrival pattern; returns each
        job's result, with raised exceptions returned in place (one bad
        CR must not sink the measurement of the other N-1)."""
        results = [None] * len(jobs)
        if self.pattern == "schedule" and len(self.offsets) < len(jobs):
            raise ValueError(
                f"schedule has {len(self.offsets)} offsets for "
                f"{len(jobs)} jobs")

        def call(i, job):
            try:
                results[i] = job()
            except Exception as e:
                results[i] = e

        with ThreadPoolExecutor(max_workers=self.concurrency) as pool:
            start = time.monotonic()
            futures = []
            for i, job in enumerate(jobs):
                if self.pattern == "rate":
                    due = start + i / self.rate
                    delay = due - time.monotonic()
                    if delay > 0:
                        time.sleep(delay)
                elif self.pattern == "schedule":
                    due = start + self.offsets[i]
                    delay = due - time.monotonic()
                    if delay > 0:
                        time.sleep(delay)
                futures.append(pool.submit(call, i, job))
            for f in futures:
                f.result()
        return results
