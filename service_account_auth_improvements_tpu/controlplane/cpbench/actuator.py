"""Fake-kubelet actuator: plays the cluster around the control plane.

The system under test is the reconcile stack; everything a real cluster
would do around it is played here, against the same FakeKube apiserver:

- **StatefulSet controller**: creates ``<sts>-<i>`` pods from the STS
  template (scheduling gates and all), deletes pods past
  ``spec.replicas`` on scale-down — the role tests play by hand in
  tests/test_gang.py ``_mk_pod``.
- **Scheduler**: binds ungated pods to nodes. Every STS gets its own
  node pool (one node per ordinal, labeled ``cloud.google.com/
  gke-nodepool``) so a multi-host gang lands pool-consistent — the
  placement the notebook controller's one-pool-one-slice check verifies
  against the bound nodes. Gated pods are NEVER bound: the gang gates
  must be lifted by the controller first, exactly as kube-scheduler
  honors schedulingGates.
- **Kubelet**: flips bound pods Ready after a latency sampled from a
  tunable distribution, then maintains ``sts.status.readyReplicas``.
  Every sample is recorded per pod, so a scenario can subtract actuation
  from the end-to-end number and report pure controller overhead.
"""

from __future__ import annotations

import copy
import heapq
import logging
import math
import random
import threading
import time

from service_account_auth_improvements_tpu.controlplane import obs
from service_account_auth_improvements_tpu.controlplane.engine import (
    Informer,
)
from service_account_auth_improvements_tpu.controlplane.kube import errors
from service_account_auth_improvements_tpu.controlplane.tpu import (
    SEL_NODEPOOL,
)

log = logging.getLogger(__name__)


class LatencyDist:
    """Tunable actuation-latency distribution.

    Spec strings (milliseconds):

    - ``const:20``          — every pod takes 20 ms to go Ready
    - ``uniform:5,15``      — uniform in [5, 15] ms
    - ``lognormal:20,0.5``  — median 20 ms, sigma 0.5 (long tail — the
      realistic image-pull/container-start shape)
    """

    def __init__(self, spec: str = "uniform:5,15"):
        kind, _, args = spec.partition(":")
        self.kind = kind.strip().lower()
        try:
            vals = [float(a) for a in args.split(",")] if args else []
        except ValueError:
            raise ValueError(f"malformed latency spec {spec!r}")
        if self.kind == "const" and len(vals) == 1:
            self.a, self.b = vals[0], vals[0]
        elif self.kind == "uniform" and len(vals) == 2 and vals[0] <= vals[1]:
            self.a, self.b = vals
        elif self.kind == "lognormal" and len(vals) == 2 and vals[0] > 0:
            self.a, self.b = vals
        else:
            raise ValueError(f"malformed latency spec {spec!r}")
        if self.a < 0:
            raise ValueError(f"latency must be >= 0 in {spec!r}")
        self.spec = spec

    def sample(self, rng: random.Random) -> float:
        """One draw, in seconds."""
        if self.kind == "const":
            ms = self.a
        elif self.kind == "uniform":
            ms = rng.uniform(self.a, self.b)
        else:  # lognormal: a = median ms, b = sigma
            ms = rng.lognormvariate(math.log(self.a), self.b)
        return ms / 1000.0


class _Flipper(threading.Thread):
    """Delayed-call scheduler (the kubelet's 'container is starting')."""

    def __init__(self):
        super().__init__(name="cpbench-flipper", daemon=True)
        self._cond = threading.Condition()
        self._heap: list = []
        self._seq = 0
        # NOT named _stop: threading.Thread has an internal _stop()
        # METHOD, and shadowing it with a bool makes is_alive()/join()
        # on a finished thread raise "'bool' object is not callable"
        # deep in threading internals (found by FakeKube's stats-cell
        # reaper, which probes thread liveness)
        self._stopping = False

    def call_later(self, delay: float, fn) -> None:
        with self._cond:
            self._seq += 1
            heapq.heappush(
                self._heap, (time.monotonic() + delay, self._seq, fn)
            )
            self._cond.notify()

    def stop(self) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify()

    def run(self) -> None:
        while True:
            with self._cond:
                while not self._stopping and (
                        not self._heap
                        or self._heap[0][0] > time.monotonic()):
                    wait = 0.2
                    if self._heap:
                        wait = min(
                            wait, max(self._heap[0][0] - time.monotonic(),
                                      0.001),
                        )
                    self._cond.wait(wait)
                if self._stopping:
                    return
                _, _, fn = heapq.heappop(self._heap)
            try:
                fn()
            except Exception:  # a lost flip must not kill the kubelet
                log.exception("cpbench flip failed")


class FakeKubelet:
    """STS-controller + scheduler + kubelet against a FakeKube."""

    def __init__(self, kube, latency: LatencyDist | str = "uniform:5,15",
                 seed: int = 0, tracer=None, relist_period: float = 0.0):
        # per-client attribution (cpprof): everything the fake cluster
        # does — pod creates, binds, Ready flips, STS status — books
        # under "kubelet" in the apiserver's per-client split
        if hasattr(kube, "client_for") \
                and getattr(kube, "client_id", None) is None:
            kube = kube.client_for("kubelet")
        self.kube = kube
        #: with a tracer, each pod's schedule→Ready interval lands on the
        #: owning notebook's trace as a ``kubelet.actuation`` span — the
        #: ground truth cpbench's stage attribution books as kubelet time
        self._tracer = tracer
        self.latency = (latency if isinstance(latency, LatencyDist)
                        else LatencyDist(latency))
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()
        self._lock = threading.Lock()
        self._scheduled: set[str] = set()      # pod uids with a flip queued
        self._created_at: dict[tuple, float] = {}  # (ns, pod) -> instant
        self.samples: dict[tuple[str, str], float] = {}  # (ns, pod) -> s
        self.gate_violations = 0   # pods seen bound/Ready while still gated
        self.pods_created = 0
        self.pods_ready = 0
        #: chaos knob — a stalled kubelet keeps scheduling and binding
        #: but stops flipping pods Ready (the node is up, the kubelet's
        #: sync loop is wedged); queued flips re-arm until unstalled
        self._stalled = False
        #: pods whose bind failed (pinned pool momentarily has no nodes
        #: — node death) with a retry armed; mirrors kube-scheduler's
        #: backoff-and-retry for unschedulable pods
        self._bind_retry: set[str] = set()
        self._flipper = _Flipper()
        # tracer'd informers: the STS/pod watch hops inside the fake
        # cluster surface as informer.deliver spans on the owning
        # notebook's trace (via the notebook-name label)
        self._sts_inf = Informer(kube, "statefulsets", group="apps",
                                 tracer=tracer,
                                 relist_period=relist_period)
        self._sts_inf.add_handler(self._on_sts)
        self._pod_inf = Informer(kube, "pods", tracer=tracer,
                                 relist_period=relist_period)
        self._pod_inf.add_handler(self._on_pod)
        # _sync_sts_status runs per pod Ready-flip/delete: an O(pods)
        # cache scan there is O(pods²) over a bench — index instead
        self._pod_inf.add_index(
            "sts",
            lambda p: [f"{p['metadata'].get('namespace')}/"
                       f"{(p['metadata'].get('labels') or {})['statefulset']}"]
            if (p["metadata"].get("labels") or {}).get("statefulset")
            else [],
        )

    def start(self) -> None:
        self._flipper.start()
        self._sts_inf.start()
        self._pod_inf.start()
        self._sts_inf.wait_for_sync(10)
        self._pod_inf.wait_for_sync(10)

    def stop(self) -> None:
        self._sts_inf.stop()
        self._pod_inf.stop()
        self._flipper.stop()

    def stall(self) -> None:
        """Chaos: stop flipping pods Ready (wedged kubelet sync loop).
        Scheduling/binding continue — the control plane sees a cluster
        that accepts work but never delivers it."""
        self._stalled = True

    def unstall(self) -> None:
        self._stalled = False

    def _retry_later(self, delay: float, fn) -> None:
        """A real cluster component retries through outages: apiserver
        errors (chaos blackouts) re-arm the action instead of dropping
        it — a lost flip/bind/create would wedge a workload forever in a
        way no real kubelet/scheduler/STS-controller would."""
        self._flipper.call_later(delay, fn)

    def actuation_for(self, namespace: str, name: str) -> float:
        """Max actuation sample (seconds) over ``<name>-*`` pods — the
        component of this CR's ready latency the kubelet injected (pods
        start in parallel, so the max is the gang's critical path)."""
        prefix = f"{name}-"
        with self._lock:
            vals = [v for (ns, pod), v in self.samples.items()
                    if ns == namespace and pod.startswith(prefix)]
        return max(vals, default=0.0)

    # ------------------------------------------------- StatefulSet control

    def _on_sts(self, ev_type: str, sts: dict) -> None:
        if ev_type == "DELETED":
            return  # ownerReference cascade deletes the pods
        meta = sts["metadata"]
        ns, name = meta.get("namespace"), meta["name"]
        try:
            self._sync_sts(sts)
        except errors.NotFound:
            pass  # STS vanished mid-sync (cascade); nothing to converge
        except errors.ApiError:
            # apiserver hiccup/blackout mid-sync: re-arm from the cache —
            # the real STS controller's workqueue would retry exactly so
            def retry(ns=ns, name=name):
                cur = self._sts_inf.get(ns, name)
                if cur is not None:
                    self._on_sts("SYNC", cur)

            self._retry_later(0.15, retry)

    def _sync_sts(self, sts: dict) -> None:
        meta = sts["metadata"]
        ns, name = meta.get("namespace"), meta["name"]
        replicas = int((sts.get("spec") or {}).get("replicas") or 0)
        template = (sts.get("spec") or {}).get("template") or {}
        want_sel = ((template.get("spec") or {}).get("nodeSelector")
                    or {})
        for i in range(replicas):
            pod_name = f"{name}-{i}"
            existing = self._pod_inf.get(ns, pod_name)
            if existing is not None:
                have_sel = ((existing.get("spec") or {}).get(
                    "nodeSelector") or {})
                if have_sel == want_sel:
                    continue
                # rolling update on placement change: a real STS
                # controller replaces pods whose template changed —
                # without this, a notebook re-placed onto a different
                # pool (preempt → resume → new placement, reconciles
                # coalesced so the scale-to-zero never ran) keeps its
                # old-pool pods and the gang wedges on
                # SlicePlacementConflict forever
                try:
                    # the kubelet sync loop re-runs every period: a
                    # raced delete is re-decided next sync, NotFound
                    # is absorbed
                    # cplint: disable=check-then-act — sync-loop re-decides
                    self.kube.delete("pods", pod_name, namespace=ns)
                except errors.NotFound:
                    pass
            try:
                self.kube.create("pods", self._pod_from_template(
                    sts, template, pod_name, i))
                with self._lock:
                    self.pods_created += 1
                    # actuation truly starts here: the kubelet.actuation
                    # span runs create→Ready so the STS→pod→bind watch
                    # hops count as cluster time, not controller gaps
                    self._created_at[(ns or "", pod_name)] = \
                        time.monotonic()
            except errors.AlreadyExists:
                pass  # informer cache lagging a pod we already made
        # scale-down (stop annotation → replicas=0): delete extra ordinals
        for pod in self._pod_inf.list():
            m = pod["metadata"]
            if m.get("namespace") != ns:
                continue
            if (m.get("labels") or {}).get("statefulset") != name:
                continue
            ordinal = m["name"].rsplit("-", 1)[-1]
            if ordinal.isdigit() and int(ordinal) >= replicas:
                try:
                    self.kube.delete("pods", m["name"], namespace=ns)
                except errors.NotFound:
                    pass
        self._sync_sts_status(ns, name, replicas)

    @staticmethod
    def _pod_from_template(sts: dict, template: dict, pod_name: str,
                           ordinal: int) -> dict:
        tmeta = template.get("metadata") or {}
        return {
            "metadata": {
                "name": pod_name,
                "namespace": sts["metadata"].get("namespace"),
                "labels": {
                    **(tmeta.get("labels") or {}),
                    "apps.kubernetes.io/pod-index": str(ordinal),
                },
                "annotations": dict(tmeta.get("annotations") or {}),
                "ownerReferences": [{
                    "apiVersion": "apps/v1", "kind": "StatefulSet",
                    "name": sts["metadata"]["name"],
                    "uid": sts["metadata"]["uid"], "controller": True,
                }],
            },
            "spec": copy.deepcopy(template.get("spec") or {}),
            "status": {"phase": "Pending"},
        }

    def _sync_sts_status(self, ns: str, name: str,
                         replicas: int | None = None) -> None:
        """Maintain status.readyReplicas — what the notebook controller's
        update_status reads. Served from the actuator's own informer
        caches (the real StatefulSet controller is informer-driven too):
        callers invoke this from watch dispatch, where the cache already
        reflects the event being handled, so a live GET+LIST per pod flip
        would only re-read what the watch just delivered."""
        sts = self._sts_inf.get(ns, name)
        if sts is None:
            try:
                sts = self.kube.get("statefulsets", name, namespace=ns,
                                    group="apps")
            except errors.NotFound:
                return
            except errors.ApiError:
                self._retry_later(
                    0.15, lambda: self._sync_sts_status(ns, name)
                )
                return
        if replicas is None:
            replicas = int((sts.get("spec") or {}).get("replicas") or 0)
        ready = 0
        for pod in self._pod_inf.by_index("sts", f"{ns}/{name}"):
            for cond in (pod.get("status") or {}).get("conditions") or []:
                if cond.get("type") == "Ready" and \
                        cond.get("status") == "True":
                    ready += 1
        cur = sts.get("status") or {}
        if (cur.get("readyReplicas"), cur.get("replicas")) == (ready,
                                                               replicas):
            return
        try:
            self.kube.patch("statefulsets", name, {"status": {
                "replicas": replicas, "readyReplicas": ready,
            }}, namespace=ns, group="apps")
        except errors.NotFound:
            pass
        except errors.ApiError:
            # readyReplicas is level state: re-derive once the apiserver
            # is back rather than dropping the write
            self._retry_later(
                0.15, lambda: self._sync_sts_status(ns, name)
            )

    # --------------------------------------------------- scheduler/kubelet

    def _on_pod(self, ev_type: str, pod: dict) -> None:
        meta = pod["metadata"]
        sts_label = (meta.get("labels") or {}).get("statefulset")
        if ev_type == "DELETED":
            # a vanished pod moves readyReplicas: re-derive the STS
            # status now that the cache (updated before dispatch) has
            # dropped it
            if sts_label:
                self._sync_sts_status(meta.get("namespace"), sts_label)
                # a pod deleted OUT FROM UNDER a live STS (node death,
                # chaos force-delete) must be replaced — the real STS
                # controller watches pods and recreates missing ordinals
                self._maybe_recreate(meta.get("namespace"), sts_label,
                                     meta["name"])
            return
        if any(c.get("type") == "Ready" and c.get("status") == "True"
               for c in (pod.get("status") or {}).get("conditions") or []):
            # the Ready flip we (or a replay) wrote is now in the cache:
            # fold it into the STS status. Event-driven, so the sync
            # always sees a cache at least as new as the flip itself.
            if sts_label:
                self._sync_sts_status(meta.get("namespace"), sts_label)
            return
        spec = pod.get("spec") or {}
        if spec.get("schedulingGates"):
            # kube-scheduler semantics: a gated pod is invisible to
            # binding. The gang controller lifts the gate; the MODIFIED
            # event brings the pod back here.
            return
        ns, name, uid = meta.get("namespace"), meta["name"], meta["uid"]
        if not spec.get("nodeName"):
            try:
                if not self._bind(pod):
                    # unbindable (pinned pool has no nodes — node death):
                    # the pod stays Pending and must never flip Ready
                    # unbound, but the real scheduler RETRIES pending
                    # pods — when the pool's nodes come back (repair),
                    # no pod event fires, so poll from the cache
                    self._arm_bind_retry(ns, name, uid)
                    return
            except errors.NotFound:
                return  # deleted mid-flight (churn)
            except errors.ApiError:
                self._arm_bind_retry(ns, name, uid)
                return
        with self._lock:
            if uid in self._scheduled:
                return
            self._scheduled.add(uid)
        with self._rng_lock:
            delay = self.latency.sample(self._rng)
        with self._lock:
            self.samples[(ns or "", name)] = delay
            scheduled_at = self._created_at.pop(
                (ns or "", name), time.monotonic()
            )
        self._flipper.call_later(
            delay,
            lambda: self._flip_ready(ns, name, uid, scheduled_at),
        )

    def _bind(self, pod: dict) -> bool:
        """Assign a node; False when the pod is unbindable (it must stay
        Pending and NOT be flipped Ready). A pod whose nodeSelector names
        a pool (user pin or a tpusched placement) binds into that pool's
        EXISTING nodes, one host per ordinal — the placement
        kube-scheduler would make. Otherwise every STS gets its own
        synthetic pool (one node per ordinal) so a multi-host gang lands
        pool-consistent by construction."""
        meta = pod["metadata"]
        ns, name = meta.get("namespace"), meta["name"]
        ordinal = name.rsplit("-", 1)[-1]
        want_pool = ((pod.get("spec") or {}).get("nodeSelector") or {}).get(
            SEL_NODEPOOL
        )
        if want_pool:
            nodes = sorted(
                n["metadata"]["name"]
                for n in self.kube.list(
                    "nodes",
                    label_selector=f"{SEL_NODEPOOL}={want_pool}")["items"]
            )
            if not nodes:
                # pinned pool has no nodes: stay Pending, like the real
                # scheduler would leave an unsatisfiable nodeSelector
                return False
            idx = int(ordinal) if ordinal.isdigit() else 0
            self.kube.patch(
                "pods", name,
                {"spec": {"nodeName": nodes[idx % len(nodes)]}},
                namespace=ns,
            )
            return True
        sts = (meta.get("labels") or {}).get("statefulset") or "solo"
        pool = f"{ns}-{sts}"
        node_name = f"node-{pool}-{ordinal}"
        try:
            self.kube.create("nodes", {
                "metadata": {"name": node_name,
                             "labels": {SEL_NODEPOOL: pool}},
            })
        except errors.AlreadyExists:
            pass
        self.kube.patch("pods", name, {"spec": {"nodeName": node_name}},
                        namespace=ns)
        return True

    def _arm_bind_retry(self, ns: str, name: str, uid: str) -> None:
        """Re-try binding a Pending pod from the cache until it binds or
        disappears (one armed retry per pod uid — retries must not
        multiply when several bind failures race)."""
        with self._lock:
            if uid in self._bind_retry:
                return
            self._bind_retry.add(uid)

        def retry():
            with self._lock:
                self._bind_retry.discard(uid)
            pod = self._pod_inf.get(ns, name)
            if pod is not None and pod["metadata"].get("uid") == uid:
                self._on_pod("SYNC", pod)

        self._retry_later(0.25, retry)

    def _maybe_recreate(self, ns: str, sts_name: str,
                        pod_name: str) -> None:
        """Replace a pod deleted under a live STS (node death): if the
        cached STS still wants this ordinal, confirm the STS is live
        (cheap GET — the cache may lag a cascade delete) and re-run
        creation. Scale-downs skip out on the cache check alone."""
        sts = self._sts_inf.get(ns, sts_name)
        if sts is None:
            return
        replicas = int((sts.get("spec") or {}).get("replicas") or 0)
        ordinal = pod_name.rsplit("-", 1)[-1]
        if not ordinal.isdigit() or int(ordinal) >= replicas:
            return  # scale-down delete: the ordinal is no longer wanted
        try:
            live = self.kube.get("statefulsets", sts_name, namespace=ns,
                                 group="apps")
        except errors.NotFound:
            return  # cascade delete: cache lagging the STS's death
        except errors.ApiError:
            self._retry_later(
                0.15,
                lambda: self._maybe_recreate(ns, sts_name, pod_name),
            )
            return
        if live["metadata"].get("deletionTimestamp"):
            return
        self._on_sts("SYNC", live)

    def _flip_ready(self, ns: str, name: str, uid: str,
                    scheduled_at: float | None = None) -> None:
        if self._stalled:
            # wedged kubelet: the flip stays due, it just doesn't happen
            # until the stall lifts
            self._retry_later(
                0.05, lambda: self._flip_ready(ns, name, uid, scheduled_at)
            )
            return
        try:
            pod = self.kube.get("pods", name, namespace=ns)
        except errors.NotFound:
            return  # deleted before it came up (churn / culling)
        except errors.ApiError:
            self._retry_later(
                0.1, lambda: self._flip_ready(ns, name, uid, scheduled_at)
            )
            return
        if pod["metadata"].get("uid") != uid:
            return  # recreated under the same name; the new pod rebinds
        if (pod.get("spec") or {}).get("schedulingGates"):
            with self._lock:
                self.gate_violations += 1
            return
        container = "notebook"
        for c in (pod.get("spec") or {}).get("containers") or []:
            container = c.get("name") or container
            break
        now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        try:
            self.kube.patch("pods", name, {"status": {
                "phase": "Running",
                "conditions": [{"type": "Ready", "status": "True",
                                "lastTransitionTime": now}],
                "containerStatuses": [{
                    "name": container, "ready": True,
                    "state": {"running": {"startedAt": now}},
                }],
            }}, namespace=ns)
        except errors.NotFound:
            return
        except errors.ApiError:
            # outage between the GET and the status write: re-arm —
            # a real kubelet keeps syncing status until it lands
            self._retry_later(
                0.1, lambda: self._flip_ready(ns, name, uid, scheduled_at)
            )
            return
        with self._lock:
            self.pods_ready += 1
        # no direct STS sync here: the Ready patch's MODIFIED event lands
        # in _on_pod, which syncs against a cache that includes it
        if self._tracer is not None and scheduled_at is not None:
            # span runs pod-create → Ready-visible-on-the-STS: everything
            # the cluster (STS controller + scheduler + kubelet) did, so
            # attribution books it as actuation rather than a gap
            nb = (pod["metadata"].get("labels") or {}).get("notebook-name")
            if nb:
                self._tracer.record(
                    "kubelet.actuation",
                    obs.object_key("notebooks", ns, nb),
                    scheduled_at, time.monotonic(), attrs={"pod": name},
                )
