"""sched_policy: the learned-placement judge — best_fit vs learned, A/B.

The closed loop, exercised end to end with the control plane training
itself as the workload (docs/scheduler.md "Learned placement"):

1. **arm A (best_fit)**: the plain scheduler drains the workload; its
   decision journal — the ``sched-journal/v1`` rows every placement
   writes — is the training set (benches ARE the dataset generator);
2. **train**: a policy checkpoint is fitted from arm A's journal with
   the repo's own train-stack shape (seeded, CPU, seconds at smoke
   scale — the same path ``cpbench --journal-out`` + the policy train
   CLI run offline);
3. **arm B (learned)**: the identical workload re-runs with
   ``placement_policy="learned"`` on that checkpoint; every learned
   decision journals its score vector, every abstention its reason.

Two workloads:

===================  ==================================================
``sched_policy``      the sched_contention shape: N v5e 4x4 gangs vs 4
                      one-slice pools, delete-on-Ready drain (no
                      preemption — the A/B isolates placement, not
                      victim churn).
``sched_policy_frag`` fragmentation-heavy: single-host 2x2 notebooks
                      churning through HETEROGENEOUS pools (4/8/16/8
                      chips) — the shape where pool-wide chip
                      accounting hides fragmentation from best_fit.
===================  ==================================================

Judged by ``bench_gate --policy``: 0 chip-oversubscribed pools in BOTH
arms, learned SLO attainment no worse than best_fit's, zero illegal
choices (a learned pick outside the shared feasibility mask — masked
out by construction, counted anyway), ttp p50/p95 and fragmentation
reported side by side.

JAX is imported lazily inside the training step only: this module
registers its scenarios on every cpbench import (the stdlib-only CI
bench lane included) and the scenarios themselves fail loud — not at
import — when the JAX half is absent.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
import time

from service_account_auth_improvements_tpu.controlplane.controllers.notebook import (  # noqa: E501
    GROUP,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.loadgen import (  # noqa: E501
    LoadGenerator,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.scenarios import (  # noqa: E501
    SCENARIOS,
    BenchConfig,
    ScenarioResult,
    _NotebookWorld,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.tracker import (  # noqa: E501
    percentiles,
)
from service_account_auth_improvements_tpu.controlplane.kube import errors
from service_account_auth_improvements_tpu.controlplane.obs import (
    slo as slo_mod,
)
from service_account_auth_improvements_tpu.controlplane.scheduler.policy.features import (  # noqa: E501
    placement_rows,
)
from service_account_auth_improvements_tpu.controlplane import tpu as tpu_mod

AB_SCHEMA = "sched-policy-ab/v1"


def _gang_nodes() -> list[dict]:
    """The sched_contention inventory: 4 one-slice v5e 4x4 pools."""
    nodes = []
    for p in range(4):
        for h in range(4):
            nodes.append({
                "metadata": {
                    "name": f"node-pp{p}-{h}",
                    "labels": {
                        tpu_mod.SEL_NODEPOOL: f"policy-pool-{p}",
                        tpu_mod.SEL_ACCELERATOR: "tpu-v5-lite-podslice",
                        tpu_mod.SEL_TOPOLOGY: "4x4",
                    },
                },
                "status": {"capacity": {tpu_mod.RESOURCE_TPU: "4"}},
            })
    return nodes


def _frag_nodes() -> list[dict]:
    """Heterogeneous single-host pools: one 2x2-class node each at 4,
    8, 16, and 8 chips — mixed capacities are what make leftover-chip
    fragmentation visible (a 4-chip demand placed wrong strands free
    chips nothing can use once the queue shape shifts)."""
    nodes = []
    for p, chips in enumerate((4, 8, 16, 8)):
        nodes.append({
            "metadata": {
                "name": f"node-fp{p}",
                "labels": {
                    tpu_mod.SEL_NODEPOOL: f"frag-pool-{p}",
                    tpu_mod.SEL_ACCELERATOR: "tpu-v5-lite-podslice",
                    tpu_mod.SEL_TOPOLOGY: "2x2",
                },
            },
            "status": {"capacity": {tpu_mod.RESOURCE_TPU: str(chips)}},
        })
    return nodes


def _fragmentation(journal_entries: list, demand_chips: int) -> dict:
    """Fragmentation, from the journal's own decision-time inventory
    snapshots (identical definition across arms by construction):

    - ``leftover_chips_mean``: free chips left in the CHOSEN pool after
      placement — what best_fit greedily minimizes;
    - ``stranded_free_chips_mean``: free chips sitting in partially
      occupied pools at decision time — capacity that is neither whole
      (big demands can't use it) nor charged (nobody owns it)."""
    leftovers, stranded = [], []
    for row in placement_rows(journal_entries):
        attrs = row.get("attrs") or {}
        free = attrs.get("free_chips") or {}
        total = attrs.get("total_chips") or {}
        pool = attrs.get("pool")
        if pool not in free:
            continue
        leftovers.append(free[pool] - attrs.get("demand_chips",
                                                demand_chips))
        stranded.append(sum(
            f for p, f in free.items()
            if 0 < f < (total.get(p) or 0)
        ))
    def _mean(xs):
        return round(sum(xs) / len(xs), 3) if xs else None
    return {
        "decisions": len(leftovers),
        "leftover_chips_mean": _mean(leftovers),
        "stranded_free_chips_mean": _mean(stranded),
    }


def _policy_counts(journal_entries: list) -> dict:
    """Who decided, per placement row: policy totals, fallback reasons,
    and the illegal-choice count (must be 0 — the mask makes it
    unrepresentable; this counter is the evidence)."""
    decisions: dict = {}
    fallbacks: dict = {}
    for row in placement_rows(journal_entries):
        attrs = row.get("attrs") or {}
        policy = attrs.get("policy") or "unknown"
        decisions[policy] = decisions.get(policy, 0) + 1
        if attrs.get("fallback"):
            reason = str(attrs["fallback"]).split(" ")[0]
            fallbacks[reason] = fallbacks.get(reason, 0) + 1
    return {
        "decisions": decisions,
        "fallbacks": fallbacks,
        "illegal_choices": fallbacks.get("illegal-choice", 0),
    }


def _drain_arm(cfg: BenchConfig, scenario: str, policy: str,
               checkpoint: str | None, nodes: list[dict],
               tpu_spec: dict, want_ready: int,
               demand_chips: int) -> dict:
    """One A/B arm: N notebooks drain through the scheduler
    (delete-on-Ready frees capacity for the queue), chip-accounted
    double-booking audited every poll tick. Returns the arm record +
    the world's journal entries (under ``_journal``, stripped by the
    caller)."""
    world = _NotebookWorld(cfg, scenario, scheduler=True,
                           placement_policy=policy,
                           policy_checkpoint=checkpoint,
                           preemption=False)
    ns = "bench"
    pool_chips: dict[str, int] = {}
    for node in nodes:
        world.kube.create("nodes", node)
        pool = node["metadata"]["labels"][tpu_mod.SEL_NODEPOOL]
        pool_chips[pool] = pool_chips.get(pool, 0) + int(
            node["status"]["capacity"][tpu_mod.RESOURCE_TPU])
    placement_ms: dict[str, float] = {}
    placement_lock = threading.Lock()

    def on_placement(ev_type: str, nb: dict) -> None:
        if ev_type in ("DELETED", "SYNC"):
            return
        name = nb["metadata"]["name"]
        if (nb["metadata"].get("annotations") or {}).get(
                tpu_mod.ANNOTATION_NODEPOOL) is None:
            return
        rec = world.tracker.record(ns, name)
        if rec is None or rec.created is None:
            return
        with placement_lock:
            placement_ms.setdefault(
                name, (time.monotonic() - rec.created) * 1000.0)

    world._ready_inf.add_handler(on_placement)
    world.start()
    names = [f"pol-{i:03d}" for i in range(cfg.n)]
    LoadGenerator(cfg.concurrency, cfg.pattern, cfg.rate).run(
        world.create_jobs(names, ns, tpu_spec, want_ready=want_ready)
    )
    deleted: set[str] = set()
    overbooked_ticks = 0
    deadline = time.monotonic() + cfg.timeout
    while len(deleted) < len(names) and time.monotonic() < deadline:
        # one cached LIST per tick: an atomic snapshot (the
        # sched_contention rationale — per-name GETs read a torn cut)
        snapshot = {
            o["metadata"]["name"]: o
            for o in world.cached.list("notebooks", namespace=ns,
                                       group=GROUP)["items"]
        }
        load: dict[str, int] = {}
        to_delete: list[str] = []
        for name in names:
            if name in deleted:
                continue
            nb = snapshot.get(name)
            if nb is None:
                continue
            pool = (nb["metadata"].get("annotations") or {}).get(
                tpu_mod.ANNOTATION_NODEPOOL)
            if pool:
                load[pool] = load.get(pool, 0) + demand_chips
            rec = world.tracker.record(ns, name)
            if rec is not None and rec.ready is not None:
                to_delete.append(name)
        # chip-accounted double-booking: annotated demand beyond a
        # pool's capacity (covers multi-notebook single-host pools,
        # where >1 member is legal, AND one-slice gang pools, where
        # a second 16-chip gang blows the 16-chip budget)
        if any(load.get(p, 0) > chips
               for p, chips in pool_chips.items()):
            overbooked_ticks += 1
        for name in to_delete:
            try:
                world.kube.delete("notebooks", name, namespace=ns,
                                  group=GROUP)
            except errors.NotFound:
                pass
            deleted.add(name)
        time.sleep(0.02)
    drained = len(deleted) == len(names)
    world.stop()
    summary = world.tracker.summary()
    journal_entries = world.journal.entries()
    journal_jsonl = world.journal.to_jsonl()
    ttp = list(placement_ms.values())
    return {
        "policy": policy,
        "n": cfg.n,
        "placed": len(placement_ms),
        "drained": drained,
        "reconciles": summary["reconciles"],
        "ttp_ms": percentiles(ttp),
        "double_bookings": overbooked_ticks,
        "slo": slo_mod.report({"time_to_placement": ttp}),
        "fragmentation": _fragmentation(journal_entries, demand_chips),
        **_policy_counts(journal_entries),
        "_journal": journal_entries,
        "_jsonl": journal_jsonl,
        "_summary": summary,
    }


def _train_policy(journal_entries: list, seed: int,
                  workdir: str) -> dict:
    """Arm A's journal → checkpoint, via the SAME file format the
    offline path uses (JSONL on disk, ``train_from_journal``) so the
    bench exercises the real harvest surface, not a shortcut."""
    from service_account_auth_improvements_tpu.controlplane.scheduler.policy.train import (  # noqa: E501
        train_from_journal,
    )

    journal_path = os.path.join(workdir, "harvest.jsonl")
    with open(journal_path, "w") as f:
        for entry in journal_entries:
            f.write(json.dumps(entry, sort_keys=True, default=str))
            f.write("\n")
    return train_from_journal(
        journal_path, workdir, seed=seed, steps=200, batch_size=32,
    )


def _ab_scenario(cfg: BenchConfig, scenario: str, nodes: list[dict],
                 tpu_spec: dict, want_ready: int,
                 demand_chips: int) -> ScenarioResult:
    started = time.monotonic()
    workdir = tempfile.mkdtemp(prefix="schedpolicy-")
    try:
        return _ab_scenario_in(cfg, scenario, nodes, tpu_spec,
                               want_ready, demand_chips, started,
                               workdir)
    finally:
        # the harvest file + checkpoint are scenario-scoped scratch;
        # repeated bench runs must not accumulate tempdirs
        shutil.rmtree(workdir, ignore_errors=True)


def _ab_scenario_in(cfg: BenchConfig, scenario: str, nodes: list[dict],
                    tpu_spec: dict, want_ready: int,
                    demand_chips: int, started: float,
                    workdir: str) -> ScenarioResult:
    arm_a = _drain_arm(cfg, scenario, "best_fit", None, nodes,
                       tpu_spec, want_ready, demand_chips)
    journal_a = arm_a.pop("_journal")
    # the harvest arm's journal is the scenario's --journal-out
    # artifact: exactly what the training step below consumed
    journal_jsonl = arm_a.pop("_jsonl")
    summary = arm_a.pop("_summary")
    try:
        training = _train_policy(journal_a, cfg.seed, workdir)
        train_error = None
    except (ImportError, ValueError) as e:
        training, train_error = None, repr(e)
    if training is not None:
        arm_b = _drain_arm(cfg, scenario, "learned",
                           training["checkpoint"], nodes, tpu_spec,
                           want_ready, demand_chips)
        arm_b.pop("_journal")
        arm_b.pop("_jsonl")
        summary = arm_b.pop("_summary")
    else:
        arm_b = None
    learned = (arm_b or {}).get("decisions", {}).get("learned", 0)
    extra = {
        "schema": AB_SCHEMA,
        "pools": {n_["metadata"]["labels"][tpu_mod.SEL_NODEPOOL]: int(
            n_["status"]["capacity"][tpu_mod.RESOURCE_TPU])
            for n_ in nodes},
        "arms": {"best_fit": arm_a,
                 **({"learned": arm_b} if arm_b else {})},
        "policy_training": training,
        "train_error": train_error,
        "learned_decisions": learned,
        "journal": {},
    }
    ok = (
        arm_a["drained"] and arm_a["double_bookings"] == 0
        and arm_b is not None
        and arm_b["drained"] and arm_b["double_bookings"] == 0
        and arm_b["illegal_choices"] == 0
        # an arm where the policy never actually decided is not an A/B
        and learned > 0
    )
    summary = dict(summary)
    summary["extra"] = extra
    # the judged attainment record: the LEARNED arm's (the --policy leg
    # additionally compares it against best_fit's, carried in the arms)
    summary["slo"] = (arm_b or arm_a)["slo"]
    return ScenarioResult(
        name=scenario, elapsed_s=time.monotonic() - started,
        records=[], summary=summary, ok=ok,
        journal_jsonl=journal_jsonl,
    )


def scenario_sched_policy(cfg: BenchConfig) -> ScenarioResult:
    return _ab_scenario(
        cfg, "sched_policy", _gang_nodes(),
        {"generation": "v5e", "topology": "4x4"},
        want_ready=4, demand_chips=16,
    )


def scenario_sched_policy_frag(cfg: BenchConfig) -> ScenarioResult:
    return _ab_scenario(
        cfg, "sched_policy_frag", _frag_nodes(),
        {"generation": "v5e", "topology": "2x2"},
        want_ready=1, demand_chips=4,
    )


POLICY_SCENARIOS = {
    "sched_policy": scenario_sched_policy,
    "sched_policy_frag": scenario_sched_policy_frag,
}
SCENARIOS.update(POLICY_SCENARIOS)
