"""cpbench CLI: run scenarios, emit CONTROLPLANE_BENCH.json.

``python -m service_account_auth_improvements_tpu.controlplane.cpbench
--smoke`` is the CI lane: every scenario at reduced scale, ≤30 s on a
laptop CPU, no JAX/TPU anywhere on the import path. ``--full`` is the
record-setting run (≥100 CRs per scenario) behind BASELINE.md's
control-plane row.

The JSON is the regression artifact: per-scenario p50/p95/p99 for each
lifecycle phase, reconcile/requeue/backoff totals, and the
actuation-vs-controller-overhead split (docs/controlplane_bench.md
explains how to read it).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time

from service_account_auth_improvements_tpu.controlplane.cpbench.actuator import (  # noqa: E501
    LatencyDist,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.chaos import (  # noqa: E501,F401 — importing registers the chaos family into SCENARIOS
    CHAOS_SCENARIOS,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.scenarios import (  # noqa: E501
    SCENARIOS,
    BenchConfig,
    run_scenario,
)

SCHEMA = "cpbench/v1"

#: CRs per scenario. Smoke is sized to finish well inside the 30 s CI
#: budget; full is the ≥100-CRs-per-scenario record run. The chaos
#: family is wall-clock-bound by its injection windows (blackout,
#: stall, storm pulses), not CR count, so its sizes stay modest even
#: at --full.
SMOKE_N = {
    "notebook_ready": 24,
    "gang_ready": 8,          # 8 gangs × 4 host pods
    "churn": 16,              # per run, split over cycles
    "profile_fanout": 24,
    "webhook_inject": 200,
    "sched_contention": 12,   # 12 gangs contending for 4 slice pools
    "chaos_relist": 8,        # 8 gangs vs 2 pools through the storms
    "chaos_blackout": 8,      # half healthy, half mid-outage
    "chaos_node_death": 4,    # 4 gangs, one pool dies under its gang
    "chaos_kubelet_stall": 8,
}
FULL_N = {
    "notebook_ready": 150,
    "gang_ready": 100,        # 100 gangs × 4 host pods
    "churn": 100,
    "profile_fanout": 120,
    "webhook_inject": 1000,
    "sched_contention": 48,   # 12 drain waves over the 4 pools
    "chaos_relist": 16,
    "chaos_blackout": 16,
    "chaos_node_death": 6,
    "chaos_kubelet_stall": 16,
}


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="cpbench", description=__doc__.splitlines()[0],
    )
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--smoke", action="store_true",
                      help="reduced scale, <=30s, the CI lane (default)")
    mode.add_argument("--full", action="store_true",
                      help=">=100 CRs per scenario, the record run")
    ap.add_argument("--scenario", action="append", choices=sorted(SCENARIOS),
                    help="run only these (repeatable; default: all "
                         "healthy scenarios)")
    ap.add_argument("--chaos", action="store_true",
                    help="include the chaos scenario family (fault "
                         "injection + recovery invariants; "
                         "docs/chaos.md) in the run")
    ap.add_argument("--n", type=int,
                    help="override CRs per scenario (all scenarios)")
    ap.add_argument("--concurrency", type=int, default=8,
                    help="concurrent apiserver writers")
    ap.add_argument("--pattern", choices=("burst", "rate"),
                    default="burst", help="arrival pattern")
    ap.add_argument("--rate", type=float, default=50.0,
                    help="creates/second for --pattern rate")
    ap.add_argument("--actuation", default="uniform:5,15",
                    help="fake-kubelet latency dist (ms): const:X | "
                         "uniform:A,B | lognormal:MEDIAN,SIGMA")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=30.0,
                    help="per-scenario ready deadline (seconds)")
    ap.add_argument("--out", default="CONTROLPLANE_BENCH.json",
                    help="output path ('-' for stdout only)")
    ap.add_argument("--dump-dir", default="bench_out",
                    help="black-box artifact directory: scenarios with "
                         "non-Ready objects or invariant violations "
                         "dump journal + explain timelines here (CI "
                         "uploads it if: always() — a failed gate must "
                         "carry its own evidence); empty string "
                         "disables")
    ap.add_argument("--verbose", action="store_true",
                    help="keep controller logs (expected transient "
                         "NotFound backoffs during churn are noisy)")
    return ap


def run(args) -> dict:
    LatencyDist(args.actuation)  # fail fast on a malformed spec
    mode = "full" if args.full else "smoke"
    sizes = FULL_N if args.full else SMOKE_N
    # default run = the healthy family (the regression lane CI parses);
    # --chaos folds the fault-injection family in; --scenario overrides
    wanted = args.scenario or sorted(
        name for name in SCENARIOS
        if args.chaos or name not in CHAOS_SCENARIOS
    )
    started = time.monotonic()
    report: dict = {
        "schema": SCHEMA,
        "mode": mode,
        "generated_unix": time.time(),
        "config": {
            "concurrency": args.concurrency,
            "pattern": args.pattern,
            "rate": args.rate,
            "actuation": args.actuation,
            "seed": args.seed,
        },
        "scenarios": {},
    }
    for name in wanted:
        cfg = BenchConfig(
            n=args.n or sizes[name],
            concurrency=args.concurrency,
            pattern=args.pattern,
            rate=args.rate,
            actuation=args.actuation,
            seed=args.seed,
            timeout=args.timeout,
        )
        t0 = time.monotonic()
        result = run_scenario(name, cfg)
        entry = dict(result.summary)
        entry["ok"] = result.ok
        entry["elapsed_s"] = round(result.elapsed_s, 3)
        report["scenarios"][name] = entry
        if result.blackbox and getattr(args, "dump_dir", ""):
            # black-box flight record: journal tail + explain timeline
            # per non-Ready/violating object, one file per scenario
            os.makedirs(args.dump_dir, exist_ok=True)
            path = os.path.join(args.dump_dir, f"{name}_blackbox.json")
            with open(path, "w") as f:
                json.dump(result.blackbox, f, indent=2, sort_keys=True,
                          default=str)
            print(f"{name}: black-box evidence -> {path}",
                  file=sys.stderr)
        ready = (entry.get("phases_ms") or {}).get("create_to_ready") or {}
        att = (entry.get("stage_attribution") or {}).get(
            "attributed_fraction") or {}
        att_txt = (f" attr={att['mean']:.0%}" if "mean" in att else "")
        print(
            f"{name:16s} {'ok' if result.ok else 'FAIL':4s} "
            f"n={entry['n']:<5d} "
            f"p50={ready.get('p50', float('nan')):8.2f}ms "
            f"p95={ready.get('p95', float('nan')):8.2f}ms "
            f"p99={ready.get('p99', float('nan')):8.2f}ms "
            f"reconciles={entry['reconciles']:<6d} "
            f"({time.monotonic() - t0:.1f}s){att_txt}",
            file=sys.stderr,
        )
    report["elapsed_s"] = round(time.monotonic() - started, 3)
    report["ok"] = all(
        s["ok"] for s in report["scenarios"].values()
    ) and bool(report["scenarios"])
    return report


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not args.verbose:
        # churn legitimately races deletes against in-flight reconciles;
        # the backoff counter records them — the tracebacks are noise
        logging.getLogger(
            "service_account_auth_improvements_tpu"
        ).setLevel(logging.CRITICAL)
    report = run(args)
    text = json.dumps(report, indent=2, sort_keys=True)
    if args.out == "-":
        print(text)
    else:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"wrote {args.out}", file=sys.stderr)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
