"""cpbench CLI: run scenarios, emit CONTROLPLANE_BENCH.json.

``python -m service_account_auth_improvements_tpu.controlplane.cpbench
--smoke`` is the CI lane: every scenario at reduced scale, ≤30 s on a
laptop CPU, no JAX/TPU anywhere on the import path. ``--full`` is the
record-setting run (≥100 CRs per scenario) behind BASELINE.md's
control-plane row.

The JSON is the regression artifact: per-scenario p50/p95/p99 for each
lifecycle phase, reconcile/requeue/backoff totals, and the
actuation-vs-controller-overhead split (docs/controlplane_bench.md
explains how to read it).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import statistics
import sys
import time

from service_account_auth_improvements_tpu.controlplane.cpbench.actuator import (  # noqa: E501
    LatencyDist,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.chaos import (  # noqa: E501,F401 — importing registers the chaos family into SCENARIOS
    CHAOS_SCENARIOS,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.ha import (  # noqa: E501,F401 — importing registers the ha_scale family into SCENARIOS
    HA_SCENARIOS,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.park import (  # noqa: E501,F401 — importing registers the park_resume family into SCENARIOS
    PARK_SCENARIOS,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.policy import (  # noqa: E501,F401 — importing registers the sched_policy family into SCENARIOS
    POLICY_SCENARIOS,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.scenarios import (  # noqa: E501
    SCENARIOS,
    BenchConfig,
    run_scenario,
)
from service_account_auth_improvements_tpu.controlplane.cpbench.storm import (  # noqa: E501,F401 — importing registers the storm_scale family into SCENARIOS
    STORM_SCENARIOS,
)
from service_account_auth_improvements_tpu.controlplane import obs

SCHEMA = "cpbench/v1"

#: CRs per scenario. Smoke is sized to finish well inside the 30 s CI
#: budget; full is the ≥100-CRs-per-scenario record run. The chaos
#: family is wall-clock-bound by its injection windows (blackout,
#: stall, storm pulses), not CR count, so its sizes stay modest even
#: at --full.
SMOKE_N = {
    "notebook_ready": 24,
    "gang_ready": 8,          # 8 gangs × 4 host pods
    "churn": 16,              # per run, split over cycles
    "profile_fanout": 24,
    "webhook_inject": 200,
    "sched_contention": 12,   # 12 gangs contending for 4 slice pools
    "apiserver_stress": 240,  # CRs per sweep arm (x3 arms: 1/2/4 workers)
    "chaos_relist": 8,        # 8 gangs vs 2 pools through the storms
    "chaos_blackout": 8,      # half healthy, half mid-outage
    "chaos_node_death": 4,    # 4 gangs, one pool dies under its gang
    "chaos_kubelet_stall": 8,
    "chaos_429_storm": 8,     # 8 gangs drained through 429 pulses
    "chaos_park_blackout": 8,  # 4 parked + 4 queued through 2 outages
    "chaos_alert_fidelity": 8,  # canary-fed page alert through a blackout
    "ha_scale": 120,          # CRs per replica arm (x3 arms: 1/2/4)
    "ha_failover": 60,        # two waves around the leader kill
    "ha_apf": 400,            # protected-lane requests per A/B arm
    "sched_policy": 12,       # per A/B arm (best_fit, then learned)
    "sched_policy_frag": 16,  # single-host churn per arm
    "park_resume_cycle": 8,   # paced park→resume per-notebook latency
    "park_resume_storm": 12,  # thundering-herd park/resume bursts
    "park_during_gang": 4,    # 2 gangs parked under a second wave
    "park_oversubscribe": 6,  # 6 gangs through 2 pools (x2 arms)
    "storm_scale": 240,       # composed-arrival main arm (+2 A/B arms)
    "storm_autoscale": 240,   # workshop storm against 1→3 replicas
    "storm_chaos": 120,       # 429 storm + blackout composed
}
FULL_N = {
    "notebook_ready": 150,
    "gang_ready": 100,        # 100 gangs × 4 host pods
    "churn": 100,
    "profile_fanout": 120,
    "webhook_inject": 1000,
    "sched_contention": 48,   # 12 drain waves over the 4 pools
    "apiserver_stress": 10_000,  # the HA-item scale: ~40k watch events/arm
    "chaos_relist": 16,
    "chaos_blackout": 16,
    "chaos_node_death": 6,
    "chaos_kubelet_stall": 16,
    "chaos_429_storm": 16,
    "chaos_park_blackout": 16,
    "chaos_alert_fidelity": 16,
    "ha_scale": 10_000,       # the ROADMAP scale: 10k CRs per arm, and
                              # ~100k watch events across the 4-replica
                              # arm's informers
    "ha_failover": 2_000,
    "ha_apf": 3_000,
    "sched_policy": 48,       # the sched_contention --full scale
    "sched_policy_frag": 64,
    "park_resume_cycle": 32,
    "park_resume_storm": 48,
    "park_during_gang": 8,
    "park_oversubscribe": 16,
    "storm_scale": 100_000,   # the tentpole regime: 100k CRs, 5
                              # watchers x ~2 events/CR => 1M+ watch
                              # events through the fanout
    "storm_autoscale": 4_000,
    "storm_chaos": 2_000,
}


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="cpbench", description=__doc__.splitlines()[0],
    )
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--smoke", action="store_true",
                      help="reduced scale, <=30s, the CI lane (default)")
    mode.add_argument("--full", action="store_true",
                      help=">=100 CRs per scenario, the record run")
    ap.add_argument("--scenario", action="append", choices=sorted(SCENARIOS),
                    help="run only these (repeatable; default: all "
                         "healthy scenarios)")
    ap.add_argument("--chaos", action="store_true",
                    help="include the chaos scenario family (fault "
                         "injection + recovery invariants; "
                         "docs/chaos.md) in the run")
    ap.add_argument("--ha", action="store_true",
                    help="include the ha_scale family (sharded "
                         "multi-replica plane: replica sweep, "
                         "leader-kill failover, APF A/B; docs/ha.md) "
                         "in the run")
    ap.add_argument("--policy", action="store_true",
                    help="include the sched_policy family (learned "
                         "placement A/B: best_fit arm → train on its "
                         "journal → learned arm; needs the JAX half "
                         "of the tree; docs/scheduler.md) in the run")
    ap.add_argument("--fleet", action="store_true",
                    help="include the cpfleet observability lane "
                         "(ha_scale's fleet-aggregated replica sweep + "
                         "chaos_alert_fidelity's burn-rate alert "
                         "fire/resolve check; gated by bench_gate "
                         "--fleet; docs/observability.md 'Fleet') in "
                         "the run")
    ap.add_argument("--park", action="store_true",
                    help="include the park_resume family (checkpoint-"
                         "park/resume latency, resume storm, park-"
                         "during-gang, oversubscription A/B; "
                         "docs/scheduler.md 'Oversubscription & "
                         "parking') in the run")
    ap.add_argument("--storm", action="store_true",
                    help="include the storm_scale family (trace-driven "
                         "composed arrivals at the 100k-CR regime, "
                         "hot-path A/B, saturation-driven replica "
                         "autoscaling, composed chaos; gated by "
                         "bench_gate --storm; docs/controlplane_bench"
                         ".md 'Storm scale') in the run")
    ap.add_argument("--journal-out", default="", metavar="DIR",
                    help="dump each scenario's decision journal as "
                         "<DIR>/<scenario>_journal.jsonl next to the "
                         "bench record — the sched-journal/v1 harvest "
                         "surface the placement policy trains on "
                         "(empty string disables)")
    ap.add_argument("--profile", action="store_true",
                    help="cpprof: sample hot stacks + lock contention + "
                         "saturation per scenario into extra.prof, and "
                         "record the profiler-off A/B on notebook_ready "
                         "(gated by bench_gate --prof-report); full "
                         "folded profiles land in --dump-dir on "
                         "violations")
    ap.add_argument("--n", type=int,
                    help="override CRs per scenario (all scenarios)")
    ap.add_argument("--concurrency", type=int, default=8,
                    help="concurrent apiserver writers")
    ap.add_argument("--pattern", choices=("burst", "rate"),
                    default="burst", help="arrival pattern")
    ap.add_argument("--rate", type=float, default=50.0,
                    help="creates/second for --pattern rate")
    ap.add_argument("--actuation", default="uniform:5,15",
                    help="fake-kubelet latency dist (ms): const:X | "
                         "uniform:A,B | lognormal:MEDIAN,SIGMA")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=30.0,
                    help="per-scenario ready deadline (seconds)")
    ap.add_argument("--out", default="CONTROLPLANE_BENCH.json",
                    help="output path ('-' for stdout only)")
    ap.add_argument("--dump-dir", default="bench_out",
                    help="black-box artifact directory: scenarios with "
                         "non-Ready objects or invariant violations "
                         "dump journal + explain timelines here (CI "
                         "uploads it if: always() — a failed gate must "
                         "carry its own evidence); empty string "
                         "disables")
    ap.add_argument("--verbose", action="store_true",
                    help="keep controller logs (expected transient "
                         "NotFound backoffs during churn are noisy)")
    return ap


def _prof_extra(profiler, locks_t0: dict, extra: dict) -> dict:
    """The per-scenario ``extra.prof`` record: top hot stacks (sampler,
    reconcile-attributed), top contended lock sites (lockwatch delta
    over this scenario), saturation gauges, and the per-client apiserver
    request split — the one place bench_gate --prof-report looks."""
    rep = profiler.report(top_k=10)
    # wide window for the share sum (a lock-heavy process can push the
    # fake's sites past any top-10), narrow slice for the report rows
    all_locks = obs.lock_contention_top(since=locks_t0, limit=50)
    locks = all_locks[:10]
    # the ONE share definition (obs.store_lock_wait_share — shared with
    # the apiserver_stress sweep arms); bench_gate --store-lock-max-share
    # fails CI when the fake becomes the serialization point again
    share = obs.store_lock_wait_share(all_locks, rep["duration_s"])
    return {
        "schema": "cpprof/v1",
        "hz": rep["hz"],
        "samples": rep["samples"],
        "duration_s": rep["duration_s"],
        "top_stack": rep["top_stack"],
        "top_controller": rep["top_controller"],
        "stacks": rep["stacks"],
        "functions": rep["functions"],
        "locks": locks,
        "top_contended_lock": locks[0]["site"] if locks else None,
        "store_lock_wait_share": share,
        "saturation": obs.saturation_snapshot(),
        "by_client": extra.get("apiserver_requests_by_client") or {},
    }


def _overhead_ab(args) -> dict:
    """CPPROF=0 vs 1 A/B on notebook_ready, the evidence that profiling
    is cheap enough to leave on (bench_gate --prof-report holds the p95
    ratio to ≤1.05). Methodology, tuned for a noisy shared box whose
    run-to-run drift (±20 % observed) dwarfs the sampler's ~1 % true
    cost:

    - **paired runs** (O N O N ... O — every profiled run sandwiched
      between unprofiled neighbors): ambient load on a shared box
      drifts with a correlation time of tens of seconds — comparable to
      the whole experiment — so arm-pooled statistics (min-of-k,
      median-of-k, any mirrored order) inherit whichever slow swell
      happened to cover more of one arm; measured ±6 % wander, sign
      included. Each profiled run divided by the MEAN of its two
      neighbors cancels the swell locally (both neighbors ride the same
      one); the reported ratio is the median over the pairs, robust to
      a single loaded pair.
    - **n pinned at 48** (both lanes; --n still overrides): the full
      burst (150 CRs) sits on the saturation cliff where p95 amplifies
      ambient noise far more than it amplifies sampler cost, while the
      smoke burst (24 CRs) finishes in ~150 ms — below the box's
      scheduling jitter. 48 sits between: saturated enough that a real
      overhead regression (a 10x costlier sampler) shows, long enough
      that p95 isn't noise, and identical across lanes so the smoke
      gate and the committed record measure the same experiment.

    The lock instrumentation stays installed in both arms: wrappers on
    live locks cannot be peeled off a running process, so the A/B
    isolates the sampler — the only part with a global (GIL) cost."""
    cfg = BenchConfig(
        n=args.n or 48,
        concurrency=args.concurrency, pattern=args.pattern,
        rate=args.rate, actuation=args.actuation, seed=args.seed,
        timeout=args.timeout,
    )
    pairs = 10
    # unmeasured warm-up: the A/B runs first in a cold process, and the
    # first few runs ride a convex warm-up curve (allocator, caches) —
    # on that curve EVERY pair reads below 1 (the midpoint of a convex
    # arc is below its endpoints' mean), systematically understating
    # overhead. Two throwaway runs flatten it before measurement.
    for _ in range(2):
        run_scenario("notebook_ready", cfg)
    sequence: list[float | None] = []   # p95 per run, off/on alternating
    ok = True
    for i in range(2 * pairs + 1):      # O N O N ... O
        profiled = i % 2 == 1
        profiler = obs.Profiler() if profiled else None
        if profiler is not None:
            profiler.start()
        try:
            result = run_scenario("notebook_ready", cfg)
        finally:
            if profiler is not None:
                profiler.stop()
        ok = ok and result.ok
        p95 = (result.summary["phases_ms"]
               .get("create_to_ready") or {}).get("p95")
        sequence.append(round(p95, 3) if p95 is not None else None)
    paired = [
        sequence[i] / ((sequence[i - 1] + sequence[i + 1]) / 2.0)
        for i in range(1, len(sequence), 2)
        if sequence[i] and sequence[i - 1] and sequence[i + 1]
    ]
    ons = [sequence[i] for i in range(1, len(sequence), 2)
           if sequence[i]]
    offs = [sequence[i] for i in range(0, len(sequence), 2)
            if sequence[i]]
    return {
        "scenario": "notebook_ready",
        "method": "paired off/on x10 at n=48, median of "
                  "on-vs-adjacent-offs ratios",
        "n": cfg.n,
        "p95_on_ms": (round(statistics.median(ons), 3)
                      if ons else None),
        "p95_off_ms": (round(statistics.median(offs), 3)
                       if offs else None),
        "p95_runs_ms": sequence,
        "paired_ratios": [round(r, 4) for r in paired],
        "ratio": (round(statistics.median(paired), 4)
                  if paired else None),
        "runs_ok": ok,
    }


def run(args) -> dict:
    LatencyDist(args.actuation)  # fail fast on a malformed spec
    profiling = getattr(args, "profile", False)
    if profiling:
        # lock wrappers only watch locks created AFTER installation —
        # install before any scenario world exists. Idempotent (shares
        # the CPLINT_LOCKWATCH instance when the lint lane installed it
        # first: ONE wrapper layer, by design).
        obs.install_lock_contention()
    mode = "full" if args.full else "smoke"
    sizes = FULL_N if args.full else SMOKE_N
    # default run = the healthy family (the regression lane CI parses);
    # --chaos folds the fault-injection family in, --ha the sharded-
    # plane family (both arm-sweep benches, not latency-lane members);
    # --scenario overrides
    fleet_lane = {"ha_scale", "chaos_alert_fidelity"}
    wanted = args.scenario or sorted(
        name for name in SCENARIOS
        if (getattr(args, "fleet", False) and name in fleet_lane)
        or ((args.chaos or name not in CHAOS_SCENARIOS)
            and (getattr(args, "ha", False) or name not in HA_SCENARIOS)
            and (getattr(args, "policy", False)
                 or name not in POLICY_SCENARIOS)
            and (getattr(args, "park", False)
                 or name not in PARK_SCENARIOS)
            and (getattr(args, "storm", False)
                 or name not in STORM_SCENARIOS))
    )
    started = time.monotonic()
    report: dict = {
        "schema": SCHEMA,
        "mode": mode,
        "generated_unix": time.time(),
        "config": {
            "concurrency": args.concurrency,
            "pattern": args.pattern,
            "rate": args.rate,
            "actuation": args.actuation,
            "seed": args.seed,
        },
        "scenarios": {},
    }
    if profiling and "notebook_ready" in wanted:
        # the A/B runs FIRST, in the freshest process state: after a
        # full suite the heap is large and GC pauses spike individual
        # runs by 2x, noise the pairing can't always reject (measured —
        # the same experiment reads ±1 % fresh and ±10 % post-suite).
        # An overhead measurement exists to catch sampler-cost
        # regressions; fresh-state is the controlled condition.
        report["profiler_overhead"] = _overhead_ab(args)
        ov = report["profiler_overhead"]
        print(
            f"profiler A/B     "
            f"p95 on={ov['p95_on_ms'] or float('nan'):.2f}ms "
            f"off={ov['p95_off_ms'] or float('nan'):.2f}ms "
            f"ratio={ov['ratio']}",
            file=sys.stderr,
        )
    for name in wanted:
        cfg = BenchConfig(
            n=args.n or sizes[name],
            concurrency=args.concurrency,
            pattern=args.pattern,
            rate=args.rate,
            actuation=args.actuation,
            seed=args.seed,
            timeout=args.timeout,
        )
        t0 = time.monotonic()
        profiler = locks_t0 = None
        if profiling:
            profiler = obs.Profiler()
            locks_t0 = obs.lock_contention_snapshot()
            profiler.start()
        try:
            result = run_scenario(name, cfg)
        finally:
            if profiler is not None:
                profiler.stop()
        entry = dict(result.summary)
        entry["ok"] = result.ok
        entry["elapsed_s"] = round(result.elapsed_s, 3)
        if profiler is not None:
            entry.setdefault("extra", {})["prof"] = _prof_extra(
                profiler, locks_t0, entry.get("extra") or {}
            )
            if not result.ok and getattr(args, "dump_dir", ""):
                # a violating scenario ships its FULL folded profile —
                # the flamegraph input, not just the top-k summary
                os.makedirs(args.dump_dir, exist_ok=True)
                fold_path = os.path.join(args.dump_dir,
                                         f"{name}_profile.folded")
                with open(fold_path, "w") as f:
                    f.write(profiler.folded())
                print(f"{name}: folded profile -> {fold_path}",
                      file=sys.stderr)
        report["scenarios"][name] = entry
        if result.journal_jsonl and getattr(args, "journal_out", ""):
            # the harvest surface, standalone: sched-journal/v1 rows
            # ready for scheduler/policy/train.py --journal
            os.makedirs(args.journal_out, exist_ok=True)
            jpath = os.path.join(args.journal_out,
                                 f"{name}_journal.jsonl")
            with open(jpath, "w") as f:
                f.write(result.journal_jsonl)
            print(f"{name}: decision journal -> {jpath}",
                  file=sys.stderr)
        if result.blackbox and getattr(args, "dump_dir", ""):
            # black-box flight record: journal tail + explain timeline
            # per non-Ready/violating object, one file per scenario
            os.makedirs(args.dump_dir, exist_ok=True)
            path = os.path.join(args.dump_dir, f"{name}_blackbox.json")
            with open(path, "w") as f:
                json.dump(result.blackbox, f, indent=2, sort_keys=True,
                          default=str)
            print(f"{name}: black-box evidence -> {path}",
                  file=sys.stderr)
        ready = (entry.get("phases_ms") or {}).get("create_to_ready") or {}
        att = (entry.get("stage_attribution") or {}).get(
            "attributed_fraction") or {}
        att_txt = (f" attr={att['mean']:.0%}" if "mean" in att else "")
        print(
            f"{name:16s} {'ok' if result.ok else 'FAIL':4s} "
            f"n={entry['n']:<5d} "
            f"p50={ready.get('p50', float('nan')):8.2f}ms "
            f"p95={ready.get('p95', float('nan')):8.2f}ms "
            f"p99={ready.get('p99', float('nan')):8.2f}ms "
            f"reconciles={entry['reconciles']:<6d} "
            f"({time.monotonic() - t0:.1f}s){att_txt}",
            file=sys.stderr,
        )
    report["elapsed_s"] = round(time.monotonic() - started, 3)
    report["ok"] = all(
        s["ok"] for s in report["scenarios"].values()
    ) and bool(report["scenarios"])
    return report


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not args.verbose:
        # churn legitimately races deletes against in-flight reconciles;
        # the backoff counter records them — the tracebacks are noise
        logging.getLogger(
            "service_account_auth_improvements_tpu"
        ).setLevel(logging.CRITICAL)
    report = run(args)
    text = json.dumps(report, indent=2, sort_keys=True)
    if args.out == "-":
        print(text)
    else:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"wrote {args.out}", file=sys.stderr)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
