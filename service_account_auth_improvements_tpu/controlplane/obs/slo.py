"""cpscope SLO engine: objectives, attainment, error-budget burn.

Until now the plane had latency *measurements* (cpbench percentiles,
engine histograms) but no *objectives* — nothing to tell a regression
from noise, or CI from product truth. This module declares the
objectives once and computes two numbers per objective from whatever
samples exist:

- **attainment** — the fraction of samples meeting the target
  (``value_ms <= target_ms``). The objective is met when attainment ≥
  the declared objective fraction (e.g. 0.95 for a p95 target);
- **error-budget burn** — the violation fraction divided by the budget
  (``1 - objective``). Burn 1.0 = spending the budget exactly as
  declared; > 1.0 = burning faster (the page-worthy signal SRE burn-rate
  alerts key on); < 1.0 = headroom.

Samples come from raw lists (cpbench's exact timelines) or from the
existing Prometheus histograms via :func:`attainment_from_histogram`
(bucket-cumulative, no raw retention needed) — the production
``/slostatus`` path. Gauges ``slo_attainment`` / ``slo_error_budget_burn``
expose both per objective.

The target numbers are PRODUCT ceilings, not bench baselines: the ±20%
bench_gate envelope catches regressions long before an SLO trips; an SLO
miss means the product promise broke, on any hardware.
"""

from __future__ import annotations

import dataclasses
import threading

from service_account_auth_improvements_tpu.controlplane.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
)
from service_account_auth_improvements_tpu.controlplane.obs.trace import (
    current_tracer,
)


@dataclasses.dataclass(frozen=True)
class Objective:
    name: str
    description: str
    target_ms: float
    #: required attainment fraction (0.95 = a p95 target)
    objective: float = 0.95


#: the declared objectives. create→Ready and time-to-placement come from
#: the paper's product surface (notebook spawn latency IS the product);
#: the recovery ceiling comes from the chaos family's recovery-time
#: samples — a plane that heals slower than this isn't HA-ready.
DEFAULT_OBJECTIVES = (
    Objective(
        "create_to_ready",
        "notebook CR create -> status Ready, p95 under 15s",
        target_ms=15_000.0,
    ),
    Objective(
        "time_to_placement",
        "tpusched admission -> node-pool stamp under contention, "
        "p95 under 60s",
        target_ms=60_000.0,
    ),
    Objective(
        "recovery",
        "chaos injection -> invariant-clean recovery, p95 under 30s",
        target_ms=30_000.0,
    ),
    Objective(
        "watch_delivery",
        "apiserver watch event emit -> consumer receipt under stress "
        "churn, p95 under 5s",
        target_ms=5_000.0,
    ),
    Objective(
        "failover",
        "leader/replica kill -> orphaned shards re-owned and their "
        "pending keys reconciled, p95 under 30s",
        # the ceiling budgets PRODUCTION 15 s leases: a crashed
        # replica's member + coordinator leases must expire
        # (duration x 1.25 skew tolerance ~ 19 s, measured 22.6 s
        # end-to-end over real HTTP binaries) before re-election and
        # re-mapping even start — bench worlds with 1 s leases measure
        # ~1.7 s, but the promise must hold at production timings
        target_ms=30_000.0,
    ),
    Objective(
        "resume_latency",
        "parked notebook resume request (stop cleared) -> checkpoint "
        "restored and park state cleared, p95 under 30s",
        # the product promise behind scale-to-zero: a resume must feel
        # like a slow page load, not a fresh spawn. The window covers
        # re-admission through tpusched (queue wait under contention is
        # WHY it isn't the 15 s create_to_ready ceiling) plus the
        # checkpoint restore; oversubscription is gated on holding this
        # at the same attainment as the unparked baseline (bench_gate
        # --park).
        target_ms=30_000.0,
    ),
    Objective(
        "scale_up_latency",
        "fleet saturation onset under a workshop storm -> the "
        "autoscaler's new replica covering shards, p95 under 30s",
        # the storm promise: from the first saturated scrape of a
        # workshop storm to the joined replica actively owning shards.
        # The window covers the autoscaler's hysteresis (2 consecutive
        # saturated scrapes by design — engine/autoscale.py), the
        # replica start, and the shard re-map + barrier; production
        # 15 s leases put the re-map in the ~20 s band, so 30 s is the
        # same production-timing budget the failover ceiling uses.
        target_ms=30_000.0,
    ),
)

OBJECTIVES_BY_NAME = {o.name: o for o in DEFAULT_OBJECTIVES}

#: ``slo_sample_duration_seconds`` bucket bounds. Every DEFAULT_OBJECTIVES
#: target (5/15/30/60 s) is an exact bound, so the fleet aggregator's
#: bucket-merged attainment (:func:`attainment_from_counts`) is exact for
#: the declared objectives, not merely conservative.
SLO_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 15.0, 30.0,
               60.0, 120.0)


def attainment(samples_ms, target_ms: float) -> float | None:
    """Fraction of samples meeting the target; None without samples."""
    xs = list(samples_ms)
    if not xs:
        return None
    return sum(1 for v in xs if v <= target_ms) / len(xs)


def burn_rate(attained: float | None, objective: float) -> float | None:
    """Violation fraction over budget. An objective of 1.0 has zero
    budget: any violation is infinite burn (represented as None-safe
    large value by the caller's rendering; here: float('inf'))."""
    if attained is None:
        return None
    budget = 1.0 - objective
    violated = 1.0 - attained
    if budget <= 0:
        return 0.0 if violated <= 0 else float("inf")
    return violated / budget


def report(samples_by_objective: dict, objectives=None) -> dict:
    """Attainment record for a set of raw sample lists — the shape
    cpbench writes per scenario and ``bench_gate --slo-report`` gates:
    ``{objective: {target_ms, objective, n, attainment, burn, met}}``.
    An objective with zero samples is NOT met — absence of evidence
    isn't attainment (the chaos-gate asymmetry, applied to SLOs)."""
    objs = {o.name: o for o in (objectives or DEFAULT_OBJECTIVES)}
    out: dict = {}
    for name, samples in samples_by_objective.items():
        obj = objs.get(name)
        if obj is None:
            raise KeyError(f"undeclared SLO objective {name!r}")
        att = attainment(samples, obj.target_ms)
        burn = burn_rate(att, obj.objective)
        out[name] = {
            "target_ms": obj.target_ms,
            "objective": obj.objective,
            "n": len(list(samples)),
            "attainment": None if att is None else round(att, 4),
            "burn": (None if burn is None
                     else round(burn, 4) if burn != float("inf")
                     else "inf"),
            "met": att is not None and att >= obj.objective,
        }
    return out


def attainment_from_counts(bucket_bounds, counts,
                           target_s: float) -> float | None:
    """Attainment from cumulative bucket counts (the ``Histogram._counts``
    shape: one slot per bound plus the trailing +Inf/total slot):
    cumulative count of the largest bucket ≤ target over the total.
    Conservative — when the target falls between bucket bounds the
    bucket BELOW it is used (never over-reports attainment). This is
    the ONE bucket→attainment definition: the in-process histogram path
    below and the fleet aggregator's cross-replica bucket merge
    (obs/fleet.py) both resolve here, so a single-replica /slostatus and
    the fleet roll-up can never disagree about what a bucket means."""
    counts = list(counts)
    if not counts or counts[-1] == 0:
        return None
    total = counts[-1]
    att = 0
    for i, bound in enumerate(bucket_bounds):
        if bound <= target_s:
            att = counts[i]
        else:
            break
    return att / total


def attainment_from_histogram(hist, target_s: float,
                              label_values: tuple = ()) -> float | None:
    """Attainment straight from a metrics/registry Histogram — the
    in-process convenience wrapper over :func:`attainment_from_counts`."""
    key = tuple(str(v) for v in label_values)
    with hist._lock:
        counts = list(hist._counts.get(key) or ())
    return attainment_from_counts(hist.buckets, counts, target_s)


class SloEngine:
    """Live SLO state for one process: observe samples (or ingest
    histograms), expose gauges, answer ``/slostatus``."""

    #: per-objective raw-sample retention (attainment is a fraction over
    #: the retained window — a month-old miss must age out)
    MAX_SAMPLES = 4096

    def __init__(self, objectives=None, registry: Registry | None = None):
        self.objectives = tuple(objectives or DEFAULT_OBJECTIVES)
        self._by_name = {o.name: o for o in self.objectives}
        self._lock = threading.Lock()
        self._samples: dict[str, list] = {o.name: []
                                          for o in self.objectives}
        reg = registry if registry is not None else Registry()
        self.g_attainment = Gauge(
            "slo_attainment",
            "fraction of samples meeting the objective's target",
            ("objective",), registry=reg,
        )
        self.g_burn = Gauge(
            "slo_error_budget_burn",
            "error-budget burn rate (1.0 = budget spent exactly)",
            ("objective",), registry=reg,
        )
        # the cumulative series the fleet aggregator federates: the
        # gauges above are windowed over the retained sample ring (they
        # answer "how are we doing lately"), while burn-rate ALERTING
        # needs counter deltas over explicit windows — post-recovery, a
        # ring-based burn would stay elevated until the bad samples age
        # out of 4096, pinning a page alert long after the incident.
        self.c_samples = Counter(
            "slo_samples_total",
            "SLO samples observed, cumulative per objective",
            ("objective",), registry=reg,
        )
        self.c_violations = Counter(
            "slo_violations_total",
            "SLO samples over the objective's target, cumulative",
            ("objective",), registry=reg,
        )
        self.h_samples = Histogram(
            "slo_sample_duration_seconds",
            "SLO sample latency; fleet attainment merges these buckets",
            ("objective",), buckets=SLO_BUCKETS, registry=reg,
        )

    def attach(self, tracer) -> "SloEngine":
        """Make this engine discoverable via ``current_tracer().slo`` —
        the journal's wiring pattern: controllers call the module-level
        :func:`observe` with zero plumbing, and cpbench worlds get
        isolated engines."""
        tracer.slo = self
        return self

    def observe(self, objective: str, value_ms: float) -> None:
        obj = self._by_name.get(objective)
        if obj is None:
            raise KeyError(f"undeclared SLO objective {objective!r}")
        self.c_samples.labels(objective).inc()
        if value_ms > obj.target_ms:
            self.c_violations.labels(objective).inc()
        self.h_samples.labels(objective).observe(value_ms / 1000.0)
        with self._lock:
            samples = self._samples[objective]
            samples.append(float(value_ms))
            if len(samples) > self.MAX_SAMPLES:
                del samples[:len(samples) - self.MAX_SAMPLES]
            snapshot = list(samples)
        att = attainment(snapshot, obj.target_ms)
        burn = burn_rate(att, obj.objective)
        self.g_attainment.labels(objective).set(att if att is not None
                                                else 0.0)
        if burn is not None and burn != float("inf"):
            self.g_burn.labels(objective).set(burn)

    def status(self) -> dict:
        """The /slostatus body: every declared objective with its
        current attainment record (objectives with no samples yet say
        so rather than vanishing)."""
        with self._lock:
            samples = {name: list(v) for name, v in self._samples.items()}
        rec = report(samples, objectives=self.objectives)
        return {
            "schema": "slostatus/v1",
            "objectives": {
                o.name: {"description": o.description, **rec[o.name]}
                for o in self.objectives
            },
        }


#: lazy process-global engine — the production /slostatus + gauge
#: surface. Lazy (not import-time) so the global metrics registry only
#: grows the slo families in processes that actually serve them.
_DEFAULT: list = []
_DEFAULT_LOCK = threading.Lock()


def default_engine() -> SloEngine:
    """The process engine, gauges on the GLOBAL metrics registry —
    cmd/runner.py serves it on /slostatus and every binary's /metrics."""
    with _DEFAULT_LOCK:
        if not _DEFAULT:
            from service_account_auth_improvements_tpu.controlplane.metrics import (  # noqa: E501
                REGISTRY,
            )

            _DEFAULT.append(SloEngine(registry=REGISTRY))
        return _DEFAULT[0]


def observe(objective: str, value_ms: float) -> None:
    """Feed one sample into the ambient engine: the one attached to the
    current tracer (cpbench worlds), else the process default. This is
    how production code reports — the notebook controller observes
    create→Ready at the Ready transition, tpusched observes
    time-to-placement at the stamp — with the journal's zero-plumbing
    resolution rule. Never raises into a reconcile."""
    eng = getattr(current_tracer(), "slo", None)
    if eng is None:
        eng = default_engine()
    try:
        eng.observe(objective, value_ms)
    except Exception:  # noqa: BLE001 — telemetry, not control flow
        pass
