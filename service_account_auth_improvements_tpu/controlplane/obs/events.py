"""cpscope event recording: correlated, aggregated, rate-limited Events.

The controllers' user-visible debugging surface, rebuilt to client-go's
EventCorrelator contract (client-go tools/record: EventAggregator +
eventLogger + EventSourceObjectSpamFilter). The first recorder
(PR 0's ``controlplane/events.py``, now a thin re-export of this module)
round-tripped a GET per repeat and had no spam control at all — a
hot-looping controller could storm the apiserver with its own telemetry,
which is exactly the failure mode Events exist to *diagnose*. Three
layers fix that, all decided locally before any apiserver call:

- **dedup** — a stable name per (component, involvedObject, type,
  reason, message) digest; repeats become one ``count``/``lastTimestamp``
  PATCH against the remembered count (no read-modify-write round trip
  after the first occurrence);
- **aggregation** — more than ``aggregate_after`` *distinct* messages
  for one (involvedObject, type, reason) group collapse into a single
  "(combined from similar events)" Event whose message tracks the latest
  occurrence: cardinality stays bounded no matter how creative the
  failure text gets;
- **token-bucket rate limiting** — per involved object, ``burst``
  events then one earned back every ``refill_s/burst`` seconds
  (client-go's spam filter: 25 / qps 1/300); beyond that the record is
  DROPPED locally and counted in :meth:`stats`, never sent.

Clocks are injected (``now_fn`` wall for timestamps, ``mono_fn`` for
the bucket) so chaos scenarios and the cplint clock-injection pass can
drive them deterministically.

Reason strings are part of the public, queryable surface (``kubectl get
events --field-selector reason=...``, dashboards group by them), so they
are constants — the cplint ``event-reason`` pass holds every call site
to module-level CamelCase constants, no f-strings.
"""

from __future__ import annotations

import collections
import datetime
import hashlib
import logging
import threading

from service_account_auth_improvements_tpu.controlplane.kube import errors

log = logging.getLogger(__name__)

NORMAL = "Normal"
WARNING = "Warning"

#: message prefix of an aggregated Event (client-go parity, verbatim)
AGGREGATE_PREFIX = "(combined from similar events): "


def _utcnow() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def _mono() -> float:
    import time

    return time.monotonic()


def _fmt(ts: datetime.datetime) -> str:
    return ts.strftime("%Y-%m-%dT%H:%M:%SZ")


class EventRecorder:
    """Records v1 Events against an involved object (module docstring
    has the correlation pipeline).

    ``event()`` is fire-and-forget: a failed write is logged, never
    raised — losing an Event must not fail a reconcile. ``emit()`` is
    the raising variant for callers with their own retry policy (the
    notebook re-emission worker). Both return ``True`` when a write was
    issued and ``False`` when the spam filter dropped the record.
    """

    def __init__(self, kube, component: str, *,
                 burst: int = 25, refill_s: float = 300.0,
                 aggregate_after: int = 10, cache_size: int = 512,
                 now_fn=None, mono_fn=None):
        self.kube = kube
        self.component = component
        self.burst = burst
        self.refill_s = refill_s
        self.aggregate_after = aggregate_after
        self.cache_size = cache_size
        self._now = now_fn if now_fn is not None else _utcnow
        self._mono = mono_fn if mono_fn is not None else _mono
        self._lock = threading.Lock()
        #: event object name -> last count this recorder wrote (the
        #: dedup cache: repeats patch count+1 with no preceding GET)
        self._counts: collections.OrderedDict = collections.OrderedDict()
        #: (involved, type, reason) group -> set of message digests (the
        #: aggregation trigger) — LRU-bounded like the count cache
        self._messages: collections.OrderedDict = collections.OrderedDict()
        #: per-involved-object token bucket: key -> [tokens, last_mono]
        self._buckets: collections.OrderedDict = collections.OrderedDict()
        self._dropped = 0
        self._aggregated = 0
        self._emitted = 0

    # ------------------------------------------------------------- public

    def event(self, obj: dict, etype: str, reason: str,
              message: str, namespace: str | None = None) -> bool:
        try:
            return self.emit(obj, etype, reason, message,
                             namespace=namespace)
        except errors.ApiError as e:
            log.warning("event %s/%s dropped: %s", reason,
                        obj["metadata"].get("name"), e)
            return False

    def emit(self, obj: dict, etype: str, reason: str,
             message: str, namespace: str | None = None) -> bool:
        """``namespace`` overrides where the Event OBJECT lives — Events
        are namespaced even when the involved object isn't (a
        cluster-scoped Profile's events land in the tenant namespace it
        manages, where the tenant can actually read them)."""
        meta = obj["metadata"]
        involved = {
            "kind": obj.get("kind", ""),
            "apiVersion": obj.get("apiVersion", ""),
            "name": meta["name"],
            "namespace": meta.get("namespace"),
            "uid": meta.get("uid", ""),
        }
        namespace = namespace or meta.get("namespace") or "default"
        # correlate under the lock — pure local state; the apiserver
        # write happens after the lock drops (lockwatch held-write rule)
        with self._lock:
            if not self._take_token_locked(involved):
                self._dropped += 1
                return False
            name, message, count = self._correlate_locked(
                involved, etype, reason, message
            )
            self._emitted += 1
        now = _fmt(self._now())
        if count > 1:
            try:
                self._bump(name, namespace, count, now, message)
                return True
            except errors.NotFound:
                # the Event was GC'd (TTL) mid-life: restart its count
                # and recreate below
                with self._lock:
                    self._counts[name] = 1
        self._write_new(name, namespace, involved, etype, reason,
                        message, now)
        return True

    def stats(self) -> dict:
        """{emitted, dropped_rate_limited, aggregated} — cpbench reports
        these per scenario so spam control is visible, not silent."""
        with self._lock:
            return {
                "emitted": self._emitted,
                "dropped_rate_limited": self._dropped,
                "aggregated": self._aggregated,
            }

    # --------------------------------------------------------- correlation

    def _take_token_locked(self, involved: dict) -> bool:
        """Spam filter: one bucket per involved object. Caller holds the
        lock."""
        key = (involved["namespace"], involved["kind"], involved["name"])
        now = self._mono()
        bucket = self._buckets.get(key)
        if bucket is None:
            bucket = [float(self.burst), now]
            self._buckets[key] = bucket
            self._trim_locked(self._buckets)
        else:
            self._buckets.move_to_end(key)
        tokens, last = bucket
        if self.refill_s > 0:
            tokens = min(float(self.burst),
                         tokens + (now - last) * self.burst / self.refill_s)
        bucket[1] = now
        if tokens < 1.0:
            bucket[0] = tokens
            return False
        bucket[0] = tokens - 1.0
        return True

    def _correlate_locked(self, involved: dict, etype: str, reason: str,
                          message: str) -> tuple[str, str, int]:
        """(event name, possibly-aggregated message, count). Caller
        holds the lock."""
        gkey = (self.component, involved["namespace"], involved["kind"],
                involved["name"], etype, reason)
        digests = self._messages.get(gkey)
        if digests is None:
            digests = set()
            self._messages[gkey] = digests
            self._trim_locked(self._messages)
        else:
            self._messages.move_to_end(gkey)
        mdigest = hashlib.sha1(message.encode()).hexdigest()[:12]
        aggregate = (len(digests) >= self.aggregate_after
                     and mdigest not in digests)
        if not aggregate:
            digests.add(mdigest)
        if aggregate:
            # past the similarity threshold: everything new folds into
            # ONE aggregate Event for the group, message tracking the
            # latest occurrence (client-go EventAggregator semantics)
            self._aggregated += 1
            message = AGGREGATE_PREFIX + message
            digest = hashlib.sha1(
                "\x00".join(("aggregate",) + tuple(
                    str(p) for p in gkey)).encode()
            ).hexdigest()[:12]
        else:
            # The digest must include the recorder's component (and
            # namespace): two controllers emitting the same (kind, name,
            # type, reason, message) would otherwise collide on one
            # Event object and the second write would be mis-attributed
            # to the first's source.component.
            digest = hashlib.sha1(
                "\x00".join((self.component, involved["namespace"] or "",
                             involved["kind"], involved["name"], etype,
                             reason, message)).encode()
            ).hexdigest()[:12]
        name = f"{involved['name']}.{digest}"
        count = self._counts.get(name, 0) + 1
        self._counts[name] = count
        self._counts.move_to_end(name)
        self._trim_locked(self._counts)
        return name, message, count

    def _trim_locked(self, lru: collections.OrderedDict) -> None:
        while len(lru) > self.cache_size:
            lru.popitem(last=False)

    # ---------------------------------------------------------- API writes

    def _bump(self, name: str, namespace: str | None, count: int,
              now: str, message: str) -> None:
        """Repeat occurrence: one PATCH, no read. The remembered count is
        authoritative for this recorder; a raced writer at worst lands a
        nearby value — Events are best-effort counters (k8s offers no
        server-side increment for them)."""
        patch = {"count": count, "lastTimestamp": now}
        if message.startswith(AGGREGATE_PREFIX):
            patch["message"] = message  # aggregate tracks the latest text
        self.kube.patch("events", name, patch, namespace=namespace)

    def _write_new(self, name: str, namespace: str | None, involved: dict,
                   etype: str, reason: str, message: str,
                   now: str) -> None:
        """First occurrence this process has seen: reconcile against any
        survivor from a previous incarnation (GET), else create."""
        try:
            existing = self.kube.get("events", name, namespace=namespace)
        except errors.NotFound:
            existing = None
        if existing is not None:
            count = int(existing.get("count") or 1) + 1
            with self._lock:
                self._counts[name] = count
            # Events are telemetry with client-go correlator
            # semantics: a raced count patch loses a repeat tally,
            # never cluster state; the local cache re-converges
            # cplint: disable=check-then-act — telemetry, races lose a tally
            self.kube.patch(
                "events", name,
                {"count": count, "lastTimestamp": now},
                namespace=namespace,
            )
            return
        try:
            self.kube.create("events", {
                "apiVersion": "v1",
                "kind": "Event",
                "metadata": {"name": name, "namespace": namespace},
                "involvedObject": involved,
                "type": etype,
                "reason": reason,
                "message": message,
                "count": 1,
                "firstTimestamp": now,
                "lastTimestamp": now,
                "source": {"component": self.component},
                "reportingComponent": self.component,
            }, namespace=namespace)
        except errors.AlreadyExists:
            # lost a create race with another worker — re-read the
            # winner's count so occurrences aren't undercounted, fold
            # into a bump
            try:
                existing = self.kube.get("events", name,
                                         namespace=namespace)
                count = int(existing.get("count") or 1) + 1
            except errors.ApiError:
                count = 2
            with self._lock:
                self._counts[name] = count
            self.kube.patch("events", name,
                            {"count": count, "lastTimestamp": now},
                            namespace=namespace)


def involved_kind_and_name(event: dict) -> tuple[str, str]:
    involved = event.get("involvedObject") or {}
    return involved.get("kind", ""), involved.get("name", "")
