"""cpfleet: cross-replica observability for the sharded plane.

cpshard (engine/shard.py) made the plane multi-replica; every
observability surface stayed per-process. A notebook whose key is handed
off mid-lifecycle leaves half its spans on the losing replica and half
on the gainer, fleet SLO attainment is unknowable without hand-merging N
scrapes, and saturation — the autoscaler's input — exists only as N
disconnected gauge sets. This module is the aggregation plane:

- **discovery** rides the membership protocol that already exists: each
  replica's ``<group>-member-*`` Lease advertises its ops URL
  (``cpshard.tpukf.dev/ops-url``, stamped by the member heartbeat), so
  the live-replica set IS the scrape target set — no second registry to
  drift (:func:`lease_replicas_fn`).
- **metric federation** scrapes each replica's ``/metrics`` and merges
  families by kind: counters (and histogram ``_bucket``/``_sum``/
  ``_count`` series, which are counters) accumulate with **reset
  detection** via :func:`metrics.counter_delta` — a restarted replica's
  counter going backwards is a reset, not a negative rate; histogram
  buckets merge element-wise via :func:`metrics.merge_bucket_counts`;
  gauges are kept per-replica-labeled with an explicit fleet roll-up.
- **trace stitching** (:func:`stitch_traces`) regroups every replica's
  tracez spans by trace id — uid-derived (obs/trace.py
  ``object_trace_id``), so the loser's and gainer's spans for one CR
  incarnation already share an id — rebases each replica's monotonic
  timestamps onto its scrape-reported wall anchor, and synthesizes a
  ``shard.handoff_gap`` span over the dark window between one replica's
  last span and the next replica's first: the handoff cost is a visible
  stage, not missing time.
- **fleet SLOs**: attainment per objective from the bucket-merged
  ``slo_sample_duration_seconds`` histograms (obs/slo.py
  ``attainment_from_counts`` — the same definition a single replica
  uses), burn from the merged cumulative counters, both fed to the
  burn-rate :class:`obs.alerts.AlertEngine` on every scrape.
- **the autoscaler input signal**: ``fleet_workqueue_depth_per_worker``
  and ``fleet_worker_busy_ratio``, per replica plus a ``replica="fleet"``
  max roll-up. These two families are THE contract for the ROADMAP's
  autoscaling item: scale Manager replicas up when the fleet roll-up
  saturates, down when it idles — consumers should read these, not
  re-derive from per-replica scrapes (docs/observability.md "Fleet").

A replica that stops answering degrades the view LOUDLY (``partial``
flag, ``PARTIAL FLEET`` banner on /debug/fleetz, ``fleet_replica_up`` 0,
its last-known data marked stale) and never blocks the scrape of the
others — a dark replica is a finding, not a deadlock. Stdlib only, like
the rest of obs/.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request

from service_account_auth_improvements_tpu.controlplane.metrics import (
    Counter,
    Gauge,
    Registry,
    counter_delta,
    merge_bucket_counts,
)
from service_account_auth_improvements_tpu.controlplane.obs.slo import (
    DEFAULT_OBJECTIVES,
    attainment_from_counts,
    burn_rate,
)

log = logging.getLogger(__name__)

#: the SLO series the fleet merges (declared by obs/slo.py SloEngine)
SLO_HIST_FAMILY = "slo_sample_duration_seconds"
SLO_SAMPLES_FAMILY = "slo_samples_total"
SLO_VIOLATIONS_FAMILY = "slo_violations_total"

#: the per-replica saturation gauges rolled up into the autoscaler
#: signal (declared by engine/metrics.py)
DEPTH_FAMILY = "workqueue_depth_per_worker"
BUSY_FAMILY = "controller_runtime_worker_busy_ratio"


# --------------------------------------------------- exposition parsing

def _unescape(value: str) -> str:
    out = []
    i = 0
    while i < len(value):
        c = value[i]
        if c == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_label_body(body: str) -> tuple:
    """``a="x",b="y"`` → (("a", "x"), ("b", "y")); honors escapes."""
    labels = []
    i = 0
    n = len(body)
    while i < n:
        eq = body.index("=", i)
        name = body[i:eq].strip().lstrip(",").strip()
        if body[eq + 1] != '"':
            raise ValueError(f"unquoted label value at {eq}")
        j = eq + 2
        buf = []
        while j < n and body[j] != '"':
            if body[j] == "\\" and j + 1 < n:
                buf.append(body[j:j + 2])
                j += 2
            else:
                buf.append(body[j])
                j += 1
        if j >= n:
            raise ValueError("unterminated label value")
        labels.append((name, _unescape("".join(buf))))
        i = j + 1
    return tuple(labels)


def parse_exposition(text: str) -> dict:
    """Prometheus text format → ``{family: {"type": kind, "samples":
    {(sample_name, labels): value}}}``. ``labels`` is a tuple of
    ``(name, value)`` pairs in exposition order with ``le``/``quantile``
    included — the merge keys on it. Unparseable lines are counted into
    the special ``""`` family's ``parse_errors`` (a corrupt series must
    not void the whole scrape)."""
    families: dict = {}
    types: dict = {}
    errors = 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        try:
            brace = line.find("{")
            space = line.find(" ")
            if brace != -1 and (space == -1 or brace < space):
                name = line[:brace]
                # closing brace: scan past quoted label values
                j = brace + 1
                in_q = False
                while j < len(line):
                    c = line[j]
                    if c == "\\" and in_q:
                        j += 2
                        continue
                    if c == '"':
                        in_q = not in_q
                    elif c == "}" and not in_q:
                        break
                    j += 1
                labels = _parse_label_body(line[brace + 1:j])
                rest = line[j + 1:]
            else:
                name = line[:space] if space != -1 else line
                labels = ()
                rest = line[space + 1:] if space != -1 else ""
            value = float(rest.split()[0])
        except (ValueError, IndexError):
            errors += 1
            continue
        fam = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in types:
                fam = name[:-len(suffix)]
                break
        entry = families.setdefault(
            fam, {"type": types.get(fam, "untyped"), "samples": {}}
        )
        entry["type"] = types.get(fam, entry["type"])
        entry["samples"][(name, labels)] = value
    if errors:
        families.setdefault("", {"type": "untyped", "samples": {}})[
            "parse_errors"] = errors
    return families


def _is_cumulative(family: str, sample_name: str, kind: str) -> bool:
    """Counters accumulate across scrapes; so do histogram bucket/sum/
    count series (cumulative by definition). Everything else is a gauge
    snapshot."""
    if kind == "counter":
        return True
    if kind == "histogram" and sample_name != family:
        return True
    return False


# ------------------------------------------------------ trace stitching

def _merge_intervals(intervals: list) -> list:
    out: list = []
    for start, end in sorted(intervals):
        if out and start <= out[-1][1]:
            out[-1][1] = max(out[-1][1], end)
        else:
            out.append([start, end])
    return out


#: inter-span gaps at or below this are bridged when computing
#: attributed coverage: a GIL/scheduler pause between dequeue and
#: span-open is measurement jitter, not structural dark time — the
#: windows attribution exists to expose (handoffs, missing subsystems,
#: dark replicas) are orders of magnitude larger
GAP_TOLERANCE_S = 0.01


def stitch_traces(payloads: dict,
                  gap_tolerance_s: float = GAP_TOLERANCE_S) -> list[dict]:
    """Merge per-replica tracez payloads (``{"mono": anchor, "wall":
    anchor, "traces": [snapshots]}``) into fleet-wide traces.

    Spans are rebased to wall-clock time (``t - mono_anchor +
    wall_anchor``) — monotonic clocks are not comparable across
    processes — then grouped by trace id. Where consecutive replica
    segments of one trace leave a dark window (the loser drained, the
    gainer had not yet activated), a synthetic ``shard.handoff_gap``
    span covers it, so a handed-off key renders as ONE lifecycle whose
    handoff cost is a visible stage. Per-trace ``attributed_fraction``
    is the interval-union of all spans (synthetic included) over the
    trace's wall duration."""
    grouped: dict = {}
    for replica in sorted(payloads):
        payload = payloads[replica] or {}
        offset = float(payload.get("wall", 0.0)) - \
            float(payload.get("mono", 0.0))
        for snap in payload.get("traces") or []:
            tid = snap.get("trace_id")
            if not tid:
                continue
            g = grouped.setdefault(tid, {"key": None, "spans": [],
                                         "replicas": set(),
                                         "errors": 0, "dropped": 0})
            if g["key"] is None and snap.get("key"):
                g["key"] = snap["key"]
            g["errors"] += snap.get("errors") or 0
            g["dropped"] += snap.get("dropped_spans") or 0
            g["replicas"].add(replica)
            for s in snap.get("spans") or []:
                start = s.get("start")
                if start is None:
                    continue
                end = s.get("end")
                g["spans"].append({
                    "name": s.get("name"),
                    "span_id": s.get("span_id"),
                    "parent_id": s.get("parent_id"),
                    "replica": replica,
                    "start": start + offset,
                    "end": None if end is None else end + offset,
                    "attrs": dict(s.get("attrs") or {}),
                    "error": bool(s.get("error")),
                })
    out = []
    for tid, g in grouped.items():
        spans = g["spans"]
        done = [s for s in spans if s["end"] is not None]
        if not spans:
            continue
        # per-replica extents, ordered by first activity — the handoff
        # sequence; gaps BETWEEN consecutive segments are the protocol's
        # dark windows
        extents = {}
        for s in done:
            lo, hi = extents.get(s["replica"], (s["start"], s["end"]))
            extents[s["replica"]] = (min(lo, s["start"]),
                                     max(hi, s["end"]))
        ordered = sorted(extents.items(), key=lambda kv: kv[1][0])
        gaps = []
        for (prev_r, (_, prev_end)), (next_r, (next_start, _)) in zip(
                ordered, ordered[1:]):
            if next_start > prev_end:
                gaps.append({
                    "name": "shard.handoff_gap",
                    "span_id": f"gap-{prev_r}-{next_r}",
                    "parent_id": None,
                    "replica": next_r,
                    "start": prev_end,
                    "end": next_start,
                    "attrs": {"from": prev_r, "to": next_r,
                              "synthetic": True},
                    "error": False,
                })
        spans = sorted(spans + gaps, key=lambda s: s["start"])
        starts = [s["start"] for s in spans]
        ends = [s["end"] for s in spans if s["end"] is not None]
        start = min(starts)
        duration = (max(ends) - start) if ends else 0.0
        covered = 0.0
        prev_end = None
        for lo, hi in _merge_intervals(
                [[s["start"], s["end"]] for s in spans
                 if s["end"] is not None]):
            covered += hi - lo
            if prev_end is not None and lo - prev_end <= gap_tolerance_s:
                covered += lo - prev_end
            prev_end = hi
        stages: dict = {}
        for s in spans:
            if s["end"] is not None:
                stages[s["name"]] = stages.get(s["name"], 0.0) + \
                    (s["end"] - s["start"])
        out.append({
            "trace_id": tid,
            "key": g["key"],
            "replicas": sorted(g["replicas"]),
            "start": start,
            "duration_s": duration,
            "spans": spans,
            "stages": stages,
            "errors": g["errors"],
            "dropped_spans": g["dropped"],
            "handoff_gaps": len(gaps),
            "covered_s": round(min(covered, duration), 6),
            "attributed_fraction": (
                round(min(covered / duration, 1.0), 4)
                if duration > 0 else 1.0
            ),
        })
    out.sort(key=lambda t: -t["duration_s"])
    return out


# ------------------------------------------------------------ discovery

def lease_replicas_fn(kube, group: str = "cpshard",
                      namespace: str = "kubeflow",
                      default_lease_duration: float = 15.0,
                      now_fn=None):
    """``replicas_fn`` for :class:`FleetAggregator`: live cpshard member
    Leases that advertise an ops URL → ``{identity: url}``. Membership
    freshness uses the protocol's own ``_lease_live`` rule, so the
    scrape set and the shard coordinator can never disagree about who is
    alive. A live member without the annotation (an old binary mid
    rolling upgrade) is simply not scrapable yet — skipped, not fatal."""

    def replicas() -> dict:
        # engine.shard imported lazily: engine imports obs at module
        # load, so a top-level obs.fleet → engine.shard import would
        # cycle; discovery is the only place fleet needs it
        from service_account_auth_improvements_tpu.controlplane.engine import (  # noqa: E501
            shard as shard_mod,
        )
        now = now_fn() if now_fn is not None else shard_mod._now()
        try:
            items = kube.list(
                "leases", namespace=namespace,
                group=shard_mod.LEASE_GROUP,
                label_selector=(f"{shard_mod.LABEL_GROUP}={group},"
                                f"{shard_mod.LABEL_ROLE}=member"),
            )["items"]
        except Exception:  # noqa: BLE001 — discovery outage ≠ crash
            return {}
        out = {}
        for lease in items:
            if not shard_mod._lease_live(lease, now,
                                         default_lease_duration):
                continue
            ann = ((lease.get("metadata") or {})
                   .get("annotations") or {})
            url = ann.get(shard_mod.ANN_OPS)
            if url:
                out[lease["spec"]["holderIdentity"]] = url
        return out

    return replicas


def _http_fetch(url: str, timeout: float = 2.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


# ----------------------------------------------------------- aggregator

class FleetAggregator:
    """Scrape → merge → stitch → alert, one cadence.

    ``replicas_fn() -> {name: base_url}`` names the scrape targets (the
    Lease-discovery default in production, an injected table in tests);
    ``fetch_fn(url) -> str`` performs one HTTP GET (injected in tests —
    the merge/stitch semantics are testable without sockets).
    ``scrape_once()`` is the whole pipeline; ``start()`` runs it on a
    period, skipping ticks while ``is_coordinator`` says another replica
    owns the aggregation (every replica carries the code; the
    coordinator lease elects the one that runs it)."""

    def __init__(self, replicas_fn, *, fetch_fn=None,
                 registry: Registry | None = None,
                 objectives=None, alerts=None,
                 is_coordinator=None, journal=None,
                 period_s: float = 5.0, timeout_s: float = 2.0,
                 mono_fn=None, wall_fn=None):
        self.replicas_fn = replicas_fn
        self.fetch_fn = fetch_fn if fetch_fn is not None else (
            lambda url: _http_fetch(url, timeout_s))
        self.objectives = tuple(objectives or DEFAULT_OBJECTIVES)
        self.alerts = alerts
        self.journal = journal
        self.period_s = period_s
        self._is_coordinator = is_coordinator
        self._mono = mono_fn if mono_fn is not None else time.monotonic
        self._wall = wall_fn if wall_fn is not None else time.time
        self._lock = threading.Lock()
        #: (replica, sample_name, labels) -> [last_raw, accumulated]
        self._acc: dict = {}
        #: replica -> {(sample_name, labels): value} (gauge snapshots)
        self._gauges: dict = {}
        #: replica -> latest tracez payload / slostatus body
        self._tracez: dict = {}
        self._slostatus: dict = {}
        #: replica -> {"url", "up", "error", "scrape_ms",
        #:             "last_ok_mono"}
        self._replicas: dict = {}
        self._snapshot: dict | None = None
        self._merge_errors = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

        reg = registry if registry is not None else Registry()
        self.registry = reg
        self.g_up = Gauge(
            "fleet_replica_up",
            "1 when the replica's last ops scrape succeeded",
            ("replica",), registry=reg)
        self.c_scrape_errors = Counter(
            "fleet_scrape_errors_total",
            "failed replica ops scrapes",
            ("replica",), registry=reg)
        # THE autoscaler input signal (docs/observability.md "Fleet"):
        # per-replica saturation plus the replica="fleet" max roll-up —
        # scale on the hottest replica, not the average (sharding means
        # one replica can saturate while the fleet mean looks idle)
        self.g_depth = Gauge(
            "fleet_workqueue_depth_per_worker",
            "per-replica max workqueue depth per worker; "
            "replica=fleet is the max roll-up the autoscaler consumes",
            ("replica",), registry=reg)
        self.g_busy = Gauge(
            "fleet_worker_busy_ratio",
            "per-replica max reconcile-worker busy ratio; "
            "replica=fleet is the max roll-up the autoscaler consumes",
            ("replica",), registry=reg)
        self.g_att = Gauge(
            "fleet_slo_attainment",
            "fleet-merged SLO attainment per objective",
            ("objective",), registry=reg)
        self.g_burn = Gauge(
            "fleet_slo_error_budget_burn",
            "fleet-merged error-budget burn per objective",
            ("objective",), registry=reg)

    # ------------------------------------------------------------ control

    def is_coordinator(self) -> bool:
        fn = self._is_coordinator
        return True if fn is None else bool(fn())

    def start(self) -> "FleetAggregator":
        self._thread = threading.Thread(
            target=self._loop, name="cpfleet-scrape", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                if self.is_coordinator():
                    self.scrape_once()
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("cpfleet scrape failed")
            self._stop.wait(self.period_s)

    # ------------------------------------------------------------- scrape

    def _scrape_replica(self, name: str, url: str) -> str | None:
        """One replica's three surfaces; returns an error string or
        None. Partial success still ingests what answered — a replica
        with a broken tracez route keeps contributing metrics."""
        base = url.rstrip("/")
        error = None
        try:
            families = parse_exposition(self.fetch_fn(base + "/metrics"))
            self._ingest_metrics(name, families)
        except Exception as e:  # noqa: BLE001 — degrade, don't block
            error = f"/metrics: {e!r}"
        try:
            self._slostatus[name] = json.loads(
                self.fetch_fn(base + "/slostatus"))
        except Exception as e:  # noqa: BLE001
            error = error or f"/slostatus: {e!r}"
        try:
            self._tracez[name] = json.loads(
                self.fetch_fn(base + "/debug/tracez?format=json"))
        except Exception as e:  # noqa: BLE001
            error = error or f"/tracez: {e!r}"
        return error

    def _ingest_metrics(self, replica: str, families: dict) -> None:
        gauges: dict = {}
        for family, entry in families.items():
            kind = entry.get("type", "untyped")
            for (name, labels), value in entry["samples"].items():
                if _is_cumulative(family, name, kind):
                    key = (replica, name, labels)
                    ent = self._acc.get(key)
                    if ent is None:
                        self._acc[key] = [value, value]
                    else:
                        ent[1] += counter_delta(ent[0], value)
                        ent[0] = value
                else:
                    gauges[(name, labels)] = value
        self._gauges[replica] = gauges

    def scrape_once(self) -> dict:
        """One full pass: scrape every discovered replica, merge, stitch,
        evaluate alerts, refresh gauges, publish the snapshot that
        /debug/fleetz renders. Never raises for a dark replica — it is
        reported, not fatal."""
        now = self._mono()
        targets = dict(self.replicas_fn() or {})
        with self._lock:
            for name, url in targets.items():
                t0 = self._mono()
                error = self._scrape_replica(name, url)
                info = self._replicas.setdefault(
                    name, {"url": url, "last_ok_mono": None})
                info["url"] = url
                info["scrape_ms"] = round((self._mono() - t0) * 1000, 3)
                info["error"] = error
                info["up"] = error is None
                if error is None:
                    info["last_ok_mono"] = self._mono()
                else:
                    self.c_scrape_errors.labels(name).inc()
                self.g_up.labels(name).set(0.0 if error else 1.0)
            # replicas that left the membership: their accumulated
            # counters stay (their work happened), their liveness reads
            # 0 — distinguish "left" from "dark" in the snapshot
            for name in list(self._replicas):
                if name not in targets:
                    self._replicas[name]["up"] = False
                    self._replicas[name]["error"] = "left membership"
                    self.g_up.labels(name).set(0.0)
            snapshot = self._build_snapshot_locked(now, targets)
            self._snapshot = snapshot
        # alerts fed OUTSIDE the lock: the engine journals/emits Events
        # on transitions and telemetry fan-out must not extend the
        # scrape critical section
        if self.alerts is not None:
            for name, row in snapshot["slo"].items():
                self.alerts.observe(name, row["samples_total"],
                                    row["violations_total"], now=now)
                for rule in self.alerts.status()["rules"]:
                    if rule["objective"] == name:
                        row.setdefault("alerts", []).append(rule)
            snapshot["alerts"] = self.alerts.status()
        return snapshot

    # -------------------------------------------------------------- merge

    def _merged_counters_locked(self) -> dict:
        merged: dict = {}
        for (_replica, name, labels), (_last, acc) in self._acc.items():
            key = (name, labels)
            merged[key] = merged.get(key, 0.0) + acc
        return merged

    def _merged_hist_locked(self, family: str,
                            match: dict) -> tuple | None:
        """(bounds, cumulative counts) of one histogram family merged
        across replicas via metrics.merge_bucket_counts; None without
        samples. Replicas whose bucket layout disagrees are skipped and
        counted as merge errors — mixing layouts would silently
        mis-attribute tail latency."""
        per_replica: dict = {}
        for (replica, name, labels), (_last, acc) in self._acc.items():
            if name != f"{family}_bucket":
                continue
            ld = dict(labels)
            if any(ld.get(k) != v for k, v in match.items()):
                continue
            per_replica.setdefault(replica, {})[ld.get("le")] = acc
        bounds = None
        merged: list | None = None
        for _replica, les in sorted(per_replica.items()):
            try:
                finite = sorted((float(le), le) for le in les
                                if le not in (None, "+Inf"))
            except ValueError:
                self._merge_errors += 1
                continue
            b = tuple(x[0] for x in finite)
            counts = [les[le] for _, le in finite] + \
                [les.get("+Inf", 0.0)]
            if merged is None:
                bounds, merged = b, counts
            elif b != bounds:
                self._merge_errors += 1
            else:
                merge_bucket_counts(merged, counts)
        if merged is None:
            return None
        return bounds, merged

    def _build_snapshot_locked(self, now: float, targets: dict) -> dict:
        merged = self._merged_counters_locked()
        # fleet SLO rows: bucket-merged attainment + counter totals
        slo: dict = {}
        for obj in self.objectives:
            hist = self._merged_hist_locked(
                SLO_HIST_FAMILY, {"objective": obj.name})
            att = None
            if hist is not None:
                att = attainment_from_counts(
                    hist[0], hist[1], obj.target_ms / 1000.0)
            burn = burn_rate(att, obj.objective)
            samples = merged.get(
                (SLO_SAMPLES_FAMILY, (("objective", obj.name),)), 0.0)
            violations = merged.get(
                (SLO_VIOLATIONS_FAMILY, (("objective", obj.name),)), 0.0)
            slo[obj.name] = {
                "target_ms": obj.target_ms,
                "objective": obj.objective,
                "n": int(samples),
                "samples_total": samples,
                "violations_total": violations,
                "attainment": None if att is None else round(att, 4),
                "burn": (None if burn is None
                         else "inf" if burn == float("inf")
                         else round(burn, 4)),
                "met": att is not None and att >= obj.objective,
            }
            self.g_att.labels(obj.name).set(att if att is not None
                                            else 0.0)
            if burn is not None and burn != float("inf"):
                self.g_burn.labels(obj.name).set(burn)
        # saturation roll-up: per-replica max over label sets, fleet max
        fleet_depth = fleet_busy = 0.0
        saturation: dict = {}
        for replica in sorted(targets):
            gauges = self._gauges.get(replica) or {}
            depth = max((v for (n, _l), v in gauges.items()
                         if n == DEPTH_FAMILY), default=0.0)
            busy = max((v for (n, _l), v in gauges.items()
                        if n == BUSY_FAMILY), default=0.0)
            saturation[replica] = {"queue_depth_per_worker": depth,
                                   "busy_ratio": round(busy, 4)}
            self.g_depth.labels(replica).set(depth)
            self.g_busy.labels(replica).set(busy)
            fleet_depth = max(fleet_depth, depth)
            fleet_busy = max(fleet_busy, busy)
        self.g_depth.labels("fleet").set(fleet_depth)
        self.g_busy.labels("fleet").set(fleet_busy)
        traces = stitch_traces(self._tracez)
        multi = [t for t in traces if len(t["replicas"]) > 1]
        graded = [t for t in traces if t["key"] and t["duration_s"] > 0]
        attributed = [t["attributed_fraction"] for t in graded]
        graded_dur = sum(t["duration_s"] for t in graded)
        # PARTIAL means a CURRENT member is dark (scraped and failed) —
        # a gracefully departed replica is a departure, not a hole in
        # the view (its accumulated counters and last traces remain)
        dark = sorted(n for n in targets
                      if not self._replicas.get(n, {}).get("up"))
        replicas = {
            name: {k: info.get(k) for k in
                   ("url", "up", "error", "scrape_ms", "last_ok_mono")}
            for name, info in self._replicas.items()
        }
        for name, sat in saturation.items():
            replicas.setdefault(name, {}).update(sat)
        return {
            "schema": "fleetz/v1",
            "at_mono": now,
            "at_wall": self._wall(),
            "replicas": replicas,
            "partial": bool(dark),
            "dark": dark,
            "merge_errors": self._merge_errors,
            "slo": slo,
            "saturation": {"fleet": {
                "queue_depth_per_worker": fleet_depth,
                "busy_ratio": round(fleet_busy, 4),
            }},
            "traces": traces[:50],
            "trace_count": len(traces),
            "stitched_multi_replica": len(multi),
            "handoff_gap_spans": sum(t["handoff_gaps"] for t in traces),
            "attributed_fraction": {
                "n": len(attributed),
                "min": round(min(attributed), 4) if attributed else None,
                "mean": (round(sum(attributed) / len(attributed), 4)
                         if attributed else None),
                # duration-weighted: the fraction of total stitched
                # lifecycle TIME that is attributed — the gated number;
                # a per-trace min would grade micro-traces where one
                # scheduler slice is half the lifecycle
                "weighted": (
                    round(min(sum(t["covered_s"] for t in graded)
                              / graded_dur, 1.0), 4)
                    if graded_dur > 0 else None),
            },
            "alerts": (self.alerts.status()
                       if self.alerts is not None else None),
        }

    def snapshot(self) -> dict:
        """Latest scrape result, scraping once if none exists yet (the
        serve path's lazy first render)."""
        with self._lock:
            snap = self._snapshot
        if snap is None:
            snap = self.scrape_once()
        return snap


# ------------------------------------------------------------ rendering

def _fmt_span_attrs(attrs: dict) -> str:
    if not attrs:
        return ""
    inner = ", ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
    return " {" + inner + "}"


def render_stitched_trace(trace: dict) -> str:
    head = (
        f"TRACE {trace['key'] or '(anonymous)'} "
        f"id={trace['trace_id']} "
        f"replicas={'+'.join(trace['replicas'])} "
        f"duration={trace['duration_s'] * 1000:.1f}ms "
        f"spans={len(trace['spans'])} "
        f"handoff_gaps={trace['handoff_gaps']} "
        f"attributed={trace['attributed_fraction']:.0%} "
        f"errors={trace['errors']}"
    )
    lines = [head]
    stages = sorted(trace["stages"].items(), key=lambda kv: -kv[1])
    if stages:
        lines.append("  stages: " + "  ".join(
            f"{name}={dur * 1000:.1f}ms" for name, dur in stages))
    for s in trace["spans"]:
        offset = (s["start"] - trace["start"]) * 1000
        dur = ((s["end"] - s["start"]) * 1000
               if s["end"] is not None else float("nan"))
        attrs = dict(s["attrs"])
        attrs["replica"] = s["replica"]
        lines.append(
            f"  +{offset:9.1f}ms {dur:9.1f}ms "
            f"{s['name']}{' ERROR' if s['error'] else ''}"
            f"{_fmt_span_attrs(attrs)}"
        )
    return "\n".join(lines)


def render_fleetz(snapshot: dict, limit: int = 10) -> str:
    """The /debug/fleetz page: fleet SLO rows, per-replica saturation,
    slowest stitched traces — with the partial-fleet state impossible to
    miss."""
    replicas = snapshot.get("replicas") or {}
    up = sum(1 for r in replicas.values() if r.get("up"))
    lines = [
        f"cpfleet: {len(replicas)} replica(s), {up} up, "
        f"{snapshot.get('trace_count', 0)} stitched trace(s) "
        f"({snapshot.get('stitched_multi_replica', 0)} multi-replica, "
        f"{snapshot.get('handoff_gap_spans', 0)} handoff gap(s))"
    ]
    if snapshot.get("partial"):
        dark = ", ".join(snapshot.get("dark") or [])
        lines.append(
            f"PARTIAL FLEET: no data from [{dark}] — every row below "
            "understates the fleet; fix the dark replicas first"
        )
    if snapshot.get("merge_errors"):
        lines.append(f"merge errors: {snapshot['merge_errors']} "
                     "(mismatched histogram bucket layouts skipped)")
    alerts = snapshot.get("alerts") or {}
    firing = [r for r in alerts.get("rules") or []
              if r["state"] == "firing"]
    for r in firing:
        lines.append(
            f"ALERT FIRING [{r['severity']}] {r['objective']}: burn "
            f"short={r['burn_short']} long={r['burn_long']} "
            f">= {r['threshold']}x for {r['for_s']}s (/alertz)"
        )
    lines.append("")
    lines.append("-- fleet SLO (bucket-merged across replicas) --")
    lines.append(f"{'objective':<20} {'attainment':>10} {'burn':>8} "
                 f"{'n':>8}  met")
    for name in sorted(snapshot.get("slo") or {}):
        row = snapshot["slo"][name]
        att = row["attainment"]
        lines.append(
            f"{name:<20} "
            f"{('n/a' if att is None else f'{att:.4f}'):>10} "
            f"{str(row['burn'] if row['burn'] is not None else 'n/a'):>8} "
            f"{row['n']:>8}  {'yes' if row['met'] else 'NO'}"
        )
    lines.append("")
    lines.append("-- per-replica saturation (the autoscaler signal: "
                 "fleet_workqueue_depth_per_worker / "
                 "fleet_worker_busy_ratio) --")
    lines.append(f"{'replica':<24} {'up':>3} {'depth/worker':>13} "
                 f"{'busy':>6} {'scrape_ms':>10}  error")
    for name in sorted(replicas):
        r = replicas[name]
        lines.append(
            f"{name:<24} {('y' if r.get('up') else 'N'):>3} "
            f"{r.get('queue_depth_per_worker', 0.0):>13.2f} "
            f"{r.get('busy_ratio', 0.0):>6.2f} "
            f"{(r.get('scrape_ms') if r.get('scrape_ms') is not None else float('nan')):>10.1f}"  # noqa: E501
            f"  {r.get('error') or ''}"
        )
    sat = (snapshot.get("saturation") or {}).get("fleet") or {}
    lines.append(
        f"{'fleet (max roll-up)':<24} {'':>3} "
        f"{sat.get('queue_depth_per_worker', 0.0):>13.2f} "
        f"{sat.get('busy_ratio', 0.0):>6.2f}"
    )
    lines.append("")
    traces = snapshot.get("traces") or []
    lines.append(f"-- slowest stitched traces (top {limit} of "
                 f"{snapshot.get('trace_count', 0)}) --")
    for t in traces[:limit]:
        lines.append(render_stitched_trace(t))
        lines.append("")
    return "\n".join(lines).rstrip() + "\n"
