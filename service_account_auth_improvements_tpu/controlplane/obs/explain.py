"""cpscope explain engine: "why isn't notebook X Ready", answered.

Stitches the four evidence sources the stack already produces into ONE
causal, time-ordered timeline per notebook:

- **conditions** on the CR (Scheduled/SliceIncomplete/GangScheduled/...)
  — the level state;
- **Events** involving the CR (obs/events.py recorder + kubelet
  re-emissions) — the discrete history, with counts;
- **spans** from the object's trace (obs/trace.py) — where the time
  went;
- **journal entries** (obs/journal.py) — the decisions, including
  ambient ones with no per-object key: chaos injections and lease
  transitions that overlap the object's lifetime explain stalls nothing
  object-scoped can (a recovered notebook's timeline must name the
  blackout, not a generic timeout).

Surfaces: ``/debug/explainz/<ns>/<name>`` on every ops port
(engine/serve.py, operator view, plain text) and the SAR-gated dashboard
``GET /api/explain/<ns>/<notebook>`` (tenant view, JSON) — the latter
through :func:`redact` with the same tenant boundary as the traces API:
no cluster-wide chip counts or queue depths, no cross-namespace victim
names (those are redacted at record time by the scheduler; redact()
drops the cluster-scoped attrs).

Monotonic stamps are projected onto the wall clock with one offset
captured at explain time — exact enough for a single process, which is
where every source lives.
"""

from __future__ import annotations

import copy
import datetime
import time

from service_account_auth_improvements_tpu.controlplane.kube import errors
from service_account_auth_improvements_tpu.controlplane.obs import journal as journal_mod  # noqa: E501
from service_account_auth_improvements_tpu.controlplane.obs.trace import (
    TRACER,
    object_key,
)

#: journal kinds with no per-object key that still belong on every
#: overlapping timeline — cluster-level causes of object-level symptoms
#: (shard: election + handoff windows — a key that stalled because its
#: shard was mid-handoff needs the map epoch named, not a generic wait)
AMBIENT_KINDS = ("chaos", "lease", "shard")

#: span names that carry explanatory weight (the reconcile firehose is
#: summarized, not listed — except failures, which are always evidence)
TIMELINE_SPANS = {
    "apiserver.create", "sched.admit", "sched.queue_wait", "sched.place",
    "sched.preempt", "sched.park", "sched.resume", "notebook.children",
    "notebook.gang", "notebook.ready", "kubelet.actuation",
}

#: attrs that never cross the tenant boundary (same contract as the
#: dashboard traces API): cluster-wide occupancy is operator-only —
#: including the learned-placement evidence (per-pool scores and the
#: feasibility mask reconstruct the whole cluster's free-chip map)
CLUSTER_ATTRS = ("free_chips", "total_chips", "feasible", "scores",
                 "queue_depth")


def _parse_wall(raw) -> float | None:
    """K8s timestamp string -> epoch seconds, else None."""
    if not raw:
        return None
    for fmt in ("%Y-%m-%dT%H:%M:%SZ", "%Y-%m-%dT%H:%M:%S.%fZ"):
        try:
            return datetime.datetime.strptime(raw, fmt).replace(
                tzinfo=datetime.timezone.utc).timestamp()
        except (ValueError, TypeError):
            continue
    return None


def _iso(epoch: float | None) -> str | None:
    if epoch is None:
        return None
    return datetime.datetime.fromtimestamp(
        epoch, datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%fZ")


class ExplainSources:
    """Pre-fetched, pre-indexed Event + journal sources for BATCH
    explains: cpbench explains every object a scenario drove, and
    re-LISTing the namespace's Events plus re-snapshotting the whole
    journal ring per object is O(objects x (events + ring)) for
    identical data. One LIST per namespace, one ring snapshot, indexed
    by involved name / object key."""

    def __init__(self, kube=None, journal=None,
                 namespaces: tuple = ()):
        jnl = journal if journal is not None else \
            journal_mod.current_journal()
        self._events: dict[tuple, list] = {}
        #: Events listed across the given namespaces (cpbench's
        #: event_count comes from here — no extra LIST)
        self.total_events = 0
        self.events_ok = kube is not None
        if kube is not None:
            for ns in namespaces:
                try:
                    listed = kube.list("events", namespace=ns)["items"]
                except errors.ApiError:
                    self.events_ok = False
                    continue
                self.total_events += len(listed)
                for ev in listed:
                    inv = ev.get("involvedObject") or {}
                    self._events.setdefault(
                        (ns, inv.get("name")), []).append(ev)
        snap = jnl.entries()
        self._journal: dict[str, list] = {}
        self.ambient: list = []
        for e in snap:
            if e.get("key") is not None:
                self._journal.setdefault(e["key"], []).append(e)
            elif e["kind"] in AMBIENT_KINDS:
                self.ambient.append(e)

    def events_for(self, namespace: str | None, name: str) -> list:
        return self._events.get((namespace, name), [])

    def journal_for(self, key: str) -> list:
        return self._journal.get(key, [])


def explain(namespace: str | None, name: str, *, kube=None, tracer=None,
            journal=None, plural: str = "notebooks",
            group: str | None = "tpukf.dev",
            prefetched: "ExplainSources | None" = None) -> dict:
    """Build the explain record for one object. Every source is
    optional — the engine reports what it can see, and says what it
    couldn't (an explainer that silently omits a dead source would turn
    'no data' into 'no problem'). Batch callers (cpbench explains every
    object of a scenario) pass ``prefetched`` (:class:`ExplainSources`)
    so N explains cost one Event LIST and one journal snapshot instead
    of N of each."""
    trc = tracer if tracer is not None else TRACER
    jnl = journal if journal is not None else journal_mod.current_journal()
    key = object_key(plural, namespace, name)
    # one offset projects monotonic stamps onto the wall clock
    mono_to_wall = time.time() - time.monotonic()
    items: list[dict] = []
    sources: dict[str, bool] = {}

    obj = None
    if kube is not None:
        try:
            obj = kube.get(plural, name, namespace=namespace, group=group)
            sources["object"] = True
        except errors.NotFound:
            sources["object"] = False
        except errors.ApiError:
            sources["object"] = False
    for cond in ((obj or {}).get("status") or {}).get("conditions") or []:
        wall = _parse_wall(cond.get("lastTransitionTime")
                           or cond.get("lastProbeTime"))
        what = f"condition {cond.get('type')}={cond.get('status', '?')}"
        if cond.get("reason"):
            what += f" {cond['reason']}"
        if cond.get("message"):
            what += f": {cond['message']}"
        items.append({"wall": wall, "source": "condition", "what": what,
                      "attrs": {k: cond[k] for k in
                                ("queuePosition", "queueTotal")
                                if k in cond}})

    events = None
    if prefetched is not None:
        events = prefetched.events_for(namespace, name)
        sources["events"] = prefetched.events_ok
    elif kube is not None and namespace:
        try:
            events = kube.list("events", namespace=namespace)["items"]
            sources["events"] = True
        except errors.ApiError:
            events, sources["events"] = [], False
    if events is not None:
        for ev in events:
            inv = ev.get("involvedObject") or {}
            if inv.get("name") != name:
                continue
            wall = _parse_wall(ev.get("lastTimestamp")
                               or ev.get("firstTimestamp"))
            count = int(ev.get("count") or 1)
            what = (f"event {ev.get('type', 'Normal')}/"
                    f"{ev.get('reason', '?')}"
                    + (f" x{count}" if count > 1 else "")
                    + f": {ev.get('message', '')}")
            items.append({"wall": wall, "source": "event", "what": what,
                          "attrs": {"reason": ev.get("reason"),
                                    "count": count}})

    snap = trc.snapshot(key=key)
    sources["trace"] = snap is not None
    window_lo = None
    if snap is not None:
        reconciles = errors_n = 0
        for s in snap["spans"]:
            start = s["start"] + mono_to_wall
            window_lo = start if window_lo is None else min(window_lo, start)
            if s["name"] == "reconcile":
                reconciles += 1
                errors_n += bool(s["error"])
                if not s["error"]:
                    continue  # the firehose is summarized below
            if s["name"] not in TIMELINE_SPANS and not s["error"]:
                continue
            dur = ((s["end"] - s["start"]) * 1000.0
                   if s["end"] is not None else None)
            what = f"span {s['name']}"
            if dur is not None:
                what += f" ({dur:.1f}ms)"
            if s["error"]:
                what += (" ERROR "
                         + str(s["attrs"].get("error.message", "")))
            items.append({"wall": start, "source": "span", "what": what,
                          "attrs": dict(s["attrs"])})
        if reconciles:
            items.append({
                "wall": window_lo, "source": "span",
                "what": f"reconciles: {reconciles} total, "
                        f"{errors_n} errored",
                "attrs": {"reconciles": reconciles,
                          "reconcile_errors": errors_n},
            })

    if prefetched is not None:
        entries = prefetched.journal_for(key)
        ambient = prefetched.ambient
    else:
        entries = jnl.entries(key=key)
        ambient = [e for e in jnl.entries(kinds=AMBIENT_KINDS)
                   if e.get("key") is None]
    sources["journal"] = bool(entries or ambient)
    for e in entries:
        if e["kind"] == "reconcile":
            continue  # summarized via the trace above
        wall = _parse_wall(e.get("wall")) or (
            e["mono"] + mono_to_wall if e.get("mono") else None)
        attrs = dict(e["attrs"])
        what = f"decision {e['kind']}"
        detail = attrs.get("pool") or attrs.get("outcome") \
            or attrs.get("reason") or attrs.get("action")
        if detail:
            what += f": {detail}"
        if e["kind"] == "placement" and attrs.get("policy"):
            # which policy decided (and why it fell back) is tenant-safe
            # prose; the score vector / feasibility mask — cluster-wide
            # occupancy — stays in attrs, which redact() strips and
            # render_explain (the operator explainz surface) expands
            # into the evidence trail (docs/scheduler.md)
            what += f" [{attrs['policy']}"
            if attrs.get("fallback"):
                what += f" fallback: {attrs['fallback']}"
            what += "]"
        items.append({"wall": wall, "source": "journal", "what": what,
                      "attrs": attrs})
    lo = window_lo if window_lo is not None else min(
        (i["wall"] for i in items if i["wall"] is not None),
        default=None)
    for e in ambient:
        wall = _parse_wall(e.get("wall")) or (
            e["mono"] + mono_to_wall if e.get("mono") else None)
        if lo is not None and wall is not None and wall < lo - 1.0:
            continue  # before this object's lifetime: not its story
        attrs = dict(e["attrs"])
        action = attrs.get("action", "")
        what = f"{e['kind']}: {action}"
        if action == "blackout_started":
            what = (f"chaos: apiserver blackout began "
                    f"({attrs.get('duration_s', '?')}s window — every "
                    "verb 503, watch channels severed)")
        elif action == "blackout_ended":
            what = "chaos: apiserver blackout ended"
        elif action == "storm_429_started":
            what = (f"chaos: 429 storm began "
                    f"({attrs.get('duration_s', '?')}s window — clients "
                    f"[{attrs.get('clients', '?')}] throttled with "
                    "Retry-After)")
        elif action == "storm_429_ended":
            what = "chaos: 429 storm ended"
        elif e["kind"] == "lease":
            what = (f"lease {action}: {attrs.get('identity', '?')} "
                    f"({attrs.get('detail', '')})").strip()
        elif action == "map_applied":
            what = (f"shard: map epoch {attrs.get('epoch', '?')} "
                    f"published by {attrs.get('coordinator', '?')} "
                    f"({attrs.get('members', '?')} member(s), "
                    f"{attrs.get('moved', '?')} shard(s) moved)")
        elif action == "map_seen":
            what = (f"shard: {attrs.get('identity', '?')} applied epoch "
                    f"{attrs.get('epoch', '?')} "
                    f"(+{attrs.get('gained', 0)}/-{attrs.get('lost', 0)} "
                    "shards)")
        elif action == "handoff_acked":
            what = (f"shard: {attrs.get('identity', '?')} drained and "
                    f"acked epoch {attrs.get('epoch', '?')}")
        elif action == "handoff_gained":
            what = (f"shard: {attrs.get('identity', '?')} activated "
                    f"{attrs.get('shards', '?')} gained shard(s) at "
                    f"epoch {attrs.get('epoch', '?')} (barrier cleared)")
        elif action in ("fenced", "unfenced"):
            what = f"shard: {attrs.get('identity', '?')} {action}"
        items.append({"wall": wall, "source": e["kind"], "what": what,
                      "attrs": attrs})

    items.sort(key=lambda i: (i["wall"] is None, i["wall"] or 0.0))
    for i in items:
        i["wall_iso"] = _iso(i["wall"])

    ready = None
    if obj is not None:
        ready = _is_ready(obj, plural)
    verdict = _verdict(obj, ready, items, sources)
    return {
        "key": key, "namespace": namespace, "name": name,
        "ready": ready, "verdict": verdict, "sources": sources,
        "timeline": items,
    }


def _is_ready(obj: dict, plural: str) -> bool:
    """The controller's own readiness test, not truthiness: a 4-host
    gang with 1/4 hosts up has readyReplicas == 1, and calling that
    'Ready' would report the exact stuck-gang case this engine exists
    to diagnose as healthy. For notebooks the target is the resolved
    gang size (num_hosts x num_slices — notebook.py's want_ready); for
    other plurals, any ready replica counts."""
    have = ((obj.get("status") or {}).get("readyReplicas")) or 0
    want = 1
    if plural == "notebooks":
        try:
            from service_account_auth_improvements_tpu.controlplane import (  # noqa: E501
                tpu,
            )

            resolved = tpu.resolve((obj.get("spec") or {}).get("tpu"))
            if resolved is not None:
                want = resolved.num_hosts * resolved.num_slices
        except Exception:  # noqa: BLE001 — invalid spec: fall back to 1
            pass
    return have >= want


def _verdict(obj, ready, items, sources) -> str:
    if obj is None and not sources.get("trace") \
            and not sources.get("journal"):
        return "unknown object: no CR, no trace, no journal entries"
    if ready:
        return "Ready"
    status = (obj or {}).get("status") or {}
    if status.get("phase") == "Parked":
        # checkpoint-parked (controlplane/parking), NOT stuck: zero
        # chips held, state committed, resume on open. Keyed off the
        # status phase — explain must not import the parking package
        # (obs is imported BY it transitively via the controllers).
        ref = status.get("checkpointRef")
        verdict = "Parked — scale-to-zero"
        if ref:
            verdict += f", checkpoint {ref}"
        for i in reversed(items):
            reason = ((i.get("attrs") or {}).get("park_reason")
                      if i["source"] == "journal" else None)
            if reason:
                verdict += f" (parked: {reason})"
                break
        return verdict + "; resume on open"
    blocking = None
    for cond in ((obj or {}).get("status") or {}).get("conditions") or []:
        if cond.get("type") == "Scheduled" and cond.get("status") == "False":
            blocking = (f"parked by tpusched: {cond.get('reason', '')} "
                        f"{cond.get('message', '')}").strip()
        if cond.get("type") in ("SliceIncomplete",
                                "SlicePlacementConflict") \
                and cond.get("status") == "True":
            blocking = f"{cond['type']}: {cond.get('message', '')}"
        if cond.get("type") == "InvalidTpuSpec" \
                and cond.get("status") == "True":
            blocking = f"invalid TPU spec: {cond.get('message', '')}"
    if blocking:
        return "not Ready — " + blocking
    # ONE reversed scan so RECENCY picks the verdict: a key that moved
    # replicas an hour ago must not outrank the blackout happening now
    for i in reversed(items):
        # per-key shard journal entry (engine/manager.py's worker gate
        # journals the drop when a queued key's shard moved away): the
        # key changed replicas mid-reconcile — the new owner's requeue
        # is responsible now, and the timeline names it
        if i["source"] == "journal" \
                and (i.get("attrs") or {}).get("action") == "moved":
            a = i["attrs"]
            return ("not Ready — key moved replicas mid-reconcile "
                    f"(shard {a.get('shard', '?')} handed from "
                    f"{a.get('identity', '?')} to {a.get('owner', '?')}; "
                    "awaiting the new owner's requeue)")
        # only CHAOS qualifies as a blamable cluster-level cause:
        # shard ambient entries (map epochs, handoff acks) fire on
        # every routine startup/rolling-restart of a sharded plane and
        # would misattribute an ordinary still-reconciling object —
        # they stay in the timeline, but only the per-key "moved"
        # entry above implicates sharding for THIS key
        if i["source"] == "chaos":
            return ("not Ready — most recent cluster-level cause: "
                    + i["what"])
    if obj is None:
        return "object not found (deleted, or explain asked the wrong " \
               "namespace)"
    return "not Ready — no blocking condition recorded; see timeline"


def redact(record: dict) -> dict:
    """Tenant view of an explain record: deep copy with cluster-scoped
    attrs removed from every item (the traces-API redaction contract —
    snapshots are copies, the stored evidence must not change)."""
    out = copy.deepcopy(record)
    for item in out.get("timeline") or []:
        attrs = item.get("attrs") or {}
        for k in CLUSTER_ATTRS:
            attrs.pop(k, None)
    return out


def render_explain(record: dict) -> str:
    """Plain-text rendering for /debug/explainz — curl-friendly, one
    line per timeline item."""
    lines = [
        f"EXPLAIN {record['key']}",
        f"  ready: {record['ready']}",
        f"  verdict: {record['verdict']}",
        "  sources: " + ", ".join(
            f"{k}={'ok' if v else 'absent'}"
            for k, v in sorted(record["sources"].items())),
        "",
    ]
    for item in record["timeline"]:
        ts = item.get("wall_iso") or "????-??-??T??:??:??"
        lines.append(f"  {ts}  [{item['source']:9s}] {item['what']}")
        attrs = item.get("attrs") or {}
        if attrs.get("policy") == "learned" and attrs.get("scores"):
            # the learned decision's evidence trail, operator view
            # only: a record that went through redact() has no scores
            # left here, so nothing tenant-facing can leak through
            # this rendering
            ranked = sorted(attrs["scores"].items(),
                            key=lambda kv: -kv[1])
            lines.append(
                "            scores: " + ", ".join(
                    f"{pool}={score:g}" for pool, score in ranked))
            lines.append(
                "            feasible: ["
                + ", ".join(attrs.get("feasible") or ()) + "]")
    if not record["timeline"]:
        lines.append("  (no recorded history)")
    return "\n".join(lines) + "\n"
