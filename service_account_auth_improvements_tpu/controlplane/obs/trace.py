"""cptrace: dependency-free per-object lifecycle tracing.

The control plane answers "is my notebook up?" but not "where did the
3.5 s time-to-placement go?" — cpbench stopwatches from the outside
while the engine, controllers, and scheduler are internally dark
(NotebookOS, arXiv:2503.20591, makes the case that interactive-notebook
platforms live or die on spawn-latency visibility). This module is the
substrate: spans grouped into per-object traces, kept in a bounded
in-memory ring, surfaced via ``/debug/tracez`` (engine/serve.py), the
dashboard trace API, and cpbench's per-stage attribution.

Design points, all stdlib:

- A **trace** is identified by an *object key* (``notebooks/<ns>/<name>``
  — see :func:`object_key`) plus an opaque trace id. The id is stamped
  on the CR as the ``tpukf.dev/trace-id`` annotation at admission
  (controllers/notebook.py) so out-of-process consumers can correlate;
  in-process lookups go by key.
- **Propagation** rides a contextvar: the engine opens a ``reconcile``
  span around every attempt, and any span opened inside (scheduler
  stages, notebook child creation) parents onto it automatically —
  reconciles run synchronously on worker threads, so context locality
  holds.
- **Retroactive spans** (:meth:`Tracer.record`) cover waits measured
  after the fact: workqueue enqueue→dequeue, admission-queue wait,
  fake-kubelet actuation. They attach to the key's trace directly, no
  context needed — the recorder often runs under a *different* object's
  reconcile (a placement pass places queued peers).
- The ring evicts least-recently-touched traces beyond ``max_traces``
  and caps spans per trace, so a controller that runs for a month holds
  a bounded window of recent lifecycles, never the history.
- **Exporter hook**: every finished span is handed to each callable in
  ``Tracer.exporters`` (off-box shipping, test capture); exporter bugs
  are swallowed — tracing must never take down a reconcile.
"""

from __future__ import annotations

import contextvars
import dataclasses
import threading
import time
import uuid

#: stamped on the CR at admission so any process (or a human with
#: kubectl) can correlate the object with controller-side traces
TRACE_ANNOTATION = "tpukf.dev/trace-id"

#: (tracer, SpanContext, object key) of the innermost open span
_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "tpukf_trace_ctx", default=None
)


def object_key(plural: str, namespace: str | None, name: str) -> str:
    """Canonical trace key for one API object."""
    return f"{plural}/{namespace or ''}/{name}"


@dataclasses.dataclass(frozen=True)
class SpanContext:
    trace_id: str
    span_id: str


class Span:
    """One timed operation. Mutable while open; snapshots into its trace
    at :meth:`finish` (also the ``with`` exit). Exceptions escaping a
    ``with span:`` block are tagged (``error=True``) automatically;
    callers that swallow exceptions themselves tag via
    :meth:`record_error` — either way the span still closes."""

    __slots__ = ("tracer", "name", "trace_id", "span_id", "parent_id",
                 "key", "start", "end", "attrs", "error", "_token",
                 "_finished")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: str | None, key: str | None, attrs: dict):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:8]
        self.parent_id = parent_id
        self.key = key
        self.start = time.monotonic()
        self.end: float | None = None
        self.attrs = attrs
        self.error = False
        self._token = None
        self._finished = False

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def record_error(self, exc: BaseException) -> None:
        self.error = True
        self.attrs["error.type"] = type(exc).__name__
        self.attrs["error.message"] = str(exc)[:200]

    def __enter__(self) -> "Span":
        self._token = _CTX.set(
            (self.tracer, SpanContext(self.trace_id, self.span_id),
             self.key)
        )
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is not None:
            self.record_error(exc)
        self.finish()
        return False

    def finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        if self._token is not None:
            try:
                _CTX.reset(self._token)
            except ValueError:
                pass  # finished from a different context; nothing to pop
            self._token = None
        self.end = time.monotonic()
        self.tracer._finish(self)


class _Trace:
    __slots__ = ("trace_id", "key", "created", "spans", "dropped",
                 "bound", "once")

    def __init__(self, trace_id: str, key: str | None):
        self.trace_id = trace_id
        self.key = key
        self.created = time.monotonic()
        self.spans: list[dict] = []
        self.dropped = 0
        #: True once bind() explicitly assigned this id (annotation/uid)
        self.bound = False
        #: names recorded with once=True — survives ring eviction of the
        #: span itself (a wrapped ring must not re-fire 'notebook.ready'
        #: days later with a fresh timestamp)
        self.once: set[str] = set()


class Tracer:
    def __init__(self, max_traces: int = 1024,
                 max_spans_per_trace: int = 512):
        self.max_traces = max_traces
        self.max_spans_per_trace = max_spans_per_trace
        self._lock = threading.Lock()
        self._traces: dict[str, _Trace] = {}   # insertion = recency order
        self._by_key: dict[str, str] = {}
        #: callables invoked with each finished span dict
        self.exporters: list = []
        #: decision journal attached via ``Journal.attach`` (cpscope):
        #: library code finds it through ``current_tracer().journal`` so
        #: per-world isolation (cpbench) needs no extra threading
        self.journal = None
        #: SLO engine attached via ``SloEngine.attach`` — same pattern
        self.slo = None

    # ------------------------------------------------------------ binding

    def trace_id_for(self, key: str) -> str:
        """The key's trace id, creating the trace on first touch."""
        with self._lock:
            tid = self._by_key.get(key)
            if tid is not None and tid in self._traces:
                return tid
            return self._new_trace_locked(key).trace_id

    def has(self, key: str) -> bool:
        with self._lock:
            return self._by_key.get(key) in self._traces

    def bind(self, key: str, trace_id: str) -> None:
        """Bind ``key`` to an externally-chosen trace id (the
        ``tpukf.dev/trace-id`` annotation, derived from the CR's uid).

        A key whose current trace was only ever auto-created (spans
        recorded before the first reconcile could bind — queue waits,
        the create call) is RENAMED to the new id, keeping those spans:
        same incarnation, just late identification. A key whose trace
        was already explicitly bound to a DIFFERENT id starts fresh —
        that is a deleted-and-recreated object (new uid), and mixing two
        lifecycles under a reused name is exactly what must not happen.
        The old incarnation's trace stays in the ring until evicted."""
        if not trace_id:
            return
        with self._lock:
            cur_id = self._by_key.get(key)
            cur = self._traces.get(cur_id) if cur_id else None
            if cur is not None and cur.trace_id == trace_id:
                cur.bound = True
                return
            if cur is not None and not cur.bound \
                    and trace_id not in self._traces:
                del self._traces[cur.trace_id]
                cur.trace_id = trace_id
                cur.bound = True
                self._traces[trace_id] = cur
                self._by_key[key] = trace_id
                return
            if trace_id not in self._traces:
                self._new_trace_locked(key, trace_id=trace_id)
            self._traces[trace_id].bound = True
            self._by_key[key] = trace_id

    def _new_trace_locked(self, key: str | None,
                          trace_id: str | None = None) -> _Trace:
        tr = _Trace(trace_id or uuid.uuid4().hex[:16], key)
        self._traces[tr.trace_id] = tr
        if key is not None:
            self._by_key[key] = tr.trace_id
        while len(self._traces) > self.max_traces:
            oldest = next(iter(self._traces))
            old = self._traces.pop(oldest)
            if old.key is not None and \
                    self._by_key.get(old.key) == old.trace_id:
                del self._by_key[old.key]
        return tr

    def _touch_locked(self, tid: str) -> _Trace | None:
        tr = self._traces.pop(tid, None)
        if tr is not None:
            self._traces[tid] = tr  # re-insert = most recent
        return tr

    # ------------------------------------------------------------- spans

    def span(self, name: str, key: str | None = None,
             attrs: dict | None = None) -> Span:
        """Open a span. With ``key``: on that object's trace (child of
        the current span when it is on the same trace). Without: child
        of the current context, or the root of a fresh anonymous
        trace."""
        ctx = _CTX.get()
        parent_id = None
        if key is not None:
            trace_id = self.trace_id_for(key)
            if ctx is not None and ctx[0] is self \
                    and ctx[1].trace_id == trace_id:
                parent_id = ctx[1].span_id
        elif ctx is not None and ctx[0] is self:
            trace_id = ctx[1].trace_id
            parent_id = ctx[1].span_id
            key = ctx[2]
        else:
            with self._lock:
                trace_id = self._new_trace_locked(None).trace_id
        return Span(self, name, trace_id, parent_id, key,
                    dict(attrs or {}))

    def record(self, name: str, key: str, start: float, end: float,
               attrs: dict | None = None, error: bool = False,
               once: bool = False) -> bool:
        """Retroactive span on ``key``'s trace from already-measured
        instants (``time.monotonic`` seconds). ``once=True`` drops the
        record if the trace already holds a span of this name (idempotent
        lifecycle markers like ``notebook.ready``). Returns True when the
        span was actually recorded — with ``once``, the first-time
        verdict callers key once-per-incarnation side effects on (the
        create→Ready SLO sample must not re-fire for a pod flap)."""
        tid = self.trace_id_for(key)
        span = {
            "name": name, "span_id": uuid.uuid4().hex[:8],
            "parent_id": None, "start": start, "end": end,
            "error": error, "attrs": dict(attrs or {}),
            # exporters (the decision journal) attribute by object, not
            # by trace ring position — the key rides on the record
            "key": key, "trace_id": tid,
        }
        with self._lock:
            tr = self._touch_locked(tid)
            if tr is None:
                # a concurrent bind() renamed the trace between
                # trace_id_for() and here — follow the key, as _finish
                # does, instead of silently dropping the span
                cur = self._by_key.get(key)
                tr = self._touch_locked(cur) if cur else None
            if tr is None:
                return False
            if once:
                if name in tr.once:
                    return False
                tr.once.add(name)
            self._append_capped_locked(tr, span)
        self._export(span)
        return True

    def _finish(self, span: Span) -> None:
        d = {
            "name": span.name, "span_id": span.span_id,
            "parent_id": span.parent_id, "start": span.start,
            "end": span.end, "error": span.error,
            "attrs": dict(span.attrs),
            "key": span.key, "trace_id": span.trace_id,
        }
        with self._lock:
            tr = self._touch_locked(span.trace_id)
            if tr is None and span.key is not None:
                # the trace was renamed by bind() while this span was
                # open (first reconcile identifies the object mid-span):
                # follow the key to its current trace
                tid = self._by_key.get(span.key)
                tr = self._touch_locked(tid) if tid else None
            if tr is None:
                return
            self._append_capped_locked(tr, d)
        self._export(d)

    def _append_capped_locked(self, tr: _Trace, span: dict) -> None:
        """Cap = a per-trace ring: the OLDEST span falls off, so a
        long-lived object's trace always shows its recent activity (a
        cap that refused new spans would freeze the view at the first
        hours of a notebook's life — exactly what an operator debugging
        today's slowness doesn't want)."""
        if len(tr.spans) >= self.max_spans_per_trace:
            tr.spans.pop(0)
            tr.dropped += 1
        tr.spans.append(span)

    def _export(self, span: dict) -> None:
        for exporter in self.exporters:
            try:
                exporter(span)
            except Exception:
                pass  # an exporter bug must never fail a reconcile

    # ---------------------------------------------------------- snapshots

    def snapshot(self, key: str | None = None,
                 trace_id: str | None = None) -> dict | None:
        """Point-in-time copy of one trace (by key or id), or None."""
        with self._lock:
            if trace_id is None and key is not None:
                trace_id = self._by_key.get(key)
            tr = self._traces.get(trace_id) if trace_id else None
            if tr is None:
                return None
            return self._snapshot_locked(tr)

    def traces(self) -> list[dict]:
        """Snapshots of every retained trace (unordered)."""
        with self._lock:
            return [self._snapshot_locked(tr)
                    for tr in self._traces.values()]

    @staticmethod
    def _snapshot_locked(tr: _Trace) -> dict:
        # attrs copied too: consumers (the dashboard's tenant-boundary
        # redaction) may mutate their snapshot; the stored trace must
        # not change under them
        spans = [{**s, "attrs": dict(s["attrs"])} for s in tr.spans]
        starts = [s["start"] for s in spans]
        ends = [s["end"] for s in spans if s["end"] is not None]
        start = min(starts) if starts else tr.created
        duration = (max(ends) - start) if ends else 0.0
        stages: dict[str, float] = {}
        for s in spans:
            if s["end"] is not None:
                stages[s["name"]] = stages.get(s["name"], 0.0) + \
                    (s["end"] - s["start"])
        return {
            "trace_id": tr.trace_id, "key": tr.key, "start": start,
            "duration_s": duration, "spans": spans, "stages": stages,
            "dropped_spans": tr.dropped,
            "errors": sum(1 for s in spans if s["error"]),
        }


#: process-global tracer — the analog of metrics.REGISTRY; binaries and
#: the ops endpoint default to it, benches inject their own
TRACER = Tracer()


def current_tracer() -> Tracer:
    """Tracer of the innermost open span, else the global one — how
    library code (reconcilers) finds the tracer a Manager injected
    without threading it through every constructor."""
    ctx = _CTX.get()
    return ctx[0] if ctx is not None else TRACER


def span(name: str, key: str | None = None,
         attrs: dict | None = None) -> Span:
    return current_tracer().span(name, key=key, attrs=attrs)


def record(name: str, key: str, start: float, end: float,
           attrs: dict | None = None, error: bool = False,
           once: bool = False) -> bool:
    return current_tracer().record(name, key, start, end, attrs=attrs,
                                   error=error, once=once)


def object_trace_id(plural: str, obj: dict,
                    tracer: Tracer | None = None) -> str:
    """Bind ``obj``'s trace and return its id, derived from
    ``metadata.uid`` — deterministic across processes AND unique per
    incarnation (a deleted-and-recreated CR has a new uid, so a reused
    name never mixes two lifecycles on one trace). The uid outranks a
    stamped annotation: an exported-and-reapplied manifest carries the
    OLD incarnation's annotation, and honoring it would re-mix exactly
    the lifecycles the uid separation exists to keep apart (the
    controller re-stamps the annotation from the uid anyway). The
    annotation is the fallback for uid-less objects, else an id is
    generated. Reconcilers call this on every pass; it is two dict
    lookups when already bound."""
    meta = obj.get("metadata") or {}
    key = object_key(plural, meta.get("namespace"), meta.get("name", ""))
    t = tracer if tracer is not None else current_tracer()
    tid = (meta.get("uid") or "").replace("-", "")[:16]
    if not tid:
        tid = (meta.get("annotations") or {}).get(TRACE_ANNOTATION)
    if tid:
        t.bind(key, tid)
        return tid
    return t.trace_id_for(key)
