"""cpalert: multi-window multi-burn-rate SLO alerting (docs/observability.md).

obs/slo.py has computed error-budget burn since PR 8 — and paged nobody.
This module closes that gap with the SRE-workbook alert shape: a rule
fires only when the burn rate over a SHORT window and a LONG window both
exceed a threshold. The long window proves the burn is sustained (one
slow reconcile can't page), the short window makes the alert resolve
promptly once the bleeding stops (without it, a 1 h window would keep
paging for an hour after recovery).

Burn is computed from **cumulative counter points** (``slo_samples_total``
/ ``slo_violations_total``, fed by the fleet aggregator's reset-corrected
merge — obs/fleet.py — or by a single process's own engine), NOT from the
SLO engine's retained-sample ring: a ring-based burn stays elevated until
the incident's samples age out of retention, which would pin a page alert
long after recovery. Counter deltas over explicit windows resolve the
moment healthy traffic resumes.

Every state transition is journaled as a pinned ``alert/v1`` row and
emitted as an Event, so "when did this page, and why" is answerable from
the flight recorder alone. ``status()`` is the ``/alertz`` body.
"""

from __future__ import annotations

import dataclasses
import threading
import time

from service_account_auth_improvements_tpu.controlplane.obs.slo import (
    DEFAULT_OBJECTIVES,
)

#: pinned journal row schema — field names are asserted by tests the way
#: sched-journal/v1 rows are; consumers parse these rows, so renames are
#: breaking changes
ALERT_SCHEMA = "alert/v1"

#: Event reasons (module-level constants — the cplint event-reason pass)
REASON_ALERT_FIRING = "AlertFiring"
REASON_ALERT_RESOLVED = "AlertResolved"

#: Event types, local copies to keep obs/alerts importable without the
#: events module's kube surface (values are the k8s API constants)
NORMAL = "Normal"
WARNING = "Warning"


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One multi-window burn-rate rule. ``objective=None`` is a template
    applied to every declared objective (the usual case — the workbook
    thresholds are objective-independent)."""

    severity: str          # "page" | "ticket"
    burn_threshold: float  # both windows must burn at least this fast
    short_s: float
    long_s: float
    objective: str | None = None

    def scaled(self, factor: float) -> "AlertRule":
        """The same rule with compressed/stretched windows — bench and
        chaos scenarios run injections measured in seconds, not hours,
        and must exercise the REAL window math, just faster."""
        return dataclasses.replace(self, short_s=self.short_s * factor,
                                   long_s=self.long_s * factor)


#: the SRE-workbook catalog (ch. 5, "multiwindow, multi-burn-rate"):
#: page when 2% of a 30-day budget burns in an hour (14.4x), ticket on a
#: sustained 1x burn — budget exhausted exactly on schedule is still a
#: problem, just not a 2 a.m. one. Short windows are 1/12 of the long
#: window, the workbook's reset-latency compromise.
DEFAULT_RULES = (
    AlertRule(severity="page", burn_threshold=14.4,
              short_s=300.0, long_s=3600.0),
    AlertRule(severity="ticket", burn_threshold=1.0,
              short_s=1800.0, long_s=21600.0),
)


@dataclasses.dataclass
class _RuleState:
    rule: AlertRule
    state: str = "ok"              # "ok" | "firing"
    since_mono: float | None = None
    fired_count: int = 0
    resolved_count: int = 0
    burn_short: float | None = None
    burn_long: float | None = None


class AlertEngine:
    """Burn-rate evaluation over a stream of cumulative counter points.

    Feed :meth:`observe` one ``(samples_total, violations_total)`` point
    per objective per evaluation tick (the fleet aggregator calls it
    from every scrape). The engine keeps just enough point history to
    cover the longest window and evaluates every rule on each point:

    - **fire** when burn(short) AND burn(long) are both ≥ the threshold;
    - **resolve** when burn(short) drops below it (the long window keeps
      history, the short window answers "is it still happening");
    - **no data holds state** — a window with zero new samples yields no
      burn verdict, and flapping on silence would make every quiet
      period an implicit all-clear.
    """

    def __init__(self, objectives=None, rules=None, *,
                 journal=None, recorder=None,
                 namespace: str = "kubeflow", mono_fn=None):
        self.objectives = tuple(objectives or DEFAULT_OBJECTIVES)
        self._by_obj = {o.name: o for o in self.objectives}
        self.namespace = namespace
        self.journal = journal
        self.recorder = recorder
        self._mono = mono_fn if mono_fn is not None else time.monotonic
        self._lock = threading.Lock()
        #: objective -> [(mono, samples_total, violations_total), ...]
        self._points: dict[str, list] = {o.name: []
                                         for o in self.objectives}
        rules = tuple(rules or DEFAULT_RULES)
        self._states: dict[tuple[str, str], _RuleState] = {}
        for obj in self.objectives:
            for rule in rules:
                if rule.objective is not None \
                        and rule.objective != obj.name:
                    continue
                bound = dataclasses.replace(rule, objective=obj.name)
                self._states[(obj.name, rule.severity)] = _RuleState(bound)
        self._max_window = max(
            (st.rule.long_s for st in self._states.values()), default=0.0
        )

    # ---------------------------------------------------------- ingestion

    def observe(self, objective: str, samples_total: float,
                violations_total: float, now: float | None = None) -> None:
        """One cumulative point (already reset-corrected by the caller's
        merge — metrics.counter_delta); evaluates every rule bound to
        this objective. Unknown objectives are ignored, not raised: the
        fleet scrape may carry bench-world objectives this engine never
        declared, and telemetry must not take down the scrape loop."""
        if objective not in self._by_obj:
            return
        now = self._mono() if now is None else now
        transitions = []
        with self._lock:
            points = self._points[objective]
            points.append((now, float(samples_total),
                           float(violations_total)))
            # keep one point OLDER than the longest window as the
            # baseline its delta is computed against
            cutoff = now - self._max_window
            while len(points) > 2 and points[1][0] <= cutoff:
                points.pop(0)
            for st in self._states.values():
                if st.rule.objective != objective:
                    continue
                tr = self._evaluate_locked(st, points, now)
                if tr is not None:
                    transitions.append(tr)
        for st, state in transitions:
            self._announce(st, state)

    def _burn_locked(self, points, window_s: float,
                     now: float) -> float | None:
        """Burn rate over the trailing window from cumulative points:
        (violation fraction of the window's NEW samples) / budget. None
        when the window saw no new samples (no data, hold state) or
        history has only one point (cold start)."""
        if len(points) < 2:
            return None
        base = points[0]
        for p in points:
            if p[0] <= now - window_s:
                base = p
            else:
                break
        cur = points[-1]
        ds = cur[1] - base[1]
        dv = cur[2] - base[2]
        if ds <= 0:
            return None
        return dv / ds  # violation fraction; threshold folds the budget

    def _evaluate_locked(self, st: _RuleState, points, now):
        obj = self._by_obj[st.rule.objective]
        budget = 1.0 - obj.objective
        if budget <= 0:
            return None  # a zero-budget objective can't express burn
        short = self._burn_locked(points, st.rule.short_s, now)
        long_ = self._burn_locked(points, st.rule.long_s, now)
        st.burn_short = None if short is None else short / budget
        st.burn_long = None if long_ is None else long_ / budget
        thr = st.rule.burn_threshold
        if st.state == "ok":
            if st.burn_short is not None and st.burn_long is not None \
                    and st.burn_short >= thr and st.burn_long >= thr:
                st.state = "firing"
                st.since_mono = now
                st.fired_count += 1
                return (st, "firing")
        else:
            if st.burn_short is not None and st.burn_short < thr:
                st.state = "ok"
                st.since_mono = now
                st.resolved_count += 1
                return (st, "resolved")
        return None

    # ------------------------------------------------------ announcements

    def _announce(self, st: _RuleState, state: str) -> None:
        rule = st.rule
        if self.journal is not None:
            # the pinned flight-recorder row (schema ALERT_SCHEMA):
            # consumers key on these field names
            self.journal.decide(
                "alert", key=f"slo/{rule.objective}/{rule.severity}",
                schema=ALERT_SCHEMA, objective=rule.objective,
                severity=rule.severity, state=state,
                burn_short=st.burn_short, burn_long=st.burn_long,
                threshold=rule.burn_threshold,
                short_s=rule.short_s, long_s=rule.long_s,
            )
        if self.recorder is not None:
            involved = {
                "apiVersion": "tpukf.dev/v1",
                "kind": "FleetSLO",
                "metadata": {"name": rule.objective,
                             "namespace": self.namespace},
            }
            firing = state == "firing"
            if firing:
                etype, reason = WARNING, REASON_ALERT_FIRING
            else:
                etype, reason = NORMAL, REASON_ALERT_RESOLVED
            self.recorder.event(
                involved, etype, reason,
                f"{rule.severity} burn-rate alert on {rule.objective} "
                f"{state}: burn short={st.burn_short} "
                f"long={st.burn_long} vs {rule.burn_threshold}x "
                f"({rule.short_s:g}s/{rule.long_s:g}s windows)",
            )

    # ------------------------------------------------------------- status

    def firing(self) -> list[dict]:
        """Currently-firing rules only (the dashboard's red banner)."""
        return [r for r in self.status()["rules"]
                if r["state"] == "firing"]

    def status(self) -> dict:
        """The ``/alertz`` body: every bound rule with its live burn."""
        now = self._mono()
        rows = []
        with self._lock:
            for (objective, severity) in sorted(self._states):
                st = self._states[(objective, severity)]
                rows.append({
                    "objective": objective,
                    "severity": severity,
                    "threshold": st.rule.burn_threshold,
                    "short_s": st.rule.short_s,
                    "long_s": st.rule.long_s,
                    "state": st.state,
                    "burn_short": _round(st.burn_short),
                    "burn_long": _round(st.burn_long),
                    "for_s": (None if st.since_mono is None
                              else round(now - st.since_mono, 3)),
                    "fired_count": st.fired_count,
                    "resolved_count": st.resolved_count,
                })
        return {"schema": "alertz/v1", "rules": rows}


def _round(v: float | None) -> float | None:
    return None if v is None else round(v, 4)
