"""cpscope decision journal: a bounded, durable-enough record of *why*.

The span ring (obs/trace.py) answers "where did the time go" but wraps:
a placement made an hour ago, the preemption that evicted a tenant, the
chaos injection that explains a latency cliff — all gone once the ring
turns over. The journal is the black-box flight recorder for
*decisions*: a bounded ring of JSONL-serializable entries, each stamped
with BOTH clocks (monotonic for ordering/intervals, wall for humans and
cross-process correlation), fed two ways:

- **span subscription** (:meth:`Journal.attach`): the journal rides the
  existing ``Tracer.exporters`` hook and keeps every decision-shaped
  span — reconcile outcomes, ``sched.admit``/``sched.place`` (the
  (state, decision, outcome) tuple the ROADMAP's learned-placement item
  harvests), ``sched.preempt``, ``notebook.ready``;
- **explicit** :func:`decide` **call sites** for decisions that never
  open a span: culls, lease transitions, chaos injections.

``decide()`` (module-level) resolves the journal through
``current_tracer().journal`` so reconcile-context callers need no
wiring and cpbench worlds stay isolated, falling back to the
process-global :data:`JOURNAL`.

Lock discipline: one lock guards the ring and counters; entries are
plain dicts built before acquisition; nothing under the lock ever
touches the apiserver (lockwatch-clean by construction).
"""

from __future__ import annotations

import collections
import datetime
import io
import json
import threading
import time

from service_account_auth_improvements_tpu.controlplane.obs.trace import (
    current_tracer,
)

SCHEMA = "cpjournal/v1"

#: span name -> journal kind; spans outside this map are not decisions
SPAN_KINDS = {
    "reconcile": "reconcile",
    "sched.admit": "admission",
    "sched.place": "placement",
    "sched.preempt": "preemption",
    "sched.park": "park",
    "sched.resume": "resume",
    "notebook.ready": "ready",
}


def _utcnow() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def _mono() -> float:
    return time.monotonic()


class Journal:
    """Bounded ring of decision entries (module docstring)."""

    def __init__(self, capacity: int = 8192, now_fn=None, mono_fn=None):
        self.capacity = capacity
        self._now = now_fn if now_fn is not None else _utcnow
        self._mono = mono_fn if mono_fn is not None else _mono
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._counts: dict[str, int] = {}
        self._seq = 0

    # ------------------------------------------------------------- intake

    def decide(self, kind: str, key: str | None = None, **attrs) -> dict:
        """Record one decision; returns the entry (already stored)."""
        entry = {
            "kind": kind,
            "key": key,
            "mono": self._mono(),
            "wall": self._now().strftime("%Y-%m-%dT%H:%M:%S.%fZ"),
            "attrs": attrs,
        }
        with self._lock:
            self._seq += 1
            entry["seq"] = self._seq
            self._ring.append(entry)
            self._counts[kind] = self._counts.get(kind, 0) + 1
        return entry

    def record_span(self, span: dict) -> None:
        """``Tracer.exporters`` hook: keep decision-shaped spans."""
        kind = SPAN_KINDS.get(span.get("name", ""))
        if kind is None:
            return
        attrs = dict(span.get("attrs") or {})
        if span.get("error"):
            attrs["error"] = True
        self.decide(kind, key=span.get("key"),
                    span=span.get("name"), **attrs)

    def attach(self, tracer) -> "Journal":
        """Subscribe to ``tracer``'s exporter hook (idempotent) and make
        this journal discoverable via ``current_tracer().journal``."""
        if self.record_span not in tracer.exporters:
            tracer.exporters.append(self.record_span)
        tracer.journal = self
        return self

    # -------------------------------------------------------------- output

    def entries(self, key: str | None = None,
                kinds=None) -> list[dict]:
        """Snapshot, oldest first. ``key`` filters to one object (plus
        keyless entries are NOT included — callers that want ambient
        context, like the explain engine folding in chaos windows, ask
        for those kinds explicitly)."""
        with self._lock:
            snap = list(self._ring)
        if key is not None:
            snap = [e for e in snap if e.get("key") == key]
        if kinds is not None:
            wanted = set(kinds)
            snap = [e for e in snap if e["kind"] in wanted]
        return [dict(e, attrs=dict(e["attrs"])) for e in snap]

    def counts(self) -> dict[str, int]:
        """Per-kind totals since construction (NOT bounded by the ring —
        the evidence that N decisions happened survives their eviction)."""
        with self._lock:
            return dict(self._counts)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def to_jsonl(self, key: str | None = None) -> str:
        """The ring as JSONL — the cpbench black-box artifact format and
        the harvest surface for the learned-placement training set."""
        buf = io.StringIO()
        for entry in self.entries(key=key):
            buf.write(json.dumps(entry, sort_keys=True, default=str))
            buf.write("\n")
        return buf.getvalue()


#: process-global journal — the analog of obs.TRACER; binaries attach it
#: to the global tracer in cmd/runner.py, benches build their own
JOURNAL = Journal()


def current_journal() -> Journal:
    """Journal attached to the innermost tracer, else the global one."""
    j = getattr(current_tracer(), "journal", None)
    return j if j is not None else JOURNAL


def decide(kind: str, key: str | None = None, **attrs) -> dict:
    return current_journal().decide(kind, key=key, **attrs)
