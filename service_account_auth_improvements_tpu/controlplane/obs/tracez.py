"""/debug/tracez rendering: recent traces, slowest-first.

The text analog of OpenCensus zPages' tracez — one screen that answers
"what were the slowest lifecycles this process drove, and where did
their time go" with nothing but curl. Served by engine/serve.py next to
/metrics; the dashboard's ``/api/traces/<ns>/<name>`` serves the same
snapshots as JSON.
"""

from __future__ import annotations

from service_account_auth_improvements_tpu.controlplane.obs.trace import (
    Tracer,
)


def _fmt_attrs(attrs: dict) -> str:
    if not attrs:
        return ""
    inner = ", ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
    return " {" + inner + "}"


def render_trace(snap: dict) -> str:
    """One trace: header, stage breakdown, then spans by start time."""
    head = (
        f"TRACE {snap['key'] or '(anonymous)'} "
        f"id={snap['trace_id']} duration={snap['duration_s'] * 1000:.1f}ms "
        f"spans={len(snap['spans'])} errors={snap['errors']}"
    )
    if snap["dropped_spans"]:
        head += f" dropped={snap['dropped_spans']}"
    lines = [head]
    stages = sorted(snap["stages"].items(), key=lambda kv: -kv[1])
    if stages:
        lines.append("  stages: " + "  ".join(
            f"{name}={dur * 1000:.1f}ms" for name, dur in stages
        ))
    by_id = {s["span_id"]: s for s in snap["spans"]}
    for s in sorted(snap["spans"], key=lambda s: s["start"]):
        offset = (s["start"] - snap["start"]) * 1000
        dur = ((s["end"] - s["start"]) * 1000
               if s["end"] is not None else float("nan"))
        depth = 0
        parent = s.get("parent_id")
        while parent in by_id and depth < 8:
            depth += 1
            parent = by_id[parent].get("parent_id")
        lines.append(
            f"  {'  ' * depth}+{offset:9.1f}ms {dur:9.1f}ms "
            f"{s['name']}{' ERROR' if s['error'] else ''}"
            f"{_fmt_attrs(s['attrs'])}"
        )
    return "\n".join(lines)


def render_tracez(tracer: Tracer, limit: int = 50,
                  key: str | None = None) -> str:
    """The whole page. ``key`` filters to one object's trace."""
    if key is not None:
        snap = tracer.snapshot(key=key)
        if snap is None:
            return f"no trace for key {key!r}\n"
        return render_trace(snap) + "\n"
    snaps = sorted(tracer.traces(), key=lambda s: -s["duration_s"])
    header = (
        f"cptrace: {len(snaps)} trace(s) retained "
        f"(showing up to {limit}, slowest first)\n"
    )
    return header + "\n\n".join(
        render_trace(s) for s in snaps[:limit]
    ) + ("\n" if snaps else "")
