"""cptrace: end-to-end reconcile tracing (docs/observability.md)."""

from service_account_auth_improvements_tpu.controlplane.obs.trace import (  # noqa: F401,E501
    TRACE_ANNOTATION,
    TRACER,
    Span,
    SpanContext,
    Tracer,
    current_tracer,
    object_key,
    object_trace_id,
    record,
    span,
)
from service_account_auth_improvements_tpu.controlplane.obs.tracez import (  # noqa: F401,E501
    render_trace,
    render_tracez,
)
