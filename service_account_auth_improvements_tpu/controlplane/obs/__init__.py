"""cpscope: tracing, events, decision journal, explain engine, SLOs,
the cpprof profiler, and the cpfleet cross-replica aggregation plane
with burn-rate alerting (docs/observability.md)."""

from service_account_auth_improvements_tpu.controlplane.obs.trace import (  # noqa: F401,E501
    TRACE_ANNOTATION,
    TRACER,
    Span,
    SpanContext,
    Tracer,
    current_tracer,
    object_key,
    object_trace_id,
    record,
    span,
)
from service_account_auth_improvements_tpu.controlplane.obs.tracez import (  # noqa: F401,E501
    render_trace,
    render_tracez,
)
from service_account_auth_improvements_tpu.controlplane.obs.events import (  # noqa: F401,E501
    NORMAL,
    WARNING,
    EventRecorder,
    involved_kind_and_name,
)
from service_account_auth_improvements_tpu.controlplane.obs.journal import (  # noqa: F401,E501
    JOURNAL,
    Journal,
    current_journal,
    decide,
)
from service_account_auth_improvements_tpu.controlplane.obs.explain import (  # noqa: F401,E501
    explain,
    redact as redact_explain,
    render_explain,
)
from service_account_auth_improvements_tpu.controlplane.obs.slo import (  # noqa: F401,E501
    DEFAULT_OBJECTIVES,
    Objective,
    SloEngine,
    observe as slo_observe,
)
from service_account_auth_improvements_tpu.controlplane.obs.alerts import (  # noqa: F401,E501
    ALERT_SCHEMA,
    DEFAULT_RULES,
    AlertEngine,
    AlertRule,
)
from service_account_auth_improvements_tpu.controlplane.obs.fleet import (  # noqa: F401,E501
    FleetAggregator,
    lease_replicas_fn,
    parse_exposition,
    render_fleetz,
    stitch_traces,
)
from service_account_auth_improvements_tpu.controlplane.obs.prof import (  # noqa: F401,E501
    PROFILER,
    Profiler,
    current_actor,
    install_lock_contention,
    lock_contention_snapshot,
    lock_contention_top,
    reconcile_tag,
    render_profilez,
    saturation_snapshot,
    store_lock_wait_share,
    start_from_env as start_profiler_from_env,
    sync_metrics as prof_sync_metrics,
)
