"""cpprof: sampling wall-clock profiler + contention/saturation feeds.

The plane can say *what* happened (cptrace spans, the cpscope journal)
and *whether* the SLOs held (obs/slo.py) — this module answers *where
the CPU went and who is waiting on whom*, the question every
"fast as the hardware allows" investigation starts with (NotebookOS,
arXiv:2503.20591, treats lifecycle-latency visibility as a product
feature; a latency number without a hot stack is a mystery, not a
diagnosis).

Three feeds, merged on ``/debug/profilez`` (engine/serve.py) and in
cpbench's per-scenario ``extra.prof``:

- **Hot stacks** (:class:`Profiler`): a background daemon thread walks
  ``sys._current_frames()`` at a configurable rate (``CPPROF_HZ``,
  default 7 — see DEFAULT_HZ for why low-and-prime) and folds each
  thread's stack flamegraph-style.
  Samples are attributed to the RUNNING RECONCILE via the thread-tag
  registry below (the engine tags its workers per attempt), so stacks
  fold per controller, not per anonymous worker thread. This is a
  *wall* profiler: blocked threads are sampled too — a stack parked in
  ``queue.get`` is real wait, and for a control plane the waits are
  usually the finding.
- **Lock contention**: tools/cplint/lockwatch's instrumented locks (the
  ONE lock wrapper — lint mode and the contention view share it) record
  per-creation-site wait/hold time histograms. Enable outside lint mode
  with ``CPPROF_LOCKS=1`` (:func:`install_lock_contention`).
- **Saturation**: worker busy-fraction / queue depth-per-worker /
  informer watch-backlog gauges (engine/metrics.py) snapshotted by
  :func:`saturation_snapshot`; FakeKube's per-client request split
  (``request_counts_snapshot(by_client=True)``) rides the same report
  in cpbench — the per-client attribution the apiserver
  priority-and-fairness ROADMAP item needs as pre-work.

Everything is stdlib; the profiler costs nothing when not started and
its A/B overhead on cpbench's notebook_ready p95 is gated at ≤5 %
(tools/bench_gate.py --prof-report).
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time

#: default sampling rate: an off-round prime (no phase-lock with
#: periodic work), chosen low because a saturated control plane
#: amplifies sampler cost superlinearly — near full utilization a ~2 %
#: GIL tax turns into >10 % p95 (queueing theory, measured at cpbench
#: --full burst scale), and on a small-core box the dominant cost is
#: the WAKE itself, not the sampling work: an A/B with a no-op sampler
#: measures the same p95 tax as the real one — each wake forces a GIL
#: handoff + two context switches on the core doing the reconciling,
#: so overhead scales with wake count and nothing else. 7 Hz keeps the
#: A/B inside the ≤5 % budget with margin while still landing samples
#: on any scenario that takes a second (and stop() guarantees at least
#: one pass regardless). Raise CPPROF_HZ for short-lived
#: investigations where resolution beats overhead.
DEFAULT_HZ = 7.0

#: thread ident -> (controller, stage, object key) of the work the
#: thread is executing RIGHT NOW. The engine's reconcile workers tag
#: themselves per attempt (engine/manager.py); the sampler reads it to
#: fold stacks per controller; FakeKube reads it (via ``actor_fn`` =
#: :func:`current_actor`) to attribute apiserver requests per client.
#: Plain dict ops under the GIL — no lock on the reconcile hot path.
_THREAD_TAGS: dict[int, tuple] = {}


@contextlib.contextmanager
def reconcile_tag(controller: str, key: str | None = None,
                  stage: str = "reconcile"):
    """Tag the current thread as running ``controller``'s ``stage`` for
    the duration of the with-block (nestable; the previous tag is
    restored on exit)."""
    ident = threading.get_ident()
    prev = _THREAD_TAGS.get(ident)
    _THREAD_TAGS[ident] = (controller, stage, key)
    try:
        yield
    finally:
        if prev is None:
            _THREAD_TAGS.pop(ident, None)
        else:
            _THREAD_TAGS[ident] = prev


def current_actor() -> str | None:
    """Controller name of the innermost tag on THIS thread (None when
    untagged) — FakeKube's per-client request attribution hook."""
    tag = _THREAD_TAGS.get(threading.get_ident())
    return tag[0] if tag else None


class Profiler:
    """Sampling wall profiler over every live thread.

    ``start``/``stop`` are idempotent; a stopped profiler keeps its
    samples (``report`` / ``folded``) until the next ``start``, which
    resumes accumulation. ``stop`` takes one final synchronous sample so
    even a sub-interval workload leaves evidence. ``mono_fn`` is the
    injected clock for durations (sampling cadence itself rides
    ``Event.wait`` — it paces, it never *reads* time)."""

    def __init__(self, hz: float | None = None, mono_fn=None,
                 max_stack: int = 48):
        env_hz = os.environ.get("CPPROF_HZ")
        try:
            hz = float(hz if hz is not None else (env_hz or DEFAULT_HZ))
        except ValueError:
            hz = DEFAULT_HZ
        self.hz = min(max(hz, 1.0), 1000.0)
        self.max_stack = max_stack
        self._mono = mono_fn or time.monotonic
        self._lock = threading.Lock()
        self._stop_ev = threading.Event()
        self._thread: threading.Thread | None = None
        #: (bucket, folded stack) -> sample count. Bucket = the thread
        #: tag's controller when tagged, else the thread's name — so an
        #: untagged hot thread is still visible, just less foldable.
        self._counts: dict[tuple[str, str], int] = {}
        self._bucket_samples: dict[str, int] = {}
        self._passes = 0
        self._active_s = 0.0
        self._started_at: float | None = None
        # code object -> display label; code objects are retained, which
        # bounds the cache at the program's live code size
        self._label_cache: dict = {}
        # thread ident -> name, refreshed only when an unknown ident
        # appears (threading.enumerate() per pass is avoidable cost)
        self._name_cache: dict[int, str] = {}
        # ident -> (id(top frame), f_lasti, folded): a thread whose top
        # frame object AND instruction pointer are unchanged since the
        # last pass is parked at the same spot (queue.get, watch poll,
        # Condition.wait — most of a control plane, most of the time);
        # its fold is reused instead of re-walked. This is what keeps a
        # pass O(running threads), not O(all threads x stack depth) —
        # the difference between ~1.5 ms and ~0.2 ms per pass on a busy
        # bench, i.e. between a measurable and an unmeasurable p95 tax.
        self._fold_cache: dict[int, tuple] = {}

    # ------------------------------------------------------------ control

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    @property
    def passes(self) -> int:
        """Sampling passes completed — the cheap counter for metric
        exposition (``report()`` aggregates every fold just to build
        its tables; a scrape must not pay that)."""
        with self._lock:
            return self._passes

    def start(self) -> "Profiler":
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop_ev = threading.Event()
            self._started_at = self._mono()
            t = threading.Thread(target=self._run, name="cpprof-sampler",
                                 daemon=True)
            self._thread = t
        t.start()
        return self

    def stop(self) -> "Profiler":
        with self._lock:
            t = self._thread
            self._thread = None
            ev = self._stop_ev
            if self._started_at is not None:
                self._active_s += self._mono() - self._started_at
                self._started_at = None
        ev.set()
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
        # final synchronous pass: a workload shorter than one sampling
        # interval must still leave at least one stack behind
        self.sample_once()
        return self

    def _run(self) -> None:
        interval = 1.0 / self.hz
        stop_ev = self._stop_ev
        while not stop_ev.wait(interval):
            try:
                self.sample_once()
            except Exception:
                # a profiler bug must never take the process with it
                pass

    # ----------------------------------------------------------- sampling

    def _label(self, code) -> str:
        lbl = self._label_cache.get(code)
        if lbl is None:
            fname = code.co_filename.replace("\\", "/")
            short = "/".join(fname.rsplit("/", 2)[-2:])
            lbl = f"{short}:{code.co_name}"
            self._label_cache[code] = lbl
        return lbl

    def sample_once(self) -> int:
        """One sampling pass over every live thread except the sampler
        and the caller (whose stack IS the profiler). Returns the number
        of stacks recorded — the test surface."""
        frames = sys._current_frames()
        tags = dict(_THREAD_TAGS)
        names = self._name_cache
        if any(ident not in names for ident in frames):
            names = {t.ident: t.name for t in threading.enumerate()}
            self._name_cache = names
        me = threading.get_ident()
        sampler = self._thread
        sampler_ident = sampler.ident if sampler is not None else None
        rows = []
        fold_cache = self._fold_cache
        fresh_cache: dict[int, tuple] = {}
        for ident, frame in frames.items():
            if ident == me or ident == sampler_ident:
                continue
            # the code object rides the key too: id(frame) can be
            # recycled after a frame is freed, and id+lasti alone could
            # serve a dead stack for new work (statistical noise, but
            # cheap to shrink the window)
            fid, lasti, code = id(frame), frame.f_lasti, frame.f_code
            cached = fold_cache.get(ident)
            if cached is not None and cached[0] == fid \
                    and cached[1] == lasti and cached[2] is code:
                folded = cached[3]
            else:
                stack = []
                f = frame
                while f is not None and len(stack) < self.max_stack:
                    stack.append(self._label(f.f_code))
                    f = f.f_back
                if not stack:
                    continue
                stack.reverse()
                folded = ";".join(stack)
            fresh_cache[ident] = (fid, lasti, code, folded)
            tag = tags.get(ident)
            bucket = tag[0] if tag else names.get(ident, f"thread-{ident}")
            rows.append((bucket, folded))
        # replacing (not updating) the cache drops dead threads' entries
        self._fold_cache = fresh_cache
        with self._lock:
            self._passes += 1
            for bucket, folded in rows:
                k = (bucket, folded)
                self._counts[k] = self._counts.get(k, 0) + 1
                self._bucket_samples[bucket] = \
                    self._bucket_samples.get(bucket, 0) + 1
        return len(rows)

    # ------------------------------------------------------------ reports

    def _snapshot(self):
        with self._lock:
            active = self._active_s
            if self._started_at is not None:
                active += self._mono() - self._started_at
            return (dict(self._counts), self._passes,
                    dict(self._bucket_samples), active)

    def report(self, top_k: int = 20, controller: str | None = None,
               fold: str | None = None) -> dict:
        """Aggregated view: top-k folded stacks (each stack's sampled
        seconds are its *self* time — a fold is its own leaf) plus a
        per-function self/total table (total counts a function once per
        stack it appears anywhere in; self only when it is the leaf)."""
        counts, passes, buckets, active = self._snapshot()
        sec = (active / passes) if passes else 0.0
        items = [
            (b, s, n) for (b, s), n in counts.items()
            if (controller is None or b == controller)
            and (fold is None or fold in s)
        ]
        items.sort(key=lambda r: r[2], reverse=True)
        selfs: dict[str, int] = {}
        totals: dict[str, int] = {}
        for _, s, n in items:
            frames = s.split(";")
            selfs[frames[-1]] = selfs.get(frames[-1], 0) + n
            for fr in set(frames):
                totals[fr] = totals.get(fr, 0) + n
        functions = sorted(
            totals,
            key=lambda fr: (selfs.get(fr, 0), totals[fr]),
            reverse=True,
        )
        return {
            "schema": "cpprof/v1",
            "running": self.running,
            "hz": self.hz,
            "passes": passes,
            "samples": sum(n for _, _, n in items),
            "duration_s": round(active, 3),
            "controllers": buckets,
            "stacks": [
                {"controller": b, "stack": s, "count": n,
                 "seconds": round(n * sec, 4)}
                for b, s, n in items[:top_k]
            ],
            "functions": [
                {"name": fr,
                 "self_s": round(selfs.get(fr, 0) * sec, 4),
                 "total_s": round(totals[fr] * sec, 4)}
                for fr in functions[:top_k]
            ],
            "top_stack": items[0][1] if items else None,
            "top_controller": (max(buckets, key=buckets.get)
                               if buckets else None),
        }

    def folded(self) -> str:
        """Full profile in flamegraph folded format, one fold per line:
        ``bucket;frame;frame;... count`` (root left, leaf right)."""
        counts, _, _, _ = self._snapshot()
        lines = [f"{b};{s} {n}"
                 for (b, s), n in sorted(counts.items(),
                                         key=lambda kv: -kv[1])]
        return "\n".join(lines) + ("\n" if lines else "")


#: process-global profiler, the analog of obs.TRACER — not started
#: until :func:`start_from_env` (CPPROF=1) or an explicit ``.start()``
PROFILER = Profiler()


def start_from_env(env=None) -> Profiler | None:
    """Binary wiring (cmd/runner.py): ``CPPROF=1`` starts the global
    profiler, ``CPPROF_LOCKS=1`` installs lock-contention
    instrumentation. Returns the profiler when started."""
    env = env if env is not None else os.environ
    if env.get("CPPROF_LOCKS") == "1":
        install_lock_contention()
    if env.get("CPPROF") == "1":
        return PROFILER.start()
    return None


# ------------------------------------------------------- lock contention

def _lockwatch_mod():
    try:
        from tools.cplint import lockwatch
    except ImportError:  # deployed binaries may not ship tools/
        return None
    return lockwatch


def install_lock_contention():
    """Install lockwatch's instrumented locks (idempotent) — the same
    wrapper lint mode uses, recording per-creation-site wait/hold
    histograms as a side effect. Only locks created AFTER installation
    are watched, so call this before building managers/worlds."""
    lw = _lockwatch_mod()
    return lw.install() if lw is not None else None


def lock_contention_snapshot(watch=None) -> dict:
    """{creation site: cumulative wait/hold stats} from the active
    lockwatch (or ``watch``); {} when no instrumentation is installed."""
    lw = _lockwatch_mod()
    w = watch if watch is not None else (lw.active() if lw else None)
    if w is None or not hasattr(w, "contention_snapshot"):
        return {}
    return w.contention_snapshot()


def _short_site(site: str) -> str:
    """Trim a creation site's absolute path to its last three segments
    — reports and metric labels stay readable across checkouts."""
    path, _, line = site.rpartition(":")
    short = "/".join(path.replace("\\", "/").rsplit("/", 3)[-3:])
    return f"{short}:{line}" if line else site


def lock_contention_top(since: dict | None = None, limit: int = 10,
                        watch=None) -> list[dict]:
    """The most-contended creation sites, by waited seconds, optionally
    as a delta against an earlier :func:`lock_contention_snapshot`
    (cpbench diffs per scenario). Max fields are cumulative — a max
    cannot be diffed, so they read 'worst ever seen', not 'worst in this
    window'."""
    now = lock_contention_snapshot(watch)
    since = since or {}
    rows = []
    for site, st in now.items():
        base = since.get(site) or {}
        acquires = st["acquires"] - base.get("acquires", 0)
        if acquires <= 0:
            continue
        rows.append({
            "site": _short_site(site),
            "acquires": acquires,
            "contended": st["contended"] - base.get("contended", 0),
            "wait_s": round(st["wait_s"] - base.get("wait_s", 0.0), 6),
            "hold_s": round(st["hold_s"] - base.get("hold_s", 0.0), 6),
            "wait_max_s": round(st["wait_max_s"], 6),
            "hold_max_s": round(st["hold_max_s"], 6),
        })
    # genuinely contended sites first (any acquisition that measurably
    # waited — CONTENDED_WAIT_S in lockwatch — outranks pure fast-path
    # acquire bookkeeping, which sums to milliseconds on a hot verb
    # without a single thread ever blocking), then by waited seconds
    rows.sort(key=lambda r: (1 if r["contended"] else 0,
                             r["wait_s"], r["hold_s"]), reverse=True)
    return rows[:limit]


#: creation-site fragment identifying the fake apiserver's own locks
#: (store stripes, family event locks) in lockwatch site labels
STORE_LOCK_SITE_FRAGMENT = "kube/fake.py"


def store_lock_wait_share(locks: list, duration_s: float) -> float:
    """Store-lock wait share — the ONE definition, shared by cpbench's
    ``extra.prof`` and the ``apiserver_stress`` sweep arms (bench_gate
    --store-lock-max-share gates it): CONTENDED wait on locks created
    in kube/fake.py, divided by wall time. "Of this window's runtime,
    how much thread time was spent blocked on the fake apiserver" —
    stable whether or not anything else contends (a share-of-total-
    contention ratio would read 1.0 for a single 150 µs blip in an
    otherwise clean run and near 0 for a saturated fake on a busy
    box), and can exceed 1.0 when several threads block concurrently
    (the pre-refactor fake measured 2.9 on the 4-worker stress arm).
    Uncontended fast-path acquire bookkeeping is excluded: it sums to
    milliseconds on a hot verb without anything ever serializing.
    ``locks`` is :func:`lock_contention_top` output (use a wide limit —
    a lock-heavy process can push fake sites past any top-10)."""
    wait = sum(r["wait_s"] for r in locks
               if r["contended"] and STORE_LOCK_SITE_FRAGMENT in r["site"])
    return round(wait / max(duration_s, 1e-9), 4)


# ----------------------------------------------------------- saturation

def saturation_snapshot() -> dict:
    """Point-in-time saturation view from the engine metric families:
    per-controller worker busy ratio + active workers, per-queue depth
    and depth-per-worker, per-resource informer watch backlog age."""
    # lazy import: obs must stay importable without dragging the engine
    # in (engine/manager itself imports obs)
    from service_account_auth_improvements_tpu.controlplane.engine.metrics import (  # noqa: E501
        engine_metrics,
        refresh_busy_ratios,
    )

    # the worker loop only publishes busy_ratio at reconcile completion;
    # refreshing here lets an idle controller's ratio decay on the page
    # instead of freezing at its last busy burst
    refresh_busy_ratios()
    em = engine_metrics()

    def series(metric):
        with metric._lock:
            return dict(metric._values)

    workers: dict[str, dict] = {}
    for (ctl,), v in series(em.worker_busy_ratio).items():
        workers.setdefault(ctl, {})["busy_ratio"] = round(v, 4)
    for (ctl,), v in series(em.active_workers).items():
        workers.setdefault(ctl, {})["active"] = v
    queues: dict[str, dict] = {}
    for (name,), v in series(em.workqueue_depth).items():
        queues.setdefault(name, {})["depth"] = v
    for (name,), v in series(em.workqueue_depth_per_worker).items():
        queues.setdefault(name, {})["depth_per_worker"] = round(v, 4)
    informers = {
        res: round(v, 4)
        for (res,), v in series(em.informer_backlog).items()
    }
    return {"workers": workers, "queues": queues, "informers": informers}


# ------------------------------------------------------ metrics exposure

_metrics_lock = threading.Lock()
_metrics: dict | None = None


def sync_metrics() -> None:
    """Refresh the cpprof gauge families on the global registry from the
    lockwatch contention stats and the profiler's sample counter —
    called by the ops endpoint just before rendering /metrics (pull
    model: lock stats live in plain dicts so the lock hot path never
    touches a metric lock)."""
    global _metrics
    from service_account_auth_improvements_tpu.controlplane.engine.metrics import (  # noqa: E501
        refresh_busy_ratios,
    )

    refresh_busy_ratios()   # idle controllers' ratios decay on scrape
    contention = lock_contention_snapshot()
    with _metrics_lock:
        if _metrics is None:
            from service_account_auth_improvements_tpu.controlplane.metrics import (  # noqa: E501
                Gauge,
            )

            _metrics = {
                "wait": Gauge(
                    "cpprof_lock_wait_seconds",
                    "Cumulative seconds threads waited to acquire locks "
                    "created at this site", ("site",),
                ),
                "hold": Gauge(
                    "cpprof_lock_hold_seconds",
                    "Cumulative seconds locks created at this site were "
                    "held", ("site",),
                ),
                "acquires": Gauge(
                    "cpprof_lock_acquisitions",
                    "Cumulative acquisitions of locks created at this "
                    "site", ("site",),
                ),
                "contended": Gauge(
                    "cpprof_lock_contended_acquisitions",
                    "Acquisitions that waited measurably at this site",
                    ("site",),
                ),
                "passes": Gauge(
                    "cpprof_profiler_passes",
                    "Sampling passes completed by the cpprof profiler",
                ),
            }
        m = _metrics
    for site, st in contention.items():
        site = _short_site(site)
        m["wait"].labels(site).set(st["wait_s"])
        m["hold"].labels(site).set(st["hold_s"])
        m["acquires"].labels(site).set(st["acquires"])
        m["contended"].labels(site).set(st["contended"])
    m["passes"].set(PROFILER.passes)


# ------------------------------------------------------------ rendering

def render_profilez(profiler: Profiler | None = None,
                    controller: str | None = None,
                    fold: str | None = None, top_k: int = 20,
                    lockwatch=None) -> str:
    """The /debug/profilez page: hot stacks, functions, contended locks,
    saturated queues — one text page, filterable with ``?controller=``
    (attribution bucket) and ``?fold=`` (substring over folded
    stacks)."""
    p = profiler if profiler is not None else PROFILER
    rep = p.report(top_k=top_k, controller=controller, fold=fold)
    lines = ["cpprof /debug/profilez", ""]
    state = "running" if rep["running"] else \
        "stopped (set CPPROF=1 or start the profiler)"
    lines.append(
        f"profiler: {state}  hz={rep['hz']:g}  passes={rep['passes']}  "
        f"samples={rep['samples']}  duration={rep['duration_s']:.1f}s"
    )
    if controller or fold:
        lines.append(
            f"filters: controller={controller or '*'} fold={fold or '*'}"
        )
    lines.append("")
    lines.append(f"== hot stacks (top {top_k}, wall-sampled; waits are "
                 "samples too) ==")
    if not rep["stacks"]:
        lines.append("  (no samples)")
    for s in rep["stacks"]:
        lines.append(f"  {s['seconds']:9.3f}s  {s['count']:6d}  "
                     f"[{s['controller']}]")
        lines.append(f"      {s['stack']}")
    lines.append("")
    lines.append(f"== functions (top {top_k}, self/total seconds) ==")
    for fn in rep["functions"]:
        lines.append(f"  {fn['self_s']:9.3f} / {fn['total_s']:9.3f}  "
                     f"{fn['name']}")
    lines.append("")
    lines.append("== attribution buckets (samples) ==")
    for b, n in sorted(rep["controllers"].items(),
                       key=lambda kv: -kv[1]):
        lines.append(f"  {n:8d}  {b}")
    lines.append("")
    lines.append("== contended locks (by waited seconds) ==")
    locks = lock_contention_top(limit=top_k, watch=lockwatch)
    if not locks:
        lines.append("  (no lock instrumentation — set CPPROF_LOCKS=1 "
                     "or CPLINT_LOCKWATCH=1)")
    for lk in locks:
        lines.append(
            f"  wait={lk['wait_s']:.4f}s (max {lk['wait_max_s']:.4f}s) "
            f"hold={lk['hold_s']:.4f}s "
            f"contended={lk['contended']}/{lk['acquires']}  {lk['site']}"
        )
    lines.append("")
    lines.append("== saturation ==")
    try:
        sat = saturation_snapshot()
    except Exception as e:  # the page must render even if engine is odd
        sat = {"error": repr(e)}
    for ctl, st in sorted((sat.get("workers") or {}).items()):
        lines.append(f"  worker {ctl}: busy_ratio="
                     f"{st.get('busy_ratio', 0)} "
                     f"active={st.get('active', 0)}")
    for q, st in sorted((sat.get("queues") or {}).items()):
        lines.append(f"  queue {q}: depth={st.get('depth', 0)} "
                     f"depth_per_worker={st.get('depth_per_worker', 0)}")
    for res, age in sorted((sat.get("informers") or {}).items()):
        lines.append(f"  informer {res}: watch_backlog_s={age}")
    lines.append("")
    lines.append("filters: ?controller=<bucket>  ?fold=<substring>")
    return "\n".join(lines) + "\n"
