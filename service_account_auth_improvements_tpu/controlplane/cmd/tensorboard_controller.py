"""tensorboard-controller manager binary (reference shape:
components/tensorboard-controller/main.go)."""

from __future__ import annotations

from service_account_auth_improvements_tpu.controlplane.cmd.runner import (
    run_manager,
)
from service_account_auth_improvements_tpu.controlplane.controllers.tensorboard import (
    TensorboardReconciler,
)


def main(argv=None) -> int:
    return run_manager(
        lambda client, manager, args: TensorboardReconciler(client).register(
            manager
        ),
        argv,
    )


if __name__ == "__main__":
    raise SystemExit(main())
