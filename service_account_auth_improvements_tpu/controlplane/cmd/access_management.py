"""KFAM server binary (reference: access-management/main.go:36-58 — flags
userid-header, userid-prefix, cluster-admin; listens :8081, with the
manager-style ops sidecar — /metrics, probes, /debug/tracez — on its own
port like every other controlplane binary)."""

from __future__ import annotations

import argparse
import logging
import socketserver
import wsgiref.simple_server

from service_account_auth_improvements_tpu.controlplane.engine.serve import (
    serve_ops,
)
from service_account_auth_improvements_tpu.controlplane.kfam import KfamApp
from service_account_auth_improvements_tpu.controlplane.kube import KubeClient


class ThreadingWSGIServer(socketserver.ThreadingMixIn,
                          wsgiref.simple_server.WSGIServer):
    daemon_threads = True


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=8081)
    parser.add_argument("--metrics-port", type=int, default=8082)
    parser.add_argument("--kube-url", default=None)
    parser.add_argument("--cluster-admin", default=None)
    parser.add_argument("--userid-header", default=None)
    parser.add_argument("--userid-prefix", default=None)
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    client = KubeClient(base_url=args.kube_url)
    app = KfamApp(
        client,
        cluster_admin=args.cluster_admin,
        userid_header=args.userid_header,
        userid_prefix=args.userid_prefix,
    )
    ready = {"ok": False}
    # KFAM registers its request counter on a per-app registry (several
    # apps can share a test process) — export THAT one, not the global
    serve_ops(args.metrics_port, registry=app.registry,
              ready_check=lambda: ready["ok"])
    httpd = wsgiref.simple_server.make_server(
        "0.0.0.0", args.port, app, server_class=ThreadingWSGIServer,
    )
    ready["ok"] = True  # no informers: ready once the socket is bound
    httpd.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
