"""Shared manager-binary scaffold.

Every controller binary has the same process shape (reference:
components/*/main.go — flags, metrics/probe endpoint on one port,
reconcilers registered on a manager, signal-driven shutdown). The four
managers differ only in which reconcilers they register, so that is the
one thing a binary provides: a ``register(client, manager, args)``
callback.
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading

from service_account_auth_improvements_tpu.controlplane import obs
from service_account_auth_improvements_tpu.controlplane.engine import Manager
from service_account_auth_improvements_tpu.controlplane.engine.serve import (
    serve_ops,
)
from service_account_auth_improvements_tpu.controlplane.kube import KubeClient


def run_manager(register, argv=None, add_args=None) -> int:
    """Parse common flags, build client+manager, register reconcilers via
    ``register(client, manager, args)``, serve ops, run until signalled.
    ``add_args(parser)`` may add binary-specific flags."""
    parser = argparse.ArgumentParser()
    parser.add_argument("--metrics-port", type=int, default=8080)
    parser.add_argument("--kube-url", default=None,
                        help="API server base URL (default: in-cluster)")
    parser.add_argument("--namespace", default=None,
                        help="restrict to one namespace (default: all)")
    parser.add_argument("--workers", type=int, default=2,
                        help="reconcile workers per controller")
    parser.add_argument("--leader-elect", action="store_true",
                        help="enable Lease-based leader election "
                             "(reference main.go:68 enable-leader-election)"
                             " — ACTIVE-PASSIVE HA: one replica works, "
                             "the rest stand by")
    parser.add_argument("--leader-elect-name", default=None,
                        help="lease name (default: derived from the binary)")
    parser.add_argument("--leader-elect-namespace", default="kubeflow")
    parser.add_argument("--shard", action="store_true",
                        help="ACTIVE-ACTIVE HA (docs/ha.md): run as one "
                             "replica of a sharded plane — every replica "
                             "reconciles its own slice of the key space "
                             "(engine/shard.py). Mutually exclusive with "
                             "--leader-elect by construction: sharding IS "
                             "the multi-writer safety story")
    parser.add_argument("--shard-group", default=None,
                        help="shard group name; replicas of one "
                             "deployment share it (default: derived "
                             "from the binary)")
    parser.add_argument("--shards", type=int, default=None,
                        help="virtual shard count (default 64; must "
                             "agree across replicas of a group)")
    if add_args:
        add_args(parser)
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    # cpprof: CPPROF=1 starts the sampling profiler, CPPROF_LOCKS=1 the
    # lock-contention instrumentation — BEFORE the manager exists, so
    # its queue/informer locks are created watched (only locks created
    # after installation are instrumented). Both feed /debug/profilez.
    obs.start_profiler_from_env()
    client = KubeClient(base_url=args.kube_url)
    manager = Manager(client, namespace=args.namespace,
                      default_workers=args.workers)
    register(client, manager, args)

    # cpscope wiring: the process journal rides the global tracer's
    # exporter hook (placements, preemptions, reconcile outcomes), and
    # the process SLO engine — fed by the controllers' obs.slo_observe
    # calls (create→Ready at the Ready transition, time-to-placement at
    # the stamp) — puts its gauges on the same /metrics the kubelet
    # scrapes
    obs.JOURNAL.attach(obs.TRACER)
    from service_account_auth_improvements_tpu.controlplane.obs.slo import (  # noqa: E501
        default_engine,
    )

    slo_engine = default_engine().attach(obs.TRACER)

    if args.shard and args.leader_elect:
        # silently preferring one would leave the operator believing
        # the OTHER HA story is in force (single-writer vs sharded
        # active-active are different safety arguments)
        parser.error("--shard and --leader-elect are mutually "
                     "exclusive: sharding IS the multi-writer safety "
                     "story (docs/ha.md)")
    shard_runtime = None
    fleet_agg = None
    alert_engine = None
    if args.shard:
        import socket
        import sys
        import uuid

        from service_account_auth_improvements_tpu.controlplane.engine.shard import (  # noqa: E501
            DEFAULT_NUM_SHARDS,
            ShardRuntime,
        )
        from service_account_auth_improvements_tpu.controlplane.events import (  # noqa: E501
            EventRecorder,
        )

        group = args.shard_group or (
            "cpshard-" + (sys.argv[0].rsplit("/", 1)[-1]
                          .removesuffix(".py").replace("_", "-"))
        )
        identity = f"{socket.gethostname()}-{uuid.uuid4().hex[:6]}"
        shard_runtime = ShardRuntime(
            client, identity, group=group,
            namespace=args.leader_elect_namespace,
            num_shards=args.shards or DEFAULT_NUM_SHARDS,
            journal=obs.JOURNAL,
            # member-Lease ops-url advertisement: this is how the fleet
            # aggregator on the coordinator discovers every replica's
            # scrape endpoint — no extra registry, membership IS the
            # service discovery
            ops_url=f"http://{socket.gethostname()}:{args.metrics_port}",
        )
        manager.attach_shard(shard_runtime.member)
        # cpfleet: every replica carries an aggregator + alert engine;
        # only the coordinator-lease holder scrapes (the loop skips
        # ticks while is_coordinator is False), so /debug/fleetz and
        # /alertz answer wherever the coordinator lands after failover
        alert_engine = obs.AlertEngine(
            objectives=slo_engine.objectives,
            journal=obs.JOURNAL,
            recorder=EventRecorder(client, f"{group}-fleet"),
            namespace=args.leader_elect_namespace,
        )
        fleet_agg = obs.FleetAggregator(
            obs.lease_replicas_fn(
                client, group=group,
                namespace=args.leader_elect_namespace,
            ),
            alerts=alert_engine,
            is_coordinator=shard_runtime.is_coordinator,
            journal=obs.JOURNAL,
        )

    # readiness is LIVE informer-sync state, not a started flag: a watch
    # that loses its caches after startup (long apiserver outage) reads
    # not-ready again instead of lying to the kubelet
    ready = {"ok": False}
    serve_ops(
        args.metrics_port,
        ready_check=lambda: ready["ok"] and manager.informers_synced(),
        # /readyz?verbose: per-informer sync/failure/relist state, so a
        # false readiness names the wedged watch instead of just flipping
        ready_detail=manager.informer_status,
        # /debug/explainz/<ns>/<name> + /slostatus (obs/explain, obs/slo)
        kube=client, journal=obs.JOURNAL, slo=slo_engine,
        # /debug/profilez: the process profiler (idle unless CPPROF=1 —
        # the page then says so instead of 404ing)
        profiler=obs.PROFILER,
        # /debug/fleetz + /alertz (obs/fleet, obs/alerts; --shard only)
        fleet=fleet_agg, alerts=alert_engine,
    )

    if shard_runtime is not None:
        shard_runtime.start()
        fleet_agg.start()
        logging.getLogger(__name__).info(
            "cpshard: replica %s joined group %s "
            "(fleet aggregator armed; scrapes while coordinator)",
            identity, group)

    elector = None
    if args.leader_elect:
        import sys

        from service_account_auth_improvements_tpu.controlplane.engine.leaderelection import (  # noqa: E501
            LeaderElector,
        )

        name = args.leader_elect_name or (
            "tpukf-" + (sys.argv[0].rsplit("/", 1)[-1]
                        .removesuffix(".py").replace("_", "-"))
        )
        from service_account_auth_improvements_tpu.controlplane.events import (  # noqa: E501
            EventRecorder,
        )

        elector = LeaderElector(
            client, name, namespace=args.leader_elect_namespace,
            # leader transitions become Events on the Lease + journal
            # entries — the flight-recorder view of who held the plane
            recorder=EventRecorder(client, name),
            journal=obs.JOURNAL,
        )
        logging.getLogger(__name__).info(
            "waiting for leader lease %s/%s",
            args.leader_elect_namespace, name)
        elector.acquire()

    manager.start()
    ready["ok"] = True

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    manager.stop()
    if fleet_agg is not None:
        fleet_agg.stop()
    if shard_runtime is not None:
        # graceful leave: clears the member lease so the coordinator
        # reassigns our shards now instead of after the expiry
        shard_runtime.stop()
    if elector is not None:
        elector.release()
    return 0
