"""profile-controller manager binary (reference shape: profile-controller/
main.go:59-146 — userid-header/userid-prefix/workload-identity flags)."""

from __future__ import annotations

import argparse
import logging
import signal
import threading

from service_account_auth_improvements_tpu.controlplane.controllers.profile import (
    ProfileReconciler,
    WorkloadIdentityPlugin,
)
from service_account_auth_improvements_tpu.controlplane.engine import Manager
from service_account_auth_improvements_tpu.controlplane.engine.serve import (
    serve_ops,
)
from service_account_auth_improvements_tpu.controlplane.kube import KubeClient


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--metrics-port", type=int, default=8080)
    parser.add_argument("--kube-url", default=None)
    parser.add_argument("--namespace-labels-path", default=None)
    parser.add_argument("--workers", type=int, default=2)
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    client = KubeClient(base_url=args.kube_url)
    manager = Manager(client)
    ProfileReconciler(
        client,
        plugins={WorkloadIdentityPlugin.kind: WorkloadIdentityPlugin()},
        namespace_labels_path=args.namespace_labels_path,
    ).register(manager)

    ready = {"ok": False}
    serve_ops(args.metrics_port, ready_check=lambda: ready["ok"])
    manager.start()
    ready["ok"] = True

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    manager.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
