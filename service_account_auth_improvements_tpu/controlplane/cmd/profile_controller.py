"""profile-controller manager binary (reference shape: profile-controller/
main.go:59-146 — userid-header/userid-prefix/workload-identity flags)."""

from __future__ import annotations

from service_account_auth_improvements_tpu.controlplane.cmd.runner import (
    run_manager,
)
from service_account_auth_improvements_tpu.controlplane.controllers.profile import (
    ProfileReconciler,
    WorkloadIdentityPlugin,
)


def _add_args(parser):
    parser.add_argument("--namespace-labels-path", default=None)


def _register(client, manager, args):
    ProfileReconciler(
        client,
        plugins={WorkloadIdentityPlugin.kind: WorkloadIdentityPlugin()},
        namespace_labels_path=args.namespace_labels_path,
    ).register(manager)


def main(argv=None) -> int:
    return run_manager(_register, argv, add_args=_add_args)


if __name__ == "__main__":
    raise SystemExit(main())
