"""profile-controller manager binary (reference shape: profile-controller/
main.go:59-146 — userid-header/userid-prefix/workload-identity flags)."""

from __future__ import annotations

from service_account_auth_improvements_tpu.controlplane.cmd.runner import (
    run_manager,
)
from service_account_auth_improvements_tpu.controlplane.controllers.profile import (
    ProfileReconciler,
)
from service_account_auth_improvements_tpu.controlplane.metrics.monitoring import (
    ControllerMonitor,
)


def _add_args(parser):
    parser.add_argument("--namespace-labels-path", default=None)


def _register(client, manager, args):
    ProfileReconciler(
        client,
        # plugins default to the reconciler's full set (GCP WI + AWS
        # IRSA) — one source of truth, no binary/library drift
        namespace_labels_path=args.namespace_labels_path,
        # binary wires the monitor onto the global /metrics registry
        monitor=ControllerMonitor("profile-controller"),
    ).register(manager)


def main(argv=None) -> int:
    return run_manager(_register, argv, add_args=_add_args)


if __name__ == "__main__":
    raise SystemExit(main())
