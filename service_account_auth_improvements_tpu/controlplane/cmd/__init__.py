"""Controller binaries (``python -m ...cmd.<name>``)."""
