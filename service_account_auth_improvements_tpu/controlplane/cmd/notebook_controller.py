"""notebook-controller manager binary.

Process shape mirrors the reference manager startup (components/
notebook-controller/main.go:57-146). Culling is an opt-in side reconciler
(ENABLE_CULLING — reference main.go:110); tpusched (ENABLE_SCHEDULER,
docs/scheduler.md) runs in the same manager so placement shares the
notebook informer, with preemption behind its own ENABLE_PREEMPTION flag.
"""

from __future__ import annotations

from service_account_auth_improvements_tpu.controlplane.cmd.runner import (
    run_manager,
)
from service_account_auth_improvements_tpu.controlplane.controllers.culling import (
    CullingReconciler,
)
from service_account_auth_improvements_tpu.controlplane.controllers.notebook import (
    NotebookMetrics,
    NotebookReconciler,
)
from service_account_auth_improvements_tpu.controlplane.parking import (
    Parker,
    ParkStore,
)
from service_account_auth_improvements_tpu.controlplane.scheduler import (
    SchedulerMetrics,
    SchedulerReconciler,
)
from service_account_auth_improvements_tpu.utils.env import (
    get_env_bool,
    get_env_default,
)


def _add_args(parser):
    parser.add_argument(
        "--placement-policy", choices=("best_fit", "learned"),
        default=None,
        help="tpusched placement policy (docs/scheduler.md 'Learned "
             "placement'): best_fit (default) or learned — the trained "
             "scorer, which abstains back to best_fit on a missing "
             "checkpoint, unknown pool count, or low confidence "
             "(env PLACEMENT_POLICY)")
    parser.add_argument(
        "--policy-checkpoint", default=None,
        help="policy.npz path for --placement-policy=learned "
             "(env SCHED_POLICY_CHECKPOINT); retrains land by mtime, "
             "no restart needed")


def _register(client, manager, args):
    metrics = NotebookMetrics()
    NotebookReconciler(client, metrics).register(manager)
    if get_env_bool("ENABLE_CULLING", False):
        # checkpoint-park (docs/scheduler.md "Oversubscription &
        # parking") is wired by PARK_STORE_DIR; without it every idle
        # decision stays a plain cull and park requests are ignored —
        # tpusched's oversubscription mode requires this to be set on
        # the culling member or victims never actually release chips
        park_dir = get_env_default("PARK_STORE_DIR", "")
        parker = Parker(ParkStore(park_dir)) if park_dir else None
        CullingReconciler(client, metrics, parker=parker).register(manager)
    if get_env_bool("ENABLE_SCHEDULER", False):
        # metrics on the global REGISTRY so the ops endpoint exports the
        # queue depth / time-to-placement / preemption series
        SchedulerReconciler(
            client, SchedulerMetrics(),
            placement_policy=args.placement_policy,
            policy_checkpoint=args.policy_checkpoint,
        ).register(manager)


def main(argv=None) -> int:
    return run_manager(_register, argv, add_args=_add_args)


if __name__ == "__main__":
    raise SystemExit(main())
