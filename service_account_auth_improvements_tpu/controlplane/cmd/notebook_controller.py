"""notebook-controller manager binary.

Process shape mirrors the reference manager startup (components/
notebook-controller/main.go:57-146): flags, metrics/probe endpoint,
reconcilers registered on a manager, signal-driven shutdown. Culling is an
opt-in side reconciler (ENABLE_CULLING — reference main.go:110).
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading

from service_account_auth_improvements_tpu.controlplane.controllers.culling import (
    CullingReconciler,
)
from service_account_auth_improvements_tpu.controlplane.controllers.notebook import (
    NotebookMetrics,
    NotebookReconciler,
)
from service_account_auth_improvements_tpu.controlplane.engine import Manager
from service_account_auth_improvements_tpu.controlplane.engine.serve import (
    serve_ops,
)
from service_account_auth_improvements_tpu.controlplane.kube import KubeClient
from service_account_auth_improvements_tpu.utils.env import get_env_bool


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--metrics-port", type=int, default=8080)
    parser.add_argument("--kube-url", default=None,
                        help="API server base URL (default: in-cluster)")
    parser.add_argument("--namespace", default=None,
                        help="restrict to one namespace (default: all)")
    parser.add_argument("--workers", type=int, default=2)
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    client = KubeClient(base_url=args.kube_url)
    manager = Manager(client, namespace=args.namespace)
    metrics = NotebookMetrics()
    NotebookReconciler(client, metrics).register(manager)
    if get_env_bool("ENABLE_CULLING", False):
        CullingReconciler(client, metrics).register(manager)

    ready = {"ok": False}
    serve_ops(args.metrics_port, ready_check=lambda: ready["ok"])
    manager.start()
    ready["ok"] = True

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    manager.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
