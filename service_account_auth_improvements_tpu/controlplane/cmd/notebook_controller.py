"""notebook-controller manager binary.

Process shape mirrors the reference manager startup (components/
notebook-controller/main.go:57-146). Culling is an opt-in side reconciler
(ENABLE_CULLING — reference main.go:110); tpusched (ENABLE_SCHEDULER,
docs/scheduler.md) runs in the same manager so placement shares the
notebook informer, with preemption behind its own ENABLE_PREEMPTION flag.
"""

from __future__ import annotations

from service_account_auth_improvements_tpu.controlplane.cmd.runner import (
    run_manager,
)
from service_account_auth_improvements_tpu.controlplane.controllers.culling import (
    CullingReconciler,
)
from service_account_auth_improvements_tpu.controlplane.controllers.notebook import (
    NotebookMetrics,
    NotebookReconciler,
)
from service_account_auth_improvements_tpu.controlplane.scheduler import (
    SchedulerMetrics,
    SchedulerReconciler,
)
from service_account_auth_improvements_tpu.utils.env import get_env_bool


def _register(client, manager, args):
    metrics = NotebookMetrics()
    NotebookReconciler(client, metrics).register(manager)
    if get_env_bool("ENABLE_CULLING", False):
        CullingReconciler(client, metrics).register(manager)
    if get_env_bool("ENABLE_SCHEDULER", False):
        # metrics on the global REGISTRY so the ops endpoint exports the
        # queue depth / time-to-placement / preemption series
        SchedulerReconciler(client, SchedulerMetrics()).register(manager)


def main(argv=None) -> int:
    return run_manager(_register, argv)


if __name__ == "__main__":
    raise SystemExit(main())
