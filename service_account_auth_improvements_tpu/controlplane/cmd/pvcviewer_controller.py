"""pvcviewer-controller manager binary (reference shape:
components/pvcviewer-controller/main.go; defaulting/validation also run
in-reconcile, so the binary needs no webhook wiring to be safe)."""

from __future__ import annotations

from service_account_auth_improvements_tpu.controlplane.cmd.runner import (
    run_manager,
)
from service_account_auth_improvements_tpu.controlplane.controllers.pvcviewer import (
    PVCViewerReconciler,
)


def main(argv=None) -> int:
    return run_manager(
        lambda client, manager, args: PVCViewerReconciler(client).register(
            manager
        ),
        argv,
    )


if __name__ == "__main__":
    raise SystemExit(main())
