"""tpusched: slice-granular TPU capacity scheduler (docs/scheduler.md).

The control plane's answer to a *full cluster*: a live pool inventory
from Node watches (``inventory``), best-fit placement at Notebook
admission (``placement``), a priority admission queue with user-visible
``Scheduled=False`` parking (``queue``), and opt-in priority preemption
through the cull path (``preemption``) — wired into the Manager/informer
stack by ``reconciler``.
"""

from service_account_auth_improvements_tpu.controlplane.scheduler.inventory import (  # noqa: F401,E501
    Assignment,
    SlicePool,
    pools_from_nodes,
    used_chips,
)
from service_account_auth_improvements_tpu.controlplane.scheduler.placement import (  # noqa: F401,E501
    Demand,
    PoolIndex,
    best_fit,
    demand_from,
    feasible,
    feasible_pools,
)
from service_account_auth_improvements_tpu.controlplane.scheduler.preemption import (  # noqa: F401,E501
    choose_victim,
)
from service_account_auth_improvements_tpu.controlplane.scheduler.queue import (  # noqa: F401,E501
    AdmissionQueue,
    QueueEntry,
)
from service_account_auth_improvements_tpu.controlplane.scheduler.reconciler import (  # noqa: F401,E501
    CONDITION_SCHEDULED,
    PREEMPTED_BY_ANNOTATION,
    PRIORITY_ANNOTATION,
    QUOTA_KEY,
    SchedulerMetrics,
    SchedulerReconciler,
)
