"""Admission queue: who waits, in what order, and where they stand.

Ordering is (priority desc, admission seq asc): a strict priority queue
that degrades to plain FIFO when every notebook carries the default
priority 0 — the "per-profile FIFO" the issue asks for, since a profile's
notebooks share the profile's priority class. Positions are 1-based over
the whole queue and are what the ``Scheduled=False`` condition surfaces to
the user ("queue position 3/7").

The queue is in-memory only: entries are re-derived from unassigned
Notebook CRs on restart (level-triggered reconciles re-enqueue them), so
losing the process loses nothing but the original arrival ordering —
which creationTimestamp-ordered re-admission approximates.
"""

from __future__ import annotations

import dataclasses
import itertools
import time

from service_account_auth_improvements_tpu.controlplane.scheduler.placement import (  # noqa: E501
    Demand,
)


@dataclasses.dataclass
class QueueEntry:
    namespace: str
    name: str
    demand: Demand
    priority: int
    seq: int
    enqueued: float
    #: explicit spec.tpu.nodePool pin: placement may only use this pool
    pinned_pool: str | None = None
    #: last evaluation verdict, surfaced on the CR condition
    reason: str = "Unschedulable"
    message: str = ""

    @property
    def key(self) -> tuple[str, str]:
        return (self.namespace, self.name)


class AdmissionQueue:
    def __init__(self):
        self._entries: dict[tuple[str, str], QueueEntry] = {}
        self._seq = itertools.count()

    def add(self, namespace: str, name: str, demand: Demand,
            priority: int, pinned_pool: str | None = None) -> QueueEntry:
        """Idempotent enqueue: a queued notebook keeps its position, but a
        changed spec, priority, or pin (user edited the CR) is picked
        up."""
        key = (namespace, name)
        entry = self._entries.get(key)
        if entry is None:
            entry = QueueEntry(
                namespace=namespace, name=name, demand=demand,
                priority=priority, seq=next(self._seq),
                enqueued=time.monotonic(), pinned_pool=pinned_pool,
            )
            self._entries[key] = entry
        else:
            entry.demand = demand
            entry.priority = priority
            entry.pinned_pool = pinned_pool
        return entry

    def remove(self, key: tuple[str, str]) -> QueueEntry | None:
        return self._entries.pop(key, None)

    def get(self, key: tuple[str, str]) -> QueueEntry | None:
        return self._entries.get(key)

    def ordered(self) -> list[QueueEntry]:
        return sorted(self._entries.values(),
                      key=lambda e: (-e.priority, e.seq))

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: tuple[str, str]) -> bool:
        return key in self._entries
