"""Priority preemption: who yields when a higher-priority notebook waits.

Opt-in (ENABLE_PREEMPTION): a queued notebook may evict a strictly
lower-priority *running* (assigned) notebook whose release makes some pool
feasible for the waiter. The victim choice is conservative:

- only assignments whose single release unblocks the demand are candidates
  (no cascading multi-victim evictions — freeing two half-pools for one
  slice is a bin-packing move the ROADMAP defers);
- among candidates, the LOWEST priority yields; ties evict the YOUNGEST
  assignment (latest admitted loses first, the standard kube-scheduler
  tie-break that keeps long-running work stable).

Eviction itself is not here: the reconciler routes it through the normal
cull path (the stop annotation), so the victim's teardown — STS to zero,
gang pods deleted, chips released — is the same checkpoint-safe flow a
culled notebook takes, and a mid-eviction controller restart recovers from
the CRs alone.
"""

from __future__ import annotations

from service_account_auth_improvements_tpu.controlplane.scheduler.inventory import (  # noqa: E501
    Assignment,
    SlicePool,
)
from service_account_auth_improvements_tpu.controlplane.scheduler.placement import (  # noqa: E501
    Demand,
    feasible,
)


def choose_victim(assignments: list[Assignment],
                  pools: dict[str, SlicePool], used: dict[str, int],
                  demand: Demand, priority: int) -> Assignment | None:
    """The assignment to evict so ``demand`` (at ``priority``) can place,
    or None when no single lower-priority eviction unblocks it."""
    candidates = []
    for a in assignments:
        if a.priority >= priority:
            continue
        pool = pools.get(a.pool)
        if pool is None:
            continue
        if feasible(pool, used.get(a.pool, 0) - a.chips, demand):
            candidates.append(a)
    if not candidates:
        return None
    return min(candidates, key=lambda a: (a.priority, -a.seq))


def choose_park_victim(assignments: list[Assignment],
                       pools: dict[str, SlicePool], used: dict[str, int],
                       demand: Demand,
                       idle_age_s) -> tuple[Assignment, float] | None:
    """Oversubscription: the assignment to checkpoint-PARK so ``demand``
    can place — the COLDEST parkable tenant, not the lowest-priority one.

    Parking differs from preemption in both eligibility and ranking:

    - no priority fence — parking is lossless (state committed, resume
      on open), so even an equal- or higher-priority idler may yield.
      What it costs the victim is resume latency, which is why ranking
      is by idle age: the tenant least likely to notice pays;
    - ``idle_age_s(assignment) -> float | None`` is the parkability
      oracle (the reconciler derives it from the culler's last-activity
      annotation). None = not parkable (opted out, already
      stopping/parking, or no activity signal — never park blind);
    - same single-release rule as preemption: only an assignment whose
      lone release makes some pool feasible qualifies (no cascades).

    Returns (victim, idle_age_s) — the age is journaled as evidence —
    or None when no single park unblocks the demand.
    """
    candidates = []
    for a in assignments:
        pool = pools.get(a.pool)
        if pool is None:
            continue
        if not feasible(pool, used.get(a.pool, 0) - a.chips, demand):
            continue
        age = idle_age_s(a)
        if age is None:
            continue
        candidates.append((a, float(age)))
    if not candidates:
        return None
    # coldest first; ties park the youngest assignment (keep long-
    # running tenants stable, the preemption tie-break transplanted)
    return max(candidates, key=lambda c: (c[1], c[0].seq))
