"""Priority preemption: who yields when a higher-priority notebook waits.

Opt-in (ENABLE_PREEMPTION): a queued notebook may evict a strictly
lower-priority *running* (assigned) notebook whose release makes some pool
feasible for the waiter. The victim choice is conservative:

- only assignments whose single release unblocks the demand are candidates
  (no cascading multi-victim evictions — freeing two half-pools for one
  slice is a bin-packing move the ROADMAP defers);
- among candidates, the LOWEST priority yields; ties evict the YOUNGEST
  assignment (latest admitted loses first, the standard kube-scheduler
  tie-break that keeps long-running work stable).

Eviction itself is not here: the reconciler routes it through the normal
cull path (the stop annotation), so the victim's teardown — STS to zero,
gang pods deleted, chips released — is the same checkpoint-safe flow a
culled notebook takes, and a mid-eviction controller restart recovers from
the CRs alone.
"""

from __future__ import annotations

from service_account_auth_improvements_tpu.controlplane.scheduler.inventory import (  # noqa: E501
    Assignment,
    SlicePool,
)
from service_account_auth_improvements_tpu.controlplane.scheduler.placement import (  # noqa: E501
    Demand,
    feasible,
)


def choose_victim(assignments: list[Assignment],
                  pools: dict[str, SlicePool], used: dict[str, int],
                  demand: Demand, priority: int) -> Assignment | None:
    """The assignment to evict so ``demand`` (at ``priority``) can place,
    or None when no single lower-priority eviction unblocks it."""
    candidates = []
    for a in assignments:
        if a.priority >= priority:
            continue
        pool = pools.get(a.pool)
        if pool is None:
            continue
        if feasible(pool, used.get(a.pool, 0) - a.chips, demand):
            candidates.append(a)
    if not candidates:
        return None
    return min(candidates, key=lambda a: (a.priority, -a.seq))
