"""Placement: feasibility and best-fit choice over slice pools.

Feasibility is shape-first (generation + topology must match — GKE creates
a pool per slice shape, and a 4x4 notebook on a 2x2 pool is not a tighter
fit, it is wrong), then capacity:

- multi-host demand: the pool must carry at least ``num_hosts`` hosts and
  be COMPLETELY free — a multi-host pool is one slice, and the gang
  controller refuses pools hosting two gangs (controllers/notebook.py
  one-pool-one-slice), so the scheduler never creates that state.
- single-host demand: the pool needs enough free chips and a per-host chip
  count that fits the slice on one node.

Best-fit minimizes leftover free chips after placement (tightest pool
first) so large free pools stay whole for large slices; ties break on the
pool name for determinism.

At fleet scale the shape-first rule is also the index: :class:`PoolIndex`
buckets pools by slice class once per inventory snapshot, so a sweep
touches only the pools whose shape can match instead of every pool in
the fleet — O(pools-of-this-shape) instead of O(pools), same result by
construction (the bucket predicate IS ``feasible``'s first clause). The
storm bench (cpbench/storm.py) A/Bs the index against the full sweep;
``feasible`` remains the one feasibility definition either way.
"""

from __future__ import annotations

import dataclasses

from service_account_auth_improvements_tpu.controlplane.scheduler.inventory import (  # noqa: E501
    SlicePool,
)


@dataclasses.dataclass(frozen=True)
class Demand:
    """What one Notebook asks of a pool (from its resolved TpuSpec)."""

    generation: str
    topology: str
    total_chips: int
    num_hosts: int

    @property
    def slice_class(self) -> str:
        return f"{self.generation}:{self.topology}"


def demand_from(resolved) -> Demand:
    return Demand(
        generation=resolved.generation, topology=resolved.topology,
        total_chips=resolved.total_chips, num_hosts=resolved.num_hosts,
    )


def feasible(pool: SlicePool, used: int, demand: Demand) -> bool:
    if (pool.generation, pool.topology) != (demand.generation,
                                            demand.topology):
        return False
    if demand.num_hosts > 1:
        return pool.num_hosts >= demand.num_hosts and used == 0
    return (pool.total_chips - used >= demand.total_chips
            and pool.chips_per_host >= demand.total_chips)


class PoolIndex:
    """Pools bucketed by slice class, built once per inventory
    snapshot. The bucket key duplicates NOTHING: it is exactly the
    shape clause of :func:`feasible`, so sweeping a bucket and sweeping
    the whole dict return the same set (capacity is still checked pool
    by pool). Build it where the snapshot is built — once per
    scheduling pass, not per queue entry — and pass it to
    :func:`feasible_pools`/:func:`best_fit`."""

    def __init__(self, pools: dict[str, SlicePool]):
        by_class: dict[str, list[tuple[str, SlicePool]]] = {}
        for name, pool in pools.items():
            key = f"{pool.generation}:{pool.topology}"
            by_class.setdefault(key, []).append((name, pool))
        self._by_class = by_class

    def candidates(self, demand: Demand):
        """(name, pool) pairs whose shape can match ``demand``."""
        return self._by_class.get(demand.slice_class, ())


def feasible_pools(pools: dict[str, SlicePool], used: dict[str, int],
                   demand: Demand,
                   index: PoolIndex | None = None) -> list[str]:
    """Names of every pool that could host ``demand`` right now, sorted
    for determinism. This is THE feasibility definition: ``best_fit``
    chooses among these, and the learned policy's infeasibility mask is
    built from exactly this list — a second, diverging definition here
    would be a double-booking factory (a policy scoring a pool best-fit
    would refuse is a policy stamping annotations the inventory can't
    honor). ``index`` narrows the sweep to shape-matched candidates;
    every candidate still goes through :func:`feasible`, so the index
    can only skip pools the shape clause would reject anyway."""
    cands = pools.items() if index is None else index.candidates(demand)
    return sorted(
        name for name, pool in cands
        if feasible(pool, used.get(name, 0), demand)
    )


def best_fit(pools: dict[str, SlicePool], used: dict[str, int],
             demand: Demand,
             index: PoolIndex | None = None) -> str | None:
    """Name of the feasible pool with the least leftover capacity after
    placement, or None when nothing fits."""
    best: tuple[int, str] | None = None
    cands = pools.items() if index is None else index.candidates(demand)
    for name, pool in cands:
        pool_used = used.get(name, 0)
        if not feasible(pool, pool_used, demand):
            continue
        leftover = pool.total_chips - pool_used - demand.total_chips
        if best is None or (leftover, name) < best:
            best = (leftover, name)
    return best[1] if best else None
